"""Static KV-cache decode engine: the serving hot path.

Replaces the growing-concat ``MultiHeadAttention.Cache`` decode (a new
shape — and under jit a new compiled program — every token) with a
preallocated device-resident cache updated in place at traced position
indices. A small fixed family of compiled programs serves an entire
request stream:

- **prefill** — bucketed (one compile per prompt-length bucket, the PR-6
  path) or **chunked** (``prefill_chunk=C``): the prompt runs as a sequence
  of fixed-``C``-token dispatches directly against the big cache, so the
  whole per-bucket compile family collapses into ONE chunk program plus one
  final-chunk program (sampling fused), and a long admission can interleave
  with decode instead of stalling it;
- **decode step** — advances every occupied slot one token with per-slot
  position indices; ``fuse=D`` runs D decode iterations inside ONE donated
  ``lax.scan`` dispatch (the ``TrainStep.run_steps`` idiom via
  ``jit.scan_steps``), with the eos/max-token stop flags carried in the
  scan state so finished slots self-deactivate without a host round-trip —
  one dispatch and one host sync per D tokens;
- **prefix reuse** — ``prefix_cache_mb=M`` keeps an LRU cache of
  chunk-aligned prompt-prefix KV segments (:mod:`.prefix_cache`); a request
  whose prefix matches copies the cached chunks into its slot with one
  compiled ``dynamic_update_slice`` program per chunk — no prefill compute
  or compile for the shared portion.

Both cache buffers (and the slot state) are donated — the XLA executable
updates them in place, so cache memory stays flat for the life of the
engine. Compiles run through the observability AOT ``lower().compile()``
path, so ``explain()`` answers cost/memory questions, the
``infer.compiles`` counter pins the program-family size in tests, and — with
``FLAGS_compile_cache_dir`` set — every executable is serialized to disk
(:mod:`.aot_cache`) so a RESTARTED engine skips the compile family
entirely.

Parity: the reference serves GPT decode through
``fused_multi_transformer_op.cu`` driven by AnalysisPredictor; here the
fused decoder is the compiled step program and the "predictor" is the
:class:`~paddle_tpu.inference.scheduler.ContinuousBatchingScheduler` on top.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..analysis import sanitizer as _sanitizer

__all__ = ["DecodeEngine", "default_buckets"]


def default_buckets(max_seq: int, start: int = 16) -> Tuple[int, ...]:
    """Power-of-two prompt-padding buckets up to ``max_seq``: prompts pad to
    the smallest bucket that fits, so prefill compiles once per bucket
    instead of once per prompt length."""
    out: List[int] = []
    b = start
    while b < max_seq:
        out.append(b)
        b *= 2
    out.append(max_seq)
    return tuple(sorted(set(out)))


def _dequant(entry, dt):
    """A params-pack entry is either a plain array or an int8 payload
    ``{"q", "s"}``; dequantize the latter to ``dt`` (XLA folds the multiply
    into the consuming matmul — the QuantizedLinear idiom on raw stacked
    weights)."""
    if isinstance(entry, dict):
        return (entry["q"].astype(jnp.float32) * entry["s"]).astype(dt)
    return entry


class _PrefillJob:
    """Host-side progress of one in-flight prompt admission: which slot it
    owns, how far the cache is written (``next_pos``), how many tokens the
    prefix cache supplied, and — once the final chunk ran — the sampled
    first token."""

    __slots__ = ("slot", "prompt", "n", "eos", "limit", "seed",
                 "next_pos", "reused_tokens", "done", "first", "more")

    def __init__(self, slot, prompt, n, eos, limit, seed):
        self.slot = slot
        self.prompt = prompt
        self.n = n
        self.eos = eos
        self.limit = limit
        self.seed = seed
        self.next_pos = 0          # cache rows [0, next_pos) are written
        self.reused_tokens = 0     # rows supplied by the prefix cache
        self.done = False
        self.first: Optional[int] = None
        self.more: Optional[bool] = None

    def chunks_left(self, chunk: Optional[int]) -> int:
        """Model dispatches still needed to finish this prefill."""
        if self.done:
            return 0
        if chunk is None:
            return 1
        return max(1, -(-(self.n - self.next_pos) // chunk))


class DecodeEngine:
    """Slot-based autoregressive decode over a static KV cache.

    ``model`` is a :class:`~paddle_tpu.models.gpt.GPTForPretraining` with the
    stacked trunk. ``max_batch_slots`` fixes the decode batch width B: each
    slot holds one in-flight request, and requests are admitted into free
    slots mid-stream (continuous batching) — admission never recompiles.

    ``int8=True`` quantizes the trunk matmul weights (qkv/out/ffn1/ffn2)
    to int8 with per-layer × per-output-channel abs_max scales through
    :mod:`paddle_tpu.quantization`; the compiled programs carry int8
    constants and dequantize into the matmuls.

    Serving-throughput knobs (each defaults to the PR-6 behaviour):

    - ``fuse=D`` — default decode fusion depth: :meth:`decode_step` runs D
      iterations per dispatch (helps whenever per-dispatch host overhead is
      visible, i.e. small models / fast devices; a slot that finishes
      mid-scan idles until the dispatch drains, so very large D wastes
      compute on short completions);
    - ``prefill_chunk=C`` — chunked prefill: prompts prefill in fixed
      C-token dispatches against the big cache (compile family becomes 2
      programs for ALL prompt lengths; long prompts interleave with decode);
    - ``prefix_cache_mb=M`` — prefix KV reuse over chunk-aligned prompt
      prefixes (requires ``prefill_chunk``), LRU-evicted under an M-MiB
      device-byte budget;
    - ``draft=<GPTConfig | dict | model>`` — speculative decoding: a small
      draft model proposes ``spec_k`` tokens per step and ONE wide target
      forward verifies them (accept-longest-prefix + bonus token in-graph),
      so one dispatch emits up to ``spec_k+1`` tokens. Greedy accepted
      tokens are bitwise-identical to the non-speculative path; a
      config/dict draft is built from ``draft_seed`` so every replica holds
      the same weights. Requires ``fuse=1``;
    - ``kv_dtype="int8"`` — the K/V cache stores int8 payloads with
      per-head per-row abs_max f32 scale planes (~``4*dh/(dh+4)``x smaller
      than f32); dequant folds into the attention matmuls and prefix-cache
      segments stay quantized end-to-end. Decode tokens can differ from the
      f32 cache within quantization tolerance (the engine family itself
      stays bitwise-reproducible run to run).

    Sampling config (``do_sample``/``temperature``/``top_k``/``top_p``) is
    compiled into the programs; per-request randomness comes from each
    request's own ``seed`` folded with its absolute position, so a request's
    tokens never depend on which slot it runs in or on its batch neighbours.
    """

    def __init__(self, model, max_batch_slots: int = 4, max_seq_len: Optional[int] = None,
                 prefill_buckets: Optional[Sequence[int]] = None, do_sample: bool = False,
                 temperature: float = 1.0, top_k: int = 0, top_p: float = 1.0,
                 int8: bool = False, donate: bool = True, fuse: int = 1,
                 prefill_chunk: Optional[int] = None, prefix_cache_mb: float = 0.0,
                 draft=None, spec_k: int = 4, draft_seed: int = 0,
                 kv_dtype: Optional[str] = None):
        from ..models.gpt import GPTBlockStack, GPTConfig, _kv_zeros

        if not isinstance(model.gpt.layers, GPTBlockStack):
            raise NotImplementedError("DecodeEngine requires the stacked trunk (GPTConfig(stacked=True))")
        cfg = model.gpt.cfg
        S = int(max_seq_len) if max_seq_len is not None else int(cfg.max_seq_len)
        if S > cfg.max_seq_len:
            raise ValueError(f"max_seq_len {S} exceeds the model's positional table {cfg.max_seq_len}")
        self.cfg = cfg
        self.max_seq_len = S
        self.max_batch_slots = B = int(max_batch_slots)
        self.buckets = tuple(sorted(int(b) for b in prefill_buckets)) if prefill_buckets else default_buckets(S)
        if any(b > S for b in self.buckets):
            raise ValueError(f"prefill bucket larger than max_seq_len {S}: {self.buckets}")
        self._sample = (bool(do_sample), float(temperature), int(top_k), float(top_p))
        self.int8 = bool(int8)
        self._donate = bool(donate)
        self.fuse = int(fuse)
        if self.fuse < 1:
            raise ValueError(f"fuse depth must be >= 1, got {fuse}")
        self._chunk = int(prefill_chunk) if prefill_chunk else None
        if self._chunk is not None and not (1 <= self._chunk <= S):
            raise ValueError(f"prefill_chunk {prefill_chunk} must be in [1, max_seq_len={S}]")
        self._kv_dtype = None if kv_dtype is None else str(kv_dtype)
        if self._kv_dtype not in (None, "int8"):
            raise ValueError(f"kv_dtype must be None or 'int8', got {kv_dtype!r}")

        # --- draft model for speculative decoding ------------------------
        draft_model = None
        if draft is not None:
            if int(spec_k) < 1:
                raise ValueError(f"spec_k must be >= 1, got {spec_k}")
            if self.fuse != 1:
                raise ValueError("draft= requires fuse=1 (a speculative dispatch "
                                 "already emits up to spec_k+1 tokens)")
            if isinstance(draft, dict):
                draft = GPTConfig(**draft)
            if isinstance(draft, GPTConfig):
                # build the draft's random init under a pinned RNG stream so
                # every engine (and every fleet replica) with the same
                # draft_seed holds bitwise-identical draft weights — fleet
                # requeue after a replica kill must re-accept the same runs
                from ..framework import random as _fwrng
                from ..models.gpt import GPTForPretraining

                state = _fwrng.get_rng_state()
                _fwrng.seed(int(draft_seed))
                try:
                    draft_model = GPTForPretraining(draft)
                finally:
                    _fwrng.set_rng_state(state)
            else:
                draft_model = draft
            if not isinstance(draft_model.gpt.layers, GPTBlockStack):
                raise NotImplementedError("draft model requires the stacked trunk")
            dcfg = draft_model.gpt.cfg
            if dcfg.vocab_size != cfg.vocab_size:
                raise ValueError(f"draft vocab {dcfg.vocab_size} != target vocab {cfg.vocab_size}")
            if dcfg.max_seq_len < S:
                raise ValueError(f"draft positional table {dcfg.max_seq_len} < max_seq_len {S}")
            self.draft_cfg = dcfg  # noqa: PTA104 (host-side serving state)
        else:
            self.draft_cfg = None  # noqa: PTA104 (host-side serving state)
        self.spec_k = int(spec_k) if draft is not None else 0
        self.draft_seed = int(draft_seed)

        def pack_stack(order, params):
            # per-layer × per-output-channel abs_max scales on the
            # [L, in, out]-stacked trunk weight (channel_wise_abs_max
            # over the stack) — int8 constants land in the compiled
            # programs, dequant folds into the matmul
            from .. import quantization as Q

            quant = {"qkv_w", "out_w", "ffn1_w", "ffn2_w"}
            packed = []
            for name, w in zip(order, params):
                if name in quant:
                    q, s = Q.quant_abs_max(np.asarray(w), channel_axis=(0, 2))
                    packed.append({"q": jnp.asarray(q), "s": jnp.asarray(s)})
                else:
                    packed.append(w)
            return tuple(packed)

        stacked, wte, wpe, fnw, fnb = model._decode_params()
        params, self._idx = stacked
        self._stack_dts = tuple(w.dtype for w in params)  # dequant targets
        if int8:
            params = pack_stack(model.gpt.layers._order, params)
        self._params = {"stack": params, "wte": wte, "wpe": wpe, "fnw": fnw, "fnb": fnb}

        self._dparams = None
        if draft_model is not None:
            dstacked, dwte, dwpe, dfnw, dfnb = draft_model._decode_params()
            dparams, self._didx = dstacked  # noqa: PTA104 (host-side serving state)
            self._draft_dts = tuple(w.dtype for w in dparams)  # noqa: PTA104 (host-side serving state)
            if int8:
                dparams = pack_stack(draft_model.gpt.layers._order, dparams)
            self._dparams = {"stack": dparams, "wte": dwte, "wpe": dwpe,  # noqa: PTA104 (host-side serving state)
                             "fnw": dfnw, "fnb": dfnb}

        L = cfg.num_layers
        H = cfg.num_heads
        dh = cfg.hidden_size // cfg.num_heads
        dt = wte.dtype
        # the cache carries spec_k slack rows past max_seq_len so the
        # (spec_k+1)-wide speculative window write near the sequence limit
        # never clamps back over committed rows; slack rows are never
        # attendable by an emitted token (q_pos < max_seq_len always)
        cache_S = S + self.spec_k
        self._shape = (L, B, H, cache_S, dh)
        self._ck = _kv_zeros((L, B, H, cache_S, dh), dt, self._kv_dtype)
        self._cv = _kv_zeros((L, B, H, cache_S, dh), dt, self._kv_dtype)
        if draft_model is not None:
            dcfg = self.draft_cfg
            dL, dH = dcfg.num_layers, dcfg.num_heads
            ddh = dcfg.hidden_size // dcfg.num_heads
            # the draft cache is small — keep it in the compute dtype
            self._dck = jnp.zeros((dL, B, dH, cache_S, ddh), dwte.dtype)  # noqa: PTA104 (host-side serving state)
            self._dcv = jnp.zeros((dL, B, dH, cache_S, ddh), dwte.dtype)  # noqa: PTA104 (host-side serving state)
        else:
            self._dck = self._dcv = None  # noqa: PTA104 (host-side serving state)
        self._pos = jnp.zeros((B,), jnp.int32)
        self._tok = jnp.zeros((B,), jnp.int32)
        self._active = jnp.zeros((B,), bool)
        # host mirrors / per-slot request metadata (tiny, resent per dispatch)
        self._active_np = np.zeros((B,), bool)
        self._occupied = np.zeros((B,), bool)
        self._eos = np.full((B,), -1, np.int32)
        self._limit = np.zeros((B,), np.int32)
        self._seed = np.zeros((B,), np.int32)
        self._spec_drafted = 0
        self._spec_accepted = 0

        self.prefix_cache = None
        if prefix_cache_mb and float(prefix_cache_mb) > 0:
            if self._chunk is None:
                raise ValueError("prefix_cache_mb requires prefill_chunk= (prefix "
                                 "entries are chunk-aligned KV segments)")
            from .prefix_cache import PrefixCache

            if self._kv_dtype == "int8":
                # int8 payload + one f32 scale per (layer, head, row)
                entry_bytes = 2 * L * H * self._chunk * (dh + 4)
            else:
                entry_bytes = 2 * L * H * self._chunk * dh * jnp.dtype(dt).itemsize
            self.prefix_cache = PrefixCache(self._chunk,
                                            int(float(prefix_cache_mb) * (1 << 20)),
                                            entry_bytes)

        # host scalars baked into the traced programs — part of the disk
        # cache key so a restarted engine only reuses executables compiled
        # for the exact same specialization (kv dtype and the draft config
        # change every traced program, so both fold in)
        dfp = None
        if self.draft_cfg is not None:
            dcfg = self.draft_cfg
            dfp = (dcfg.vocab_size, dcfg.hidden_size, dcfg.num_layers,
                   dcfg.num_heads, dcfg.ffn_hidden_size, dcfg.max_seq_len,
                   self.spec_k)
        self._fingerprint = repr((
            (cfg.vocab_size, cfg.hidden_size, cfg.num_layers, cfg.num_heads,
             cfg.ffn_hidden_size, cfg.max_seq_len),
            self._sample, self.int8, self._donate, S, B, self._chunk,
            tuple(str(d) for d in self._stack_dts), str(dt),
            self._kv_dtype, dfp))

        self._build()
        self._fused_jits: Dict[int, Any] = {}
        self._compiled: Dict[tuple, Any] = {}
        self._specializations: List[dict] = []
        from ..observability.metrics import gauge_set
        gauge_set("infer.kv_bytes_per_slot", self.kv_bytes_per_slot())

    # ------------------------------------------------------------ programs
    def _build(self):
        from ..models.gpt import (
            _cache_forward,
            _chunk_prefill_forward,
            _filtered_logits,
            _kv_zeros,
            _kvc_copy,
            _kvc_slice,
            _select_token,
            _select_token_rows,
            _slot_decode_forward,
            _slot_window_forward,
        )

        cfg = self.cfg
        num_heads = cfg.num_heads
        L = cfg.num_layers
        H = num_heads
        dh = cfg.hidden_size // num_heads
        do_sample, temperature, top_k, top_p = self._sample
        idx = self._idx
        kvdt = self._kv_dtype
        spec_k = self.spec_k
        has_draft = self._dparams is not None
        if has_draft:
            dcfg = self.draft_cfg
            draft_heads = dcfg.num_heads
            dL, dH = dcfg.num_layers, dcfg.num_heads
            ddh = dcfg.hidden_size // dcfg.num_heads
            didx = self._didx
            ddts = self._draft_dts

        dts = self._stack_dts

        def unpack(p):
            return ((tuple(_dequant(e, dt) for e, dt in zip(p["stack"], dts)), idx),
                    p["wte"], p["wpe"], p["fnw"], p["fnb"])

        def unpack_draft(dp):
            return ((tuple(_dequant(e, dt) for e, dt in zip(dp["stack"], ddts)), didx),
                    dp["wte"], dp["wpe"], dp["fnw"], dp["fnb"])

        def admit_state(pos, tok, active, first, length, slot, eos, limit):
            """Shared tail of every first-token program: the in-graph
            eos/limit check and the per-slot state writes."""
            done = (eos >= 0) & (first == eos)
            more = (~done) & (length + 1 < limit)
            dus = jax.lax.dynamic_update_slice
            pos = dus(pos, length[None], (slot,))
            tok = dus(tok, first[None], (slot,))
            active = dus(active, more[None], (slot,))
            return pos, tok, active, more

        def prefill_core(p, ck, cv, pos, tok, active, ids, length, slot, eos, limit, seed):
            stacked, wte, wpe, fnw, fnb = unpack(p)
            P = ids.shape[1]
            # the bucketed scratch carries the SAME representation as the big
            # cache (int8 pack under kv_dtype), so bucketed prefill attends
            # exactly the rows a chunked prefill would — the bitwise basis
            # of the bucketed-vs-chunked parity pin survives quantization
            sk = _kv_zeros((L, 1, H, P, dh), wte.dtype, kvdt)
            sv = _kv_zeros((L, 1, H, P, dh), wte.dtype, kvdt)
            logits, sk, sv = _cache_forward(stacked, wte, wpe, fnw, fnb, ids, sk, sv,
                                            jnp.int32(0), num_heads=num_heads)
            ck = _kvc_copy(ck, sk, (0, slot, 0, 0, 0))
            cv = _kvc_copy(cv, sv, (0, slot, 0, 0, 0))
            last = jax.lax.dynamic_slice(logits, (0, length - 1, 0), (1, 1, logits.shape[2]))[:, 0]
            key = jax.random.fold_in(jax.random.key(seed), length - 1)
            first = _select_token(last.astype(jnp.float32), key, do_sample, temperature, top_k, top_p)[0]
            pos, tok, active, more = admit_state(pos, tok, active, first, length, slot, eos, limit)
            return ck, cv, pos, tok, active, first, more

        def draft_prefill(dp, dck, dcv, ids, slot):
            # draft prefill rides the SAME dispatch as the target prefill
            # (one donated program, two trunks; XLA dead-code-eliminates the
            # draft logits) so admission cost stays one dispatch per bucket
            dstacked, dwte, dwpe, dfnw, dfnb = unpack_draft(dp)
            P = ids.shape[1]
            dsk = jnp.zeros((dL, 1, dH, P, ddh), dwte.dtype)
            dsv = jnp.zeros((dL, 1, dH, P, ddh), dwte.dtype)
            _, dsk, dsv = _cache_forward(dstacked, dwte, dwpe, dfnw, dfnb, ids, dsk, dsv,
                                         jnp.int32(0), num_heads=draft_heads)
            dck = jax.lax.dynamic_update_slice(dck, dsk, (0, slot, 0, 0, 0))
            dcv = jax.lax.dynamic_update_slice(dcv, dsv, (0, slot, 0, 0, 0))
            return dck, dcv

        def draft_chunk(dp, dck, dcv, ids, slot, start):
            dstacked, dwte, dwpe, dfnw, dfnb = unpack_draft(dp)
            _, dck, dcv = _chunk_prefill_forward(dstacked, dwte, dwpe, dfnw, dfnb, ids,
                                                 dck, dcv, slot, start,
                                                 num_heads=draft_heads)
            return dck, dcv

        if has_draft:
            def prefill_fn(p, dp, ck, cv, dck, dcv, pos, tok, active, ids, length,
                           slot, eos, limit, seed):
                ck, cv, pos, tok, active, first, more = prefill_core(
                    p, ck, cv, pos, tok, active, ids, length, slot, eos, limit, seed)
                dck, dcv = draft_prefill(dp, dck, dcv, ids, slot)
                return ck, cv, dck, dcv, pos, tok, active, first, more
        else:
            prefill_fn = prefill_core

        def chunk_core(p, ck, cv, ids, slot, start):
            stacked, wte, wpe, fnw, fnb = unpack(p)
            _, ck, cv = _chunk_prefill_forward(stacked, wte, wpe, fnw, fnb, ids, ck, cv,
                                               slot, start, num_heads=num_heads)
            return ck, cv

        if has_draft:
            def chunk_fn(p, dp, ck, cv, dck, dcv, ids, slot, start):
                ck, cv = chunk_core(p, ck, cv, ids, slot, start)
                dck, dcv = draft_chunk(dp, dck, dcv, ids, slot, start)
                return ck, cv, dck, dcv
        else:
            chunk_fn = chunk_core

        def chunk_final_core(p, ck, cv, pos, tok, active, ids, slot, start, last_row,
                             length, eos, limit, seed):
            stacked, wte, wpe, fnw, fnb = unpack(p)
            logits, ck, cv = _chunk_prefill_forward(stacked, wte, wpe, fnw, fnb, ids, ck, cv,
                                                    slot, start, num_heads=num_heads,
                                                    last_row=last_row)
            key = jax.random.fold_in(jax.random.key(seed), length - 1)
            first = _select_token(logits.astype(jnp.float32), key, do_sample, temperature, top_k, top_p)[0]
            pos, tok, active, more = admit_state(pos, tok, active, first, length, slot, eos, limit)
            return ck, cv, pos, tok, active, first, more

        if has_draft:
            def chunk_final_fn(p, dp, ck, cv, dck, dcv, pos, tok, active, ids, slot,
                               start, last_row, length, eos, limit, seed):
                ck, cv, pos, tok, active, first, more = chunk_final_core(
                    p, ck, cv, pos, tok, active, ids, slot, start, last_row,
                    length, eos, limit, seed)
                dck, dcv = draft_chunk(dp, dck, dcv, ids, slot, start)
                return ck, cv, dck, dcv, pos, tok, active, first, more
        else:
            chunk_final_fn = chunk_final_core

        def insert_fn(ck, cv, seg_k, seg_v, slot, start):
            # prefix-cache hit: copy a cached chunk's KV into the slot's
            # lanes — the whole "prefill" of the shared portion is this one
            # dynamic_update_slice program. Under kv_dtype the segment is the
            # stored int8 pack and both planes copy verbatim: a cache hit
            # never round-trips through f32 in HBM.
            ck = _kvc_copy(ck, seg_k, (0, slot, 0, start, 0))
            cv = _kvc_copy(cv, seg_v, (0, slot, 0, start, 0))
            return ck, cv

        chunk = self._chunk

        def extract_fn(ck, cv, slot, start):
            size = (L, 1, H, chunk if chunk else 1, dh)
            seg_k = _kvc_slice(ck, (0, slot, 0, start, 0), size)
            seg_v = _kvc_slice(cv, (0, slot, 0, start, 0), size)
            return seg_k, seg_v

        def spec_fn(p, dp, ck, cv, dck, dcv, pos, tok, active, eos_v, limit_v, seed_v):
            """ONE speculative dispatch: spec_k+1 chained draft forwards on
            the draft cache propose a window, ONE (spec_k+1)-wide target
            forward verifies it, and the accept-longest-prefix + bonus-token
            ledger runs in-graph. Rejected-tail KV is left stale past the
            rolled-back position — harmless under write-before-attend (the
            next window overwrites those rows before any emitted row can
            attend them)."""
            stacked, wte, wpe, fnw, fnb = unpack(p)
            dstacked, dwte, dwpe, dfnw, dfnb = unpack_draft(dp)
            K = spec_k
            # --- draft scan: iteration i consumes the token at pos+i and
            # writes its draft KV there; iterations 0..K-1 yield proposals
            # d_1..d_K, iteration K only backfills the last proposal's KV so
            # the all-accepted case leaves no draft-cache hole
            props, dfilt = [], []
            dtok = tok
            for i in range(K + 1):  # noqa: PTA104 (static unroll, host loop bound)
                dpos = pos + jnp.int32(i)
                dlogits, dck, dcv = _slot_decode_forward(
                    dstacked, dwte, dwpe, dfnw, dfnb, dtok, dck, dcv, dpos,
                    num_heads=draft_heads, active=active)
                if i < K:
                    if do_sample:
                        fl = _filtered_logits(dlogits.astype(jnp.float32),
                                              temperature, top_k, top_p)
                        dkeys = jax.vmap(lambda s, q: jax.random.fold_in(
                            jax.random.fold_in(jax.random.key(s), q), 3))(seed_v, dpos)
                        nd = jax.vmap(jax.random.categorical)(dkeys, fl).astype(jnp.int32)
                        dfilt.append(fl)  # noqa: PTA104 (host-side serving state)
                    else:
                        nd = jnp.argmax(dlogits.astype(jnp.float32), axis=-1).astype(jnp.int32)
                    nd = jnp.where(active, nd, dtok)  # free slots hold
                    props.append(nd)  # noqa: PTA104 (host-side serving state)
                    dtok = nd
            # --- target verification: one (K+1)-wide window forward over
            # [tok, d_1..d_K] at per-slot positions pos..pos+K
            ids = jnp.stack([tok] + props, axis=1)
            vlogits, ck, cv = _slot_window_forward(
                stacked, wte, wpe, fnw, fnb, ids, ck, cv, pos,
                num_heads=num_heads, active=active)
            # --- per-row outcome: row j scores the token at position
            # pos+j+1. Greedy: argmax + equality accept (bitwise = sequential
            # decode, since per-row width-W math equals the s=1 math).
            # Sampled: residual resampling over the SAME filtered
            # distribution _select_token samples from.
            outs, accs = [], []
            for j in range(K + 1):  # noqa: PTA104 (static unroll, host loop bound)
                lg = vlogits[:, j].astype(jnp.float32)
                if not do_sample:
                    sel = jnp.argmax(lg, axis=-1).astype(jnp.int32)
                    outs.append(sel)  # noqa: PTA104 (host-side serving state)
                    if j < K:
                        accs.append(sel == props[j])  # noqa: PTA104 (host-side serving state)
                    continue
                flp = _filtered_logits(lg, temperature, top_k, top_p)
                kj = jax.vmap(lambda s, q: jax.random.fold_in(jax.random.key(s), q))(
                    seed_v, pos + jnp.int32(j))
                if j < K:
                    P_ = jax.nn.softmax(flp, axis=-1)
                    Q_ = jax.nn.softmax(dfilt[j], axis=-1)
                    d = props[j]
                    pd = jnp.take_along_axis(P_, d[:, None], axis=-1)[:, 0]
                    qd = jnp.take_along_axis(Q_, d[:, None], axis=-1)[:, 0]
                    u = jax.vmap(lambda k: jax.random.uniform(jax.random.fold_in(k, 1)))(kj)
                    acc = u * qd <= pd
                    res = jnp.maximum(P_ - Q_, 0.0)
                    has = jnp.sum(res, axis=-1, keepdims=True) > 0
                    rlog = jnp.where(res > 0, jnp.log(jnp.where(res > 0, res, 1.0)), -jnp.inf)
                    rlog = jnp.where(has, rlog, flp)  # P==Q residual: fall back to target
                    corr = jax.vmap(lambda k, lg2: jax.random.categorical(
                        jax.random.fold_in(k, 2), lg2))(kj, rlog).astype(jnp.int32)
                    outs.append(jnp.where(acc, d, corr))  # noqa: PTA104 (host-side serving state)
                    accs.append(acc)  # noqa: PTA104 (host-side serving state)
                else:
                    # bonus row: a direct draw from the target distribution
                    # with the position's own key — the all-accepted case
                    # samples exactly what sequential decode would
                    bonus = jax.vmap(jax.random.categorical)(kj, flp).astype(jnp.int32)
                    outs.append(bonus)  # noqa: PTA104 (host-side serving state)
            # --- emission ledger: accept-longest-prefix, eos/limit stops
            # mid-window, rejected tail rolls the slot position back simply
            # by not advancing it
            win = jnp.ones_like(active)
            act_s, pos_s, tok_s = active, pos, tok
            toks_rows, emit_rows = [], []
            for j in range(K + 1):  # noqa: PTA104 (static unroll, host loop bound)
                emit = act_s & win
                row = jnp.where(emit, outs[j], tok_s)
                tok_s = row
                pos_s = pos_s + emit.astype(jnp.int32)
                hit_eos = (eos_v >= 0) & (row == eos_v)
                live = ~hit_eos & (pos_s + 1 < limit_v)
                act_s = jnp.where(emit, act_s & live, act_s)
                toks_rows.append(row)  # noqa: PTA104 (host-side serving state)
                emit_rows.append(emit)  # noqa: PTA104 (host-side serving state)
                if j < K:
                    win = win & accs[j]
            return (ck, cv, dck, dcv, pos_s, tok_s, act_s,
                    jnp.stack(toks_rows), jnp.stack(emit_rows))

        def decode_body(consts, carry, _x):
            # ONE decode iteration — the scan body of the fused program and
            # (at D=1) the whole single-step program, so every fuse depth is
            # bitwise the same math
            p, eos_v, limit_v, seed_v = consts
            ck, cv, pos, tok, active = carry
            stacked, wte, wpe, fnw, fnb = unpack(p)
            logits, ck, cv = _slot_decode_forward(stacked, wte, wpe, fnw, fnb, tok, ck, cv,
                                                  pos, num_heads=num_heads, active=active)
            keys = jax.vmap(lambda s, q: jax.random.fold_in(jax.random.key(s), q))(seed_v, pos)
            nxt = _select_token_rows(logits.astype(jnp.float32), keys, do_sample,
                                     temperature, top_k, top_p)
            nxt = jnp.where(active, nxt, tok)  # slot-masked: free slots hold
            hit_eos = (eos_v >= 0) & (nxt == eos_v)
            new_pos = pos + active.astype(jnp.int32)
            new_active = active & ~hit_eos & (new_pos + 1 < limit_v)
            # ys: the step's token per slot + which slots really emitted
            return (ck, cv, new_pos, nxt, new_active), (nxt, active)

        self._decode_body = decode_body

        def decode_fn(p, ck, cv, pos, tok, active, eos_v, limit_v, seed_v):
            carry, _ys = decode_body((p, eos_v, limit_v, seed_v),
                                     (ck, cv, pos, tok, active), None)
            return carry

        if has_draft:
            # state args shift by one (draft params at arg 1) and both cache
            # pairs donate; the draft weights thread through like the target's
            donate = (2, 3, 4, 5, 6, 7, 8) if self._donate else ()
            donate_cache = (2, 3, 4, 5) if self._donate else ()
            self._spec_jit = jax.jit(spec_fn, donate_argnums=donate)  # noqa: PTA104 (host-side serving state)
            self._draft_chunk_jit = jax.jit(  # noqa: PTA104 (host-side serving state)
                draft_chunk, donate_argnums=(1, 2) if self._donate else ())
        else:
            donate = (1, 2, 3, 4, 5) if self._donate else ()
            donate_cache = (1, 2) if self._donate else ()
            self._spec_jit = self._draft_chunk_jit = None  # noqa: PTA104 (host-side serving state)
        self._prefill_jit = jax.jit(prefill_fn, donate_argnums=donate)
        self._decode_jit = jax.jit(decode_fn, donate_argnums=(1, 2, 3, 4, 5) if self._donate else ())
        self._chunk_jit = jax.jit(chunk_fn, donate_argnums=donate_cache)
        self._chunk_final_jit = jax.jit(chunk_final_fn, donate_argnums=donate)
        self._insert_jit = jax.jit(insert_fn, donate_argnums=(0, 1) if self._donate else ())
        self._extract_jit = jax.jit(extract_fn)  # pure read: nothing donated

    def _fused(self, depth: int):
        """The fused-decode program for ``depth`` scan iterations (compiled
        once per distinct depth; carry donated, params threaded as consts)."""
        jitfn = self._fused_jits.get(depth)
        if jitfn is None:
            from ..jit import scan_steps

            jitfn = scan_steps(self._decode_body, length=depth, with_consts=True,
                               donate_argnums=(1,) if self._donate else ())
            self._fused_jits[depth] = jitfn
        return jitfn

    def _dispatch(self, which: str, jitfn, args, label: Optional[str] = None):
        """Run one dispatch, AOT-compiling on a new (kind, shape) signature
        so the XLA Compiled handle is retained for ``explain()`` and the
        compile is counted/logged — the TrainStep._dispatch idiom. With
        ``FLAGS_compile_cache_dir`` set, executables round-trip through the
        on-disk AOT cache: a restarted engine loads instead of compiling."""
        if _sanitizer.enabled():
            # pre-flight: the decode/prefill programs donate the KV cache
            # and slot-state buffers — holding one across a dispatch is the
            # PR-10 aliasing bug; a deleted leaf raises a structured
            # StaleStateError naming its path instead of crashing in XLA
            _sanitizer.check_state("decode_engine", args, label=which)
        sig = (which,) + tuple(
            (tuple(l.shape), str(l.dtype)) for l in jax.tree_util.tree_leaves(args))
        entry = self._compiled.get(sig)
        if entry is None:
            _sanitizer.note_compile("decode_engine", which, sig[1:])
            from ..observability import introspect as _introspect
            from ..observability import runlog as _runlog
            from ..observability import span as _span
            from ..profiler import counter_inc
            from . import aot_cache

            label = label or which
            key = aot_cache.make_key(which, sig[1:], self._fingerprint)
            entry = aot_cache.load(key)
            if entry is not None:
                self._compiled[sig] = entry
                counter_inc("infer.aot_cache_hits")
                self._specializations.append({"label": label, "kind": which,
                                              "from_disk_cache": True})
                _runlog.emit("compile", component="infer", label=label, cached=True)
            else:
                with _span("infer.compile"):
                    compiled, info = _introspect.aot_compile(jitfn, args)
                entry = compiled if compiled is not None else jitfn
                if compiled is not None:
                    from ..framework.flags import flag as _flag

                    if _flag("FLAGS_shard_check"):
                        # serving pre-flight (PTA2xx) before the executable
                        # is cached: PTA203 flags any collective compiled
                        # into a decode program — the hot loop pays it per
                        # generated token — and PTA204 budget overruns
                        # abort before the request stream starts
                        from ..analysis import spmd as _spmd

                        report = _spmd.shard_check(
                            compiled, component="infer", label=label,
                            kind=which, options=_spmd.ShardCheckOptions(
                                decode=which.startswith("decode")))
                        info["spmd"] = report.summary()
                self._compiled[sig] = entry
                counter_inc("infer.compiles")
                if compiled is not None and aot_cache.store(key, compiled):
                    counter_inc("infer.aot_cache_stores")
                info["label"] = label
                info["kind"] = which
                self._specializations.append(info)
                _runlog.emit("compile", component="infer", label=label,
                             seconds=info.get("compile_seconds"),
                             flops=info.get("flops"),
                             bytes_accessed=info.get("bytes_accessed"),
                             peak_bytes=info.get("peak_bytes"))
        try:
            try:
                with _sanitizer.transfer_scope(f"infer.{which}"):
                    return entry(*args)
            except (TypeError, ValueError):
                if entry is jitfn:
                    raise
                self._compiled[sig] = jitfn  # AOT aval drift: jit path forever
                with _sanitizer.transfer_scope(f"infer.{which}"):
                    return jitfn(*args)
        except Exception as exc:
            # unhandled dispatch fault (aval drift already fell back above):
            # leave a flight-recorder dump, then let the fault propagate
            from ..observability import flightrec as _flightrec

            _flightrec.dump("dispatch_exception", exc, component="infer",
                            which=which, label=label or which)
            raise

    # ------------------------------------------------------------ slot API
    def bucket_for(self, prompt_len: int) -> int:
        """The padded prefill length for a prompt: its bucket, or — in
        chunked mode — the chunk-rounded length (capped at max_seq_len)."""
        if self._chunk is not None:
            if prompt_len > self.max_seq_len:
                raise ValueError(f"prompt of {prompt_len} tokens exceeds "
                                 f"max_seq_len {self.max_seq_len}")
            return min(self.max_seq_len, -(-prompt_len // self._chunk) * self._chunk)
        for b in self.buckets:
            if b >= prompt_len:
                return b
        raise ValueError(f"prompt of {prompt_len} tokens exceeds the largest "
                         f"prefill bucket {self.buckets[-1]}")

    def free_slots(self) -> List[int]:
        return [i for i in range(self.max_batch_slots) if not self._occupied[i]]

    # ----------------------------------------------------------- prefill
    def begin_prefill(self, prompt, slot: int, max_new_tokens: int,
                      eos_token_id: Optional[int] = None, seed: int = 0) -> _PrefillJob:
        """Claim ``slot`` for one prompt and apply any prefix-cache hits
        (insert dispatches only — no model compute). Drive the returned job
        with :meth:`prefill_step`; the scheduler interleaves those chunk
        dispatches with decode so long admissions stop stalling the stream.
        """
        from ..observability.metrics import counter_inc, gauge_set

        prompt = np.asarray(prompt, np.int32).reshape(-1)
        n = int(prompt.shape[0])
        if n < 1:
            raise ValueError("empty prompt")
        if self._occupied[slot]:
            raise ValueError(f"slot {slot} is occupied; free it first")
        if n + int(max_new_tokens) > self.max_seq_len:
            raise ValueError(f"prompt {n} + max_new_tokens {max_new_tokens} "
                             f"exceeds max_seq_len {self.max_seq_len}")
        eos = -1 if eos_token_id is None else int(eos_token_id)
        limit = n + int(max_new_tokens)
        job = _PrefillJob(slot, prompt, n, eos, limit, int(seed))
        self._occupied[slot] = True
        self._eos[slot] = eos
        self._limit[slot] = limit
        self._seed[slot] = int(seed)
        if self.prefix_cache is not None:
            # reuse at most n-1 tokens: the prompt's last token must run
            # through the model (its logits pick the first generated token)
            matched = self.prefix_cache.match(prompt, max_tokens=n - 1)
            for i, (seg_k, seg_v) in enumerate(matched):
                self._ck, self._cv = self._dispatch(
                    "prefix_insert", self._insert_jit,
                    (self._ck, self._cv, seg_k, seg_v, jnp.int32(slot),
                     jnp.int32(i * self._chunk)))
                counter_inc("infer.prefix_insert_dispatches")
            if matched and self._dparams is not None:
                # the prefix cache holds TARGET KV only; backfill the draft
                # cache for the matched prefix with cheap draft-only chunk
                # forwards (ascending — each chunk attends the earlier ones)
                for i in range(len(matched)):
                    ids = prompt[i * self._chunk:(i + 1) * self._chunk][None]
                    self._dck, self._dcv = self._dispatch(  # noqa: PTA104 (host-side serving state)
                        "draft_chunk", self._draft_chunk_jit,
                        (self._dparams, self._dck, self._dcv, jnp.asarray(ids),
                         jnp.int32(slot), jnp.int32(i * self._chunk)),
                        label=f"draft_chunk/C{self._chunk}")
            job.next_pos = job.reused_tokens = len(matched) * self._chunk
            counter_inc("serving.prefix_hits" if matched else "serving.prefix_misses")
            counter_inc("serving.prefix_tokens_reused", job.reused_tokens)
            gauge_set("serving.prefix_cache_bytes", self.prefix_cache.bytes_used())
        return job

    def prefill_step(self, job: _PrefillJob) -> bool:
        """Run ONE prefill dispatch for ``job``: the whole bucket program in
        bucketed mode, or one C-token chunk in chunked mode. Returns True
        when the prompt is fully prefilled (``job.first``/``job.more`` are
        then set and the slot starts decoding on the next decode dispatch).
        """
        from ..observability import span as _span
        from ..profiler import counter_inc

        if job.done:
            return True
        n, slot = job.n, job.slot
        spec = self._dparams is not None
        if self._chunk is None:
            P = self.bucket_for(n)
            ids = np.zeros((1, P), np.int32)
            ids[0, :n] = job.prompt
            state = ((self._params, self._dparams, self._ck, self._cv, self._dck, self._dcv)
                     if spec else (self._params, self._ck, self._cv))
            with _span("infer.prefill"):
                out = self._dispatch(
                    "prefill", self._prefill_jit,
                    state + (self._pos, self._tok, self._active,
                             jnp.asarray(ids), jnp.int32(n), jnp.int32(slot), jnp.int32(job.eos),
                             jnp.int32(job.limit), jnp.int32(job.seed)),
                    label=f"prefill/P{P}")
            if spec:
                self._ck, self._cv, self._dck, self._dcv = out[:4]  # noqa: PTA104 (host-side serving state)
                out = out[4:]
            else:
                self._ck, self._cv = out[:2]  # noqa: PTA104 (host-side serving state)
                out = out[2:]
            self._pos, self._tok, self._active, first, more = out  # noqa: PTA104 (host-side serving state)
            job.next_pos = n
        else:
            C = self._chunk
            if job.next_pos + C < n:
                # intermediate chunk: KV writes only, no logits work
                ids = job.prompt[job.next_pos:job.next_pos + C][None]
                state = ((self._params, self._dparams, self._ck, self._cv, self._dck, self._dcv)
                         if spec else (self._params, self._ck, self._cv))
                with _span("infer.prefill_chunk"):
                    out = self._dispatch(
                        "prefill_chunk", self._chunk_jit,
                        state + (jnp.asarray(ids), jnp.int32(slot), jnp.int32(job.next_pos)),
                        label=f"prefill_chunk/C{C}")
                if spec:
                    self._ck, self._cv, self._dck, self._dcv = out  # noqa: PTA104 (host-side serving state)
                else:
                    self._ck, self._cv = out  # noqa: PTA104 (host-side serving state)
                job.next_pos += C
                counter_inc("infer.prefill_chunk_dispatches")
                return False
            # final chunk: cover the remaining rows [next_pos, n) inside one
            # C-token window. The window start stays chunk-aligned unless the
            # padded write would spill past the cache end, in which case it
            # shifts back to [n-C, n) and re-writes a few rows bitwise.
            w = job.next_pos if job.next_pos + C <= self.max_seq_len else n - C
            ids = np.zeros((1, C), np.int32)
            ids[0, :n - w] = job.prompt[w:n]
            state = ((self._params, self._dparams, self._ck, self._cv, self._dck, self._dcv)
                     if spec else (self._params, self._ck, self._cv))
            with _span("infer.prefill_chunk"):
                out = self._dispatch(
                    "prefill_final", self._chunk_final_jit,
                    state + (self._pos, self._tok, self._active,
                             jnp.asarray(ids), jnp.int32(slot), jnp.int32(w),
                             jnp.int32(n - 1 - w), jnp.int32(n), jnp.int32(job.eos),
                             jnp.int32(job.limit), jnp.int32(job.seed)),
                    label=f"prefill_final/C{C}")
            if spec:
                self._ck, self._cv, self._dck, self._dcv = out[:4]  # noqa: PTA104 (host-side serving state)
                out = out[4:]
            else:
                self._ck, self._cv = out[:2]  # noqa: PTA104 (host-side serving state)
                out = out[2:]
            self._pos, self._tok, self._active, first, more = out  # noqa: PTA104 (host-side serving state)
            job.next_pos = n
            counter_inc("infer.prefill_chunk_dispatches")
        job.first = int(first)
        job.more = bool(more)
        job.done = True
        self._active_np[slot] = job.more
        counter_inc("infer.prefill_dispatches")
        counter_inc("infer.tokens")
        if self.prefix_cache is not None:
            self._store_prefix_chunks(job)
        return True

    def _store_prefix_chunks(self, job: _PrefillJob) -> None:
        """After a completed prefill, extract and cache every chunk-aligned
        prefix segment of the prompt that isn't cached yet (the slot's rows
        below n are final: decode writes only at positions >= n)."""
        from ..observability.metrics import counter_inc, gauge_set

        cache = self.prefix_cache
        for i in range(job.n // self._chunk):
            key = cache.key(job.prompt, i)
            if cache.has(key):
                continue
            seg_k, seg_v = self._dispatch(
                "prefix_extract", self._extract_jit,
                (self._ck, self._cv, jnp.int32(job.slot), jnp.int32(i * self._chunk)))
            counter_inc("infer.prefix_extract_dispatches")
            cache.put(key, seg_k, seg_v)
        gauge_set("serving.prefix_cache_bytes", cache.bytes_used())

    def prefill(self, prompt, slot: int, max_new_tokens: int, eos_token_id: Optional[int] = None,
                seed: int = 0) -> Tuple[int, bool]:
        """Admit one prompt into ``slot`` synchronously (every chunk back to
        back): prefix-cache inserts, prefill dispatches, first-token sample.
        Returns ``(first_token, more)`` — ``more`` False means the request
        finished at its first token (eos or max_new_tokens == 1)."""
        job = self.begin_prefill(prompt, slot, max_new_tokens,
                                 eos_token_id=eos_token_id, seed=seed)
        while not self.prefill_step(job):
            pass
        return job.first, job.more

    # ------------------------------------------------------------- decode
    def decode_step(self, fuse: Optional[int] = None):
        """Advance every active slot in ONE dispatch. At fuse depth 1
        returns ``(tokens[B], emitted[B], active[B])``; at depth D > 1 the
        dispatch runs D decode iterations inside one donated ``lax.scan``
        and returns ``(tokens[D, B], emitted[D, B], active[B])`` — the
        eos/limit stop flags ride the scan carry, so a slot that finishes at
        iteration j self-deactivates in-graph (``emitted[j+1:, slot]`` is
        False) with no host round-trip until the stack is drained."""
        from ..observability import span as _span
        from ..observability.metrics import observe
        from ..profiler import counter_inc

        depth = self.fuse if fuse is None else int(fuse)
        if depth < 1:
            raise ValueError(f"fuse depth must be >= 1, got {depth}")
        if self._dparams is not None:
            if depth != 1:
                raise ValueError("speculative decode runs at fuse depth 1 (one "
                                 "dispatch already emits up to spec_k+1 tokens)")
            from ..observability.metrics import gauge_set

            with _span("infer.spec_decode"):
                out = self._dispatch(
                    "spec_decode", self._spec_jit,
                    (self._params, self._dparams, self._ck, self._cv, self._dck, self._dcv,
                     self._pos, self._tok, self._active,
                     jnp.asarray(self._eos), jnp.asarray(self._limit), jnp.asarray(self._seed)),
                    label=f"spec_decode/K{self.spec_k}")
            (self._ck, self._cv, self._dck, self._dcv,  # noqa: PTA104 (host-side serving state)
             self._pos, self._tok, self._active, toks, emitted) = out  # noqa: PTA104 (host-side serving state)
            toks = np.asarray(toks)
            emitted = np.asarray(emitted)
            self._active_np = np.array(self._active)  # noqa: PTA104 (host-side serving state)
            n_active = int(emitted[0].sum())   # row 0 always emits per live slot
            n_emitted = int(emitted.sum())
            self._spec_drafted += self.spec_k * n_active  # noqa: PTA104 (host-side serving state)
            self._spec_accepted += n_emitted - n_active  # noqa: PTA104 (host-side serving state)
            counter_inc("infer.decode_dispatches")
            counter_inc("infer.tokens", n_emitted)
            counter_inc("infer.spec_draft_tokens", self.spec_k * n_active)
            counter_inc("infer.spec_accepted_tokens", n_emitted - n_active)
            if self._spec_drafted:
                gauge_set("serving.spec_acceptance_rate",
                          self._spec_accepted / self._spec_drafted)
            observe("infer.tokens_per_decode_dispatch", float(n_emitted))
            return toks, emitted, self._active_np.copy()
        if depth == 1:
            emitted = self._active_np.copy()
            with _span("infer.decode_step"):
                out = self._dispatch(
                    "decode", self._decode_jit,
                    (self._params, self._ck, self._cv, self._pos, self._tok, self._active,
                     jnp.asarray(self._eos), jnp.asarray(self._limit), jnp.asarray(self._seed)))
            self._ck, self._cv, self._pos, self._tok, self._active = out
            toks = np.asarray(self._tok)
            self._active_np = np.array(self._active)  # writable host mirror
            counter_inc("infer.decode_dispatches")
            counter_inc("infer.tokens", int(emitted.sum()))
            observe("infer.tokens_per_decode_dispatch", float(emitted.sum()))
            return toks, emitted, self._active_np.copy()
        consts = (self._params, jnp.asarray(self._eos), jnp.asarray(self._limit),
                  jnp.asarray(self._seed))
        carry = (self._ck, self._cv, self._pos, self._tok, self._active)
        with _span("infer.decode_step"):
            out = self._dispatch(f"decode_x{depth}", self._fused(depth), (consts, carry))
        (self._ck, self._cv, self._pos, self._tok, self._active), (toks, emitted) = out
        toks = np.asarray(toks)
        emitted = np.asarray(emitted)
        self._active_np = np.array(self._active)
        counter_inc("infer.decode_dispatches")
        counter_inc("infer.tokens", int(emitted.sum()))
        observe("infer.tokens_per_decode_dispatch", float(emitted.sum()))
        return toks, emitted, self._active_np.copy()

    def free_slot(self, slot: int) -> None:
        """Release a slot for the next admission (cancels it if still live)."""
        if self._active_np[slot]:
            self._active = self._active.at[slot].set(False)
            self._active_np[slot] = False
        self._occupied[slot] = False

    def reset(self) -> None:
        """Drop every in-flight request and zero the slot state (the cache
        keeps its buffers — stale K/V is always overwritten before it can be
        attended)."""
        B = self.max_batch_slots
        self._pos = jnp.zeros((B,), jnp.int32)
        self._tok = jnp.zeros((B,), jnp.int32)
        self._active = jnp.zeros((B,), bool)
        self._active_np[:] = False
        self._occupied[:] = False
        self._eos[:] = -1
        self._limit[:] = 0
        self._seed[:] = 0

    # ------------------------------------------------------------- helpers
    def generate(self, ids, max_new_tokens: int = 32, eos_token_id: Optional[int] = None,
                 seed: int = 0, fuse: Optional[int] = None) -> np.ndarray:
        """Batch generate through the slot machinery (parity helper + the
        bench decode path): each row takes one slot, prefill once per row,
        then decode steps (at ``fuse`` depth — default the engine's) until
        every row finishes. Returns ``[b, s0 + max_new_tokens]`` int32 (rows
        that hit eos pad with it) — same contract as
        ``GPTForPretraining.generate``."""
        ids = np.asarray(ids, np.int32)
        if ids.ndim == 1:
            ids = ids[None]
        b, s0 = ids.shape
        if b > self.max_batch_slots:
            raise ValueError(f"batch {b} exceeds max_batch_slots {self.max_batch_slots}")
        self.reset()
        rows = [[] for _ in range(b)]
        for i in range(b):
            tok, _more = self.prefill(ids[i], slot=i, max_new_tokens=max_new_tokens,
                                      eos_token_id=eos_token_id, seed=seed)
            rows[i].append(tok)
        while self._active_np.any():
            toks, emitted, _ = self.decode_step(fuse=fuse)
            toks = np.atleast_2d(toks)
            emitted = np.atleast_2d(emitted)
            for d in range(toks.shape[0]):
                for i in range(b):
                    if emitted[d, i]:
                        rows[i].append(int(toks[d, i]))
        for i in range(b):
            self.free_slot(i)
        out = np.zeros((b, s0 + int(max_new_tokens)), np.int32)
        out[:, :s0] = ids
        for i, r in enumerate(rows):
            pad = r[-1] if eos_token_id is None else int(eos_token_id)
            r = r + [pad] * (int(max_new_tokens) - len(r))
            out[i, s0:] = r[:int(max_new_tokens)]
        return out

    def explain(self, analyze: bool = False) -> List[dict]:
        """Per-specialization cost rows (prefill buckets/chunks, prefix
        insert/extract, and the decode programs) captured at AOT compile —
        render with ``observability.format_cost_table``.

        ``analyze=True`` attaches the SPMD analyzer verdict (PTA2xx) per
        retained executable under ``"spmd"`` — decode programs are checked
        with the PTA203 serving rule (any compiled-in collective fires per
        generated token)."""
        rows = [dict(r) for r in self._specializations]
        if analyze:
            from ..analysis import spmd as _spmd

            for row, entry in zip(rows, list(self._compiled.values())):
                if "spmd" in row or not hasattr(entry, "as_text"):
                    continue
                kind = str(row.get("kind", ""))
                row["spmd"] = _spmd.analyze_compiled(
                    entry, label=row.get("label", ""), kind=kind,
                    options=_spmd.ShardCheckOptions(
                        decode=kind.startswith("decode"))).summary()
        return rows

    def cache_bytes(self) -> int:
        """Device bytes held by the preallocated target K/V cache, summed
        over the ACTUAL stored leaves — under ``kv_dtype="int8"`` that is
        the int8 payload plus the f32 scale planes, not the compute dtype."""
        leaves = jax.tree_util.tree_leaves((self._ck, self._cv))
        return int(sum(l.size * jnp.dtype(l.dtype).itemsize for l in leaves))

    def draft_cache_bytes(self) -> int:
        """Device bytes held by the draft model's K/V cache (0 without a
        draft)."""
        if self._dck is None:
            return 0
        leaves = jax.tree_util.tree_leaves((self._dck, self._dcv))
        return int(sum(l.size * jnp.dtype(l.dtype).itemsize for l in leaves))

    def kv_bytes_per_slot(self) -> int:
        """Per-request HBM cost of admission: the target cache's stored
        bytes divided by the slot count (the ``infer.kv_bytes_per_slot``
        gauge — sizing concurrent-slot capacity from this number stays
        honest under int8 KV)."""
        return self.cache_bytes() // self.max_batch_slots

    def spec_stats(self) -> dict:
        """Cumulative speculative-decoding counters: proposals drafted,
        proposals accepted, and their ratio (0.0 before any decode)."""
        drafted = self._spec_drafted
        return {"spec_k": self.spec_k, "drafted": drafted,
                "accepted": self._spec_accepted,
                "acceptance_rate": (self._spec_accepted / drafted) if drafted else 0.0}
