"""GPT decoder-only language model — the flagship pretraining model.

Parity: the reference ships GPT as its auto-parallel/fleet workhorse
(python/paddle/fluid/tests/unittests/auto_parallel_gpt_model.py;
ppfleetx-style GPT built from paddle.nn.TransformerDecoder + the TP layers in
fleet/meta_parallel/parallel_layers/mp_layers.py:30,95,171,251).

TPU-first: every parallelism is a sharding annotation, not a wrapper —
  * vocab over 'mp' (VocabParallelEmbedding),
  * attention heads + ffn hidden over 'mp' (Column/RowParallelLinear),
  * batch over 'dp'×'sdp' (fleet.distributed_step input sharding),
  * sequence over 'sep' for long context (ring/Ulysses attention in
    distributed/ring_attention.py can replace the core here),
  * layers stackable over 'pp' via distributed/pipeline.spmd_pipeline.
The attention core dispatches to the Pallas flash kernel on TPU
(ops/flash_attention.py), replacing fused_attention_op.cu /
fused_multi_transformer_op.cu.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .. import nn
from ..distributed.mp_layers import (
    ColumnParallelLinear,
    ParallelCrossEntropy,
    RowParallelLinear,
    VocabParallelEmbedding,
)
from ..framework import random as _random
from ..nn import functional as F
from ..nn import initializer as I
from ..tensor import manipulation as M


class GPTConfig:
    """Hyperparameters. ``gpt3_1p3b()`` is the BASELINE.json config #4 model.

    ``stacked=True`` (default) builds the trunk as :class:`GPTBlockStack` —
    all L blocks as [L, ...]-stacked parameters run via lax.scan (one block
    trace, fast compile) or, under a fleet mesh with pp_degree>1, via the
    spmd_pipeline over the 'pp' axis. ``recompute=True`` turns on per-layer
    rematerialization inside the scan/pipeline (activation memory ~O(L·input)
    instead of O(L·all-intermediates)).
    """

    def __init__(
        self,
        vocab_size=50304,
        hidden_size=768,
        num_layers=12,
        num_heads=12,
        ffn_hidden_size=None,
        max_seq_len=1024,
        dropout=0.0,
        attn_dropout=0.0,
        initializer_range=0.02,
        use_flash=True,
        stacked=True,
        recompute=False,
        recompute_granularity="full",
        moe=0,
        moe_num_experts=0,
        moe_every=2,
        moe_top_k=2,
        moe_capacity_factor=1.25,
    ):
        # ``moe=E`` is the one-knob spelling: swap every moe_every-th
        # block's dense FFN for an E-expert MoELayer and pick the per-layer
        # trunk it needs (a stacked trunk assumes homogeneous layers)
        if moe:
            moe_num_experts = moe_num_experts or int(moe)
            stacked = False
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.ffn_hidden_size = ffn_hidden_size or 4 * hidden_size
        self.max_seq_len = max_seq_len
        self.dropout = dropout
        self.attn_dropout = attn_dropout
        self.initializer_range = initializer_range
        self.use_flash = use_flash
        self.stacked = stacked
        self.recompute = recompute
        # 'full' recomputes the whole block in backward (max memory saving);
        # 'selective' saves matmul outputs and recomputes the rest (parity:
        # paddle recompute_granularity full vs full_attn/core_attn)
        self.recompute_granularity = recompute_granularity
        # GPT-MoE (GShard / ERNIE-3.0-style sparse FFN): every moe_every-th
        # block swaps its dense FFN for a MoELayer. Requires stacked=False
        # (the [L,...]-stacked trunk assumes homogeneous layers).
        self.moe_num_experts = moe_num_experts
        self.moe_every = moe_every
        self.moe_top_k = moe_top_k
        self.moe_capacity_factor = moe_capacity_factor
        if moe_num_experts and stacked:
            raise ValueError("GPT-MoE needs stacked=False (heterogeneous layers)")
        if moe_num_experts and moe_every < 1:
            raise ValueError(f"moe_every must be >= 1, got {moe_every}")

    def to_dict(self):
        """JSON-able constructor kwargs — the cross-process spelling of a
        config (e.g. a speculative-decoding draft model shipped to
        ProcServingFleet replicas over the subprocess spec)."""
        return dict(
            vocab_size=self.vocab_size,
            hidden_size=self.hidden_size,
            num_layers=self.num_layers,
            num_heads=self.num_heads,
            ffn_hidden_size=self.ffn_hidden_size,
            max_seq_len=self.max_seq_len,
            dropout=self.dropout,
            attn_dropout=self.attn_dropout,
            initializer_range=self.initializer_range,
            use_flash=self.use_flash,
            stacked=self.stacked,
            recompute=self.recompute,
        )

    @staticmethod
    def gpt3_1p3b(**kw):
        cfg = dict(vocab_size=50304, hidden_size=2048, num_layers=24, num_heads=16, max_seq_len=2048)
        cfg.update(kw)
        return GPTConfig(**cfg)

    @staticmethod
    def tiny(**kw):
        cfg = dict(vocab_size=512, hidden_size=64, num_layers=2, num_heads=4, max_seq_len=128)
        cfg.update(kw)
        return GPTConfig(**cfg)


class GPTAttention(nn.Layer):
    """Causal self-attention, heads sharded over 'mp' via column/row linears."""

    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.num_heads = cfg.num_heads
        self.head_dim = cfg.hidden_size // cfg.num_heads
        assert self.head_dim * cfg.num_heads == cfg.hidden_size
        init = I.Normal(0.0, cfg.initializer_range)
        self.qkv_proj = ColumnParallelLinear(cfg.hidden_size, 3 * cfg.hidden_size, weight_attr=init, gather_output=False)
        self.out_proj = RowParallelLinear(cfg.hidden_size, cfg.hidden_size, weight_attr=init, input_is_parallel=True)
        self.attn_dropout = cfg.attn_dropout

    def gen_cache(self, x, static=False, max_seq=None, kv_dtype=None):
        from ..nn.layer.transformer import MultiHeadAttention
        from ..tensor.creation import zeros

        if static:
            # fixed-shape serving cache: preallocated [b, max_seq, h, d],
            # written in place at the carried position — decode keeps one
            # set of shapes (and one compiled program) for the whole run.
            # kv_dtype="int8" preallocates the quantized representation
            # (int8 payload + f32 scale planes) instead of compute-dtype K/V.
            if max_seq is None:
                raise ValueError("gen_cache(static=True) needs max_seq=")
            if kv_dtype is not None:
                if str(kv_dtype) != "int8":
                    raise ValueError(f"kv_dtype must be None or 'int8', got {kv_dtype!r}")
                qz = lambda: zeros([x.shape[0], int(max_seq), self.num_heads, self.head_dim], dtype="int8")  # noqa: E731
                sz = lambda: zeros([x.shape[0], int(max_seq), self.num_heads], dtype="float32")  # noqa: E731
                return MultiHeadAttention.QuantizedFixedCache(qz(), sz(), qz(), sz(), zeros([], dtype="int32"))
            empty = lambda: zeros([x.shape[0], int(max_seq), self.num_heads, self.head_dim], dtype=x.dtype)  # noqa: E731
            return MultiHeadAttention.FixedCache(empty(), empty(), zeros([], dtype="int32"))
        empty = lambda: zeros([x.shape[0], 0, self.num_heads, self.head_dim], dtype=x.dtype)
        return MultiHeadAttention.Cache(empty(), empty())

    def forward(self, x, cache=None):
        from ..nn.layer.transformer import MultiHeadAttention

        b, s = x.shape[0], x.shape[1]
        qkv = self.qkv_proj(x)
        qkv = M.reshape(qkv, [b, s, 3, self.num_heads, self.head_dim])
        q, k, v = (M.squeeze(t, 2) for t in M.split(qkv, 3, axis=2))
        if isinstance(cache, MultiHeadAttention.FixedCache):
            from ..nn.layer.transformer import _fixed_cache_mask, _fixed_cache_write

            kf, vf = _fixed_cache_write(cache, k, v)
            mask = _fixed_cache_mask(cache.pos, s, kf.shape[1])
            out = F.scaled_dot_product_attention(q, kf, vf, attn_mask=mask, dropout_p=self.attn_dropout, training=self.training)
            out = M.reshape(out, [b, s, self.num_heads * self.head_dim])
            return self.out_proj(out), MultiHeadAttention.FixedCache(kf, vf, cache.pos + s)
        if isinstance(cache, MultiHeadAttention.QuantizedFixedCache):
            from ..nn.layer.transformer import (
                _fixed_cache_mask,
                _quant_cache_read,
                _quant_cache_write,
            )

            qk, sk = _quant_cache_write(cache.qk, cache.sk, k, cache.pos)
            qv, sv = _quant_cache_write(cache.qv, cache.sv, v, cache.pos)
            kf = _quant_cache_read(qk, sk, q.dtype)
            vf = _quant_cache_read(qv, sv, q.dtype)
            mask = _fixed_cache_mask(cache.pos, s, kf.shape[1])
            out = F.scaled_dot_product_attention(q, kf, vf, attn_mask=mask, dropout_p=self.attn_dropout, training=self.training)
            out = M.reshape(out, [b, s, self.num_heads * self.head_dim])
            return self.out_proj(out), MultiHeadAttention.QuantizedFixedCache(qk, sk, qv, sv, cache.pos + s)
        if cache is not None:
            if cache.k.shape[1] > 0:
                k = M.concat([cache.k, k], axis=1)
                v = M.concat([cache.v, v], axis=1)
            cache = MultiHeadAttention.Cache(k, v)
            # new queries attend to all cached keys + causally within the block
            import jax.numpy as jnp

            from ..framework.core import _wrap_value

            past = k.shape[1] - s
            mask = jnp.tril(jnp.ones((s, k.shape[1]), bool), k=past)
            out = F.scaled_dot_product_attention(q, k, v, attn_mask=_wrap_value(mask), dropout_p=self.attn_dropout, training=self.training)
        else:
            out = F.scaled_dot_product_attention(q, k, v, is_causal=True, dropout_p=self.attn_dropout, training=self.training)
        out = M.reshape(out, [b, s, self.num_heads * self.head_dim])
        out = self.out_proj(out)
        if cache is not None:
            return out, cache
        return out


class GPTBlock(nn.Layer):
    """Pre-LN decoder block (attn + gelu MLP), mp-sharded."""

    def __init__(self, cfg: GPTConfig, use_moe=False):
        super().__init__()
        init = I.Normal(0.0, cfg.initializer_range)
        self.norm1 = nn.LayerNorm(cfg.hidden_size)
        self.attn = GPTAttention(cfg)
        self.norm2 = nn.LayerNorm(cfg.hidden_size)
        self.moe = None
        if use_moe:
            from ..distributed.moe import MoELayer

            self.moe = MoELayer(cfg.hidden_size, cfg.ffn_hidden_size,  # noqa: PTA104 (host-side, never traced)
                                num_experts=cfg.moe_num_experts, top_k=cfg.moe_top_k,
                                capacity_factor=cfg.moe_capacity_factor)
        else:
            self.ffn1 = ColumnParallelLinear(cfg.hidden_size, cfg.ffn_hidden_size, weight_attr=init, gather_output=False)  # noqa: PTA104 (host-side, never traced)
            self.ffn2 = RowParallelLinear(cfg.ffn_hidden_size, cfg.hidden_size, weight_attr=init, input_is_parallel=True)  # noqa: PTA104 (host-side, never traced)
        self.dropout = nn.Dropout(cfg.dropout)

    def gen_cache(self, x, static=False, max_seq=None, kv_dtype=None):
        return self.attn.gen_cache(x, static=static, max_seq=max_seq, kv_dtype=kv_dtype)

    def forward(self, x, cache=None):
        if cache is not None:
            att, cache = self.attn(self.norm1(x), cache=cache)
            x = x + self.dropout(att)
        else:
            x = x + self.dropout(self.attn(self.norm1(x)))
        if self.moe is not None:
            x = x + self.dropout(self.moe(self.norm2(x)))
        else:
            x = x + self.dropout(self.ffn2(F.gelu(self.ffn1(self.norm2(x)), approximate=True)))
        if cache is not None:
            return x, cache
        return x


def _attn_core(q, k, v, attn_dropout=0.0, key=None):
    """Pure-array causal self-attention via the ``sdpa`` kernel-registry
    entry: Pallas flash kernel on TPU when shapes allow, jnp reference
    otherwise (same selection the eager F.scaled_dot_product_attention
    makes)."""
    from ..ops import registry

    return registry.dispatch("sdpa", q, k, v, None, True, attn_dropout, key, None)


def _attn_core_packed(qkv, attn_dropout=0.0, key=None):
    """Same over the packed [b, s, 3, h, d] qkv-projection output, via the
    ``attention_core`` registry entry: the flat-lane kernels read q/k/v via
    index maps and return the packed d(qkv) in backward — avoiding the
    slice/relayout copies of the split form — with the classic pair and the
    jnp reference as ordered fallbacks."""
    from ..ops import registry

    return registry.dispatch("attention_core", qkv, attn_dropout, key)


def _block_apply(lp, h, key, *, num_heads, dropout=0.0, attn_dropout=0.0, epsilon=1e-5):
    """One pre-LN decoder block on raw arrays. ``lp`` = (12 stacked-param
    slices, layer index); ``key`` = dropout PRNG key or None."""
    from ..ops.layer_norm import layer_norm_fused

    (n1w, n1b, qkvw, qkvb, ow, ob, n2w, n2b, f1w, f1b, f2w, f2b), idx = lp

    def ln(v, w, b):
        # fused closed-form vjp: autodiff-of-mean/var compiled to ~0.7ms/layer
        # of backward reduce fusions on TPU (r4 profile); see ops/layer_norm.py
        return layer_norm_fused(v, w, b, epsilon)

    def drop(v, p, k):
        if p == 0.0 or k is None:
            return v
        keep = jax.random.bernoulli(k, 1.0 - p, v.shape)
        return jnp.where(keep, v / (1.0 - p), 0.0).astype(v.dtype)

    k_attn = k_res1 = k_res2 = None
    if key is not None:
        base = jax.random.fold_in(key, idx)
        k_attn, k_res1, k_res2 = (jax.random.fold_in(base, i) for i in range(3))

    b, s, d = h.shape
    hd = d // num_heads
    x1 = ln(h, n1w, n1b)
    qkv = (x1 @ qkvw + qkvb).reshape(b, s, 3, num_heads, hd)
    att = _attn_core_packed(qkv, attn_dropout, k_attn).reshape(b, s, d)
    h = h + drop(att @ ow + ob, dropout, k_res1)
    x2 = ln(h, n2w, n2b)
    y = jax.nn.gelu(x2 @ f1w + f1b, approximate=True)
    h = h + drop(y @ f2w + f2b, dropout, k_res2)
    return h


def _stack_forward(x, *rest, num_layers, num_heads, dropout, attn_dropout, recompute, has_key, mesh, n_micro):
    """Whole-trunk forward on raw arrays: scan over layers (pp==1) or
    spmd_pipeline over the 'pp' mesh axis (pp>1)."""
    from jax.sharding import NamedSharding

    from ..distributed.pipeline import active_pipeline_schedule, microbatch, spmd_pipeline, unmicrobatch

    if has_key:
        params, key = rest[:-1], rest[-1]
    else:
        params, key = rest, None
    idx = jnp.arange(num_layers, dtype=jnp.int32)
    stacked = (tuple(params), idx)
    block = functools.partial(_block_apply, num_heads=num_heads, dropout=dropout, attn_dropout=attn_dropout)

    def constrain(h):
        """Pin the scan carry's sharding (batch over dp×sdp, seq over 'sep',
        hidden replicated). Without this GSPMD flip-flops the carry between
        batch- and mp-sharded layouts at the loop boundary — the 'Involuntary
        full rematerialization' warnings (VERDICT r2)."""
        if mesh is None:
            return h
        spec = P(("dp", "sdp"), "sep" if mesh.shape.get("sep", 1) > 1 else None, None)
        try:
            return jax.lax.with_sharding_constraint(h, NamedSharding(mesh, spec))
        except (ValueError, TypeError):  # eager run outside jit
            return h

    if mesh is not None and mesh.shape.get("pp", 1) > 1:
        if has_key:
            # fold by microbatch index so the n_micro passes draw distinct
            # dropout masks (the layer index is folded inside _block_apply)
            stage_fn = lambda lp, h, mb, k: block(lp, h, jax.random.fold_in(k, mb))
            extras = (key,)
        else:
            stage_fn = lambda lp, h, mb: block(lp, h, None)
            extras = ()
        xm = microbatch(x, n_micro, mesh)
        out = spmd_pipeline(stage_fn, stacked, xm, mesh, axis="pp", remat=bool(recompute), extras=extras, mb_index=True, schedule=active_pipeline_schedule())
        return unmicrobatch(out, mesh)

    # statically-unrolled layer loop: XLA schedules/fuses across layers and
    # chooses per-layer buffer lifetimes — measured ~20% faster than
    # lax.scan over the stacked axis on TPU (scan also pins all per-layer
    # residual stacks as single live buffers, which OOMs first)
    body = lambda lp, h: block(lp, h, key)
    if recompute == "full":
        body = jax.checkpoint(body)
    elif recompute == "selective":
        # keep matmul outputs (qkv/proj/ffn), recompute cheap elementwise +
        # attention internals — near-baseline speed, most of the memory win
        body = jax.checkpoint(body, policy=jax.checkpoint_policies.dots_saveable)
    h = constrain(x)
    for i in range(num_layers):
        lp = (tuple(p[i] for p in params), idx[i])
        h = constrain(body(lp, h))
    return h


class GPTBlockStack(nn.Layer):
    """All decoder blocks as [L, ...]-stacked parameters: the leading axis
    shards over 'pp', per-tensor dims over 'mp'.
    pp==1 runs one lax.scan (single block trace — XLA compiles the block
    once); pp>1 runs the GPipe-schedule spmd_pipeline. Parity: the trunk of
    pp_layers.py:162 PipelineLayer + mp_layers.py TP layers, as shardings.
    """

    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        L, D, Ff = cfg.num_layers, cfg.hidden_size, cfg.ffn_hidden_size
        init = I.Normal(0.0, cfg.initializer_range)

        def mk(shape, initializer, mp_dim=None):
            p = self.create_parameter(shape, default_initializer=initializer)
            spec = [None] * len(shape)
            spec[0] = "pp"
            if mp_dim is not None:
                spec[mp_dim] = "mp"  # noqa: PTA104 (host-side, never traced)
            p.dist_spec = P(*spec)
            p.is_distributed = True
            return p

        self.norm1_w = mk([L, D], I.Constant(1.0))
        self.norm1_b = mk([L, D], I.Constant(0.0))
        self.qkv_w = mk([L, D, 3 * D], init, mp_dim=2)
        self.qkv_b = mk([L, 3 * D], I.Constant(0.0), mp_dim=1)
        self.out_w = mk([L, D, D], init, mp_dim=1)
        self.out_b = mk([L, D], I.Constant(0.0))
        self.norm2_w = mk([L, D], I.Constant(1.0))
        self.norm2_b = mk([L, D], I.Constant(0.0))
        self.ffn1_w = mk([L, D, Ff], init, mp_dim=2)
        self.ffn1_b = mk([L, Ff], I.Constant(0.0), mp_dim=1)
        self.ffn2_w = mk([L, Ff, D], init, mp_dim=1)
        self.ffn2_b = mk([L, D], I.Constant(0.0))
        self._order = ["norm1_w", "norm1_b", "qkv_w", "qkv_b", "out_w", "out_b",
                       "norm2_w", "norm2_b", "ffn1_w", "ffn1_b", "ffn2_w", "ffn2_b"]

    def load_blocks(self, blocks):
        """Copy weights from a list of eager :class:`GPTBlock` (parity/test
        helper: LayerList trunk -> stacked trunk)."""
        import numpy as np

        def stack(get):
            return jnp.asarray(np.stack([np.asarray(get(b)) for b in blocks]))

        self.norm1_w.set_value(stack(lambda b: b.norm1.weight.numpy()))
        self.norm1_b.set_value(stack(lambda b: b.norm1.bias.numpy()))
        self.qkv_w.set_value(stack(lambda b: b.attn.qkv_proj.weight.numpy()))
        self.qkv_b.set_value(stack(lambda b: b.attn.qkv_proj.bias.numpy()))
        self.out_w.set_value(stack(lambda b: b.attn.out_proj.weight.numpy()))
        self.out_b.set_value(stack(lambda b: b.attn.out_proj.bias.numpy()))
        self.norm2_w.set_value(stack(lambda b: b.norm2.weight.numpy()))
        self.norm2_b.set_value(stack(lambda b: b.norm2.bias.numpy()))
        self.ffn1_w.set_value(stack(lambda b: b.ffn1.weight.numpy()))
        self.ffn1_b.set_value(stack(lambda b: b.ffn1.bias.numpy()))
        self.ffn2_w.set_value(stack(lambda b: b.ffn2.weight.numpy()))
        self.ffn2_b.set_value(stack(lambda b: b.ffn2.bias.numpy()))

    def forward(self, x):
        from ..distributed.pipeline import active_pipeline_plan
        from ..tensor._helpers import ensure_tensor, op

        from ..distributed.fleet import fleet

        cfg = self.cfg
        mesh, n_micro = active_pipeline_plan()
        if mesh is None and fleet._hcg is not None:
            mesh = fleet._hcg.mesh  # no pipeline, but constrain activations
        dropping = self.training and (cfg.dropout > 0.0 or cfg.attn_dropout > 0.0)
        params = [getattr(self, n) for n in self._order]
        aux = [_random.key_tensor()] if dropping else []
        return op(
            _stack_forward,
            ensure_tensor(x),
            *params,
            *aux,
            _name="gpt_stack",
            num_layers=cfg.num_layers,
            num_heads=cfg.num_heads,
            dropout=cfg.dropout if dropping else 0.0,
            attn_dropout=cfg.attn_dropout if dropping else 0.0,
            recompute=cfg.recompute_granularity if cfg.recompute else False,
            has_key=dropping,
            mesh=mesh,
            n_micro=n_micro,
        )


# ------------------------------------------------------------- KV-cache packs
# An engine KV cache is either a plain array (compute dtype) or an int8
# pack ``{"q": int8 [..., S, dh], "s": f32 [..., S]}`` with one abs_max
# scale per (layer, slot, head, position) vector — per-head, per-position
# ("per-chunk along S" at chunk=1) granularity, so a row's round-trip error
# is bounded by its own abs_max/127 and never bleeds across positions. The
# helpers below keep every cache-touching forward representation-agnostic:
# writes quantize, attends read a dequantized view whose scale multiply XLA
# folds into the consuming matmul (the QuantizedLinear idiom on the cache).

def _kv_quantize(u):
    """``u [..., dh]`` → ``(q int8 [..., dh], s f32 [...])`` abs_max scales."""
    f = u.astype(jnp.float32)
    s = jnp.maximum(jnp.max(jnp.abs(f), axis=-1), 1e-8) / 127.0
    q = jnp.clip(jnp.round(f / s[..., None]), -127, 127).astype(jnp.int8)
    return q, s


def _kv_dequant(pack, dt):
    """Dequantized view of an int8 pack (folds into the consuming matmul)."""
    return (pack["q"].astype(jnp.float32) * pack["s"][..., None]).astype(dt)


def _kvc_read(c, dt):
    """Attend view of a cache: dequantizes a pack, passes arrays through."""
    return _kv_dequant(c, dt) if isinstance(c, dict) else c


def _kvc_update(c, u, idx):
    """In-place cache write of a compute-dtype update ``u`` at index tuple
    ``idx`` (scale plane takes ``idx[:-1]``); quantizes iff ``c`` is a pack."""
    if isinstance(c, dict):
        q, s = _kv_quantize(u)
        return {"q": jax.lax.dynamic_update_slice(c["q"], q, idx),
                "s": jax.lax.dynamic_update_slice(c["s"], s, idx[:-1])}
    return jax.lax.dynamic_update_slice(c, u, idx)


def _kvc_copy(c, seg, idx):
    """Copy an already-stored segment (same representation as ``c``) into the
    cache at ``idx`` — the prefix-cache insert: a pack segment moves int8
    payload + scale planes verbatim, never round-tripping through f32."""
    if isinstance(c, dict):
        return {"q": jax.lax.dynamic_update_slice(c["q"], seg["q"], idx),
                "s": jax.lax.dynamic_update_slice(c["s"], seg["s"], idx[:-1])}
    return jax.lax.dynamic_update_slice(c, seg, idx)


def _kvc_slice(c, idx, size):
    """Slice a segment out of the cache in its STORED representation (the
    prefix-cache extract; pair with :func:`_kvc_copy` to re-insert)."""
    if isinstance(c, dict):
        return {"q": jax.lax.dynamic_slice(c["q"], idx, size),
                "s": jax.lax.dynamic_slice(c["s"], idx[:-1], size[:-1])}
    return jax.lax.dynamic_slice(c, idx, size)


def _kv_layer(c, i):
    """Layer ``i`` of a stacked [L, ...] cache (array or pack)."""
    if isinstance(c, dict):
        return {"q": c["q"][i], "s": c["s"][i]}
    return c[i]


def _kv_stack(xs):
    """Re-stack per-layer caches (inverse of :func:`_kv_layer`)."""
    if isinstance(xs[0], dict):
        return {"q": jnp.stack([x["q"] for x in xs]),
                "s": jnp.stack([x["s"] for x in xs])}
    return jnp.stack(xs)


def _kv_zeros(shape, dt, kv_dtype=None):
    """A fresh cache buffer: ``shape`` is the payload shape ``[..., S, dh]``;
    ``kv_dtype="int8"`` allocates the quantized pack instead of ``dt``."""
    if kv_dtype == "int8":
        return {"q": jnp.zeros(shape, jnp.int8),
                "s": jnp.zeros(shape[:-1], jnp.float32)}
    return jnp.zeros(shape, dt)


def _cache_block(lp, h, ck, cv, start_pos, *, num_heads, epsilon=1e-5):
    """One decoder block with a fixed-size KV cache.

    h [b, s, d] (s = prompt len at prefill, 1 at decode); ck/cv
    [b, H, S, dh] (head-major so per-step attention reads the cache
    contiguously per head — the [b, S, H, dh] layout forced XLA to relayout
    the whole cache every decode step) hold keys/values for positions
    < start_pos and are updated in place at [start_pos, start_pos+s).
    Attention masks cache positions beyond start_pos+row. Scores run as
    bf16×bf16→f32 MXU dots (preferred_element_type) — no f32 cache
    materialization. Returns (h, ck, cv). Parity: the per-layer decode of
    fused_multi_transformer_op.cu, as lax ops on a static-shape cache.
    """
    (n1w, n1b, qkvw, qkvb, ow, ob, n2w, n2b, f1w, f1b, f2w, f2b), _ = lp

    def ln(v, w, bb):
        mean = jnp.mean(v, axis=-1, keepdims=True)
        var = jnp.var(v, axis=-1, keepdims=True)
        return (v - mean) / jnp.sqrt(var + epsilon) * w + bb

    b, s, d = h.shape
    S = (ck["q"] if isinstance(ck, dict) else ck).shape[2]
    hd = d // num_heads
    x1 = ln(h, n1w, n1b)
    qkv = (x1 @ qkvw + qkvb).reshape(b, s, 3, num_heads, hd)
    q = jnp.swapaxes(qkv[:, :, 0], 1, 2)  # [b, H, s, dh]
    k = jnp.swapaxes(qkv[:, :, 1], 1, 2)
    v = jnp.swapaxes(qkv[:, :, 2], 1, 2)
    ck = _kvc_update(ck, k, (0, 0, start_pos, 0))
    cv = _kvc_update(cv, v, (0, 0, start_pos, 0))
    rk = _kvc_read(ck, h.dtype)
    rv = _kvc_read(cv, h.dtype)
    scale = jnp.asarray(1.0 / (hd ** 0.5), q.dtype)
    scores = jnp.einsum("bhqd,bhkd->bhqk", q * scale, rk,
                        preferred_element_type=jnp.float32)
    q_pos = start_pos + jax.lax.broadcasted_iota(jnp.int32, (s, S), 0)
    k_pos = jax.lax.broadcasted_iota(jnp.int32, (s, S), 1)
    scores = jnp.where((k_pos <= q_pos)[None, None], scores, -jnp.inf)
    p = jax.nn.softmax(scores, axis=-1).astype(rv.dtype)
    att = jnp.einsum("bhqk,bhkd->bhqd", p, rv, preferred_element_type=jnp.float32)
    att = jnp.swapaxes(att.astype(h.dtype), 1, 2).reshape(b, s, d)
    h = h + att @ ow + ob
    x2 = ln(h, n2w, n2b)
    y = jax.nn.gelu(x2 @ f1w + f1b, approximate=True)
    h = h + y @ f2w + f2b
    return h, ck, cv


def _cache_forward(stacked, wte, wpe, fnw, fnb, ids, cache_k, cache_v, start_pos, *, num_heads, mesh=None):
    """Trunk forward over a fixed cache; returns (logits, cache_k, cache_v).

    cache_k/v: [L, b, H, S, dh]. ids [b, s]; positions start at start_pos.
    With ``mesh``, caches/activations carry mp (heads / vocab) sharding
    constraints so decode runs tensor-parallel (reference: the mp-sharded
    fused_multi_transformer decode path).
    """
    params, idx = stacked
    num_layers = params[0].shape[0]
    b, s = ids.shape

    def mpc(x, *spec):
        if mesh is None or mesh.shape.get("mp", 1) <= 1:
            return x
        from jax.sharding import NamedSharding

        return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, P(*spec)))

    pos = start_pos + jnp.arange(s, dtype=jnp.int32)
    h = jnp.take(wte, ids, axis=0) + jnp.take(wpe, pos, axis=0)[None]
    h = h.astype(wte.dtype)
    new_k, new_v = [], []
    for i in range(num_layers):
        lp = (tuple(p[i] for p in params), idx[i])
        h, ck, cv = _cache_block(lp, h, _kv_layer(cache_k, i), _kv_layer(cache_v, i),
                                 start_pos, num_heads=num_heads)
        # int8 packs skip the mp constraint (the serving engine never meshes)
        new_k.append(ck if isinstance(ck, dict) else mpc(ck, None, "mp"))  # noqa: PTA104 (static unroll, host loop bound)
        new_v.append(cv if isinstance(cv, dict) else mpc(cv, None, "mp"))  # noqa: PTA104 (static unroll, host loop bound)
    mean = jnp.mean(h, axis=-1, keepdims=True)
    var = jnp.var(h, axis=-1, keepdims=True)
    h = (h - mean) / jnp.sqrt(var + 1e-5) * fnw + fnb
    logits = mpc(jnp.einsum("bsd,vd->bsv", h, wte), None, None, "mp")
    return logits, _kv_stack(new_k), _kv_stack(new_v)


def _slot_cache_block(lp, h, ck, cv, pos, *, num_heads, epsilon=1e-5, active=None):
    """One decoder block over PER-SLOT cache positions (continuous-batching
    decode). ``h`` [b, W, d] holds a W-token window per batch slot (W=1 for
    plain decode, W=K+1 for the speculative verification forward); ``ck``/
    ``cv`` [b, H, S, dh] (or int8 packs); ``pos`` [b] int32 is each slot's
    write index for window row 0. The window's K/V are written at
    ``pos[b]`` via a vmapped ``dynamic_update_slice`` (write BEFORE attend,
    so a stale cache entry — including a speculative window's rejected
    tail — is always overwritten before it can become visible) and row j
    attends keys up to ``pos[b] + j`` — slots at different sequence depths
    share one compiled program. ``active`` [b] bool gates the write per
    slot: an inactive slot's cache stays bitwise untouched, so decode
    dispatches interleaved with another slot's chunked prefill cannot
    clobber its freshly written K/V at a stale ``pos``. Same per-row math
    as :func:`_cache_block` at s=1 (the bitwise basis of both the chunked-
    prefill and the greedy speculative-decoding pins).
    """
    (n1w, n1b, qkvw, qkvb, ow, ob, n2w, n2b, f1w, f1b, f2w, f2b), _ = lp

    def ln(v, w, bb):
        mean = jnp.mean(v, axis=-1, keepdims=True)
        var = jnp.var(v, axis=-1, keepdims=True)
        return (v - mean) / jnp.sqrt(var + epsilon) * w + bb

    b, s, d = h.shape
    S = (ck["q"] if isinstance(ck, dict) else ck).shape[2]
    hd = d // num_heads
    x1 = ln(h, n1w, n1b)
    qkv = (x1 @ qkvw + qkvb).reshape(b, s, 3, num_heads, hd)
    q = jnp.swapaxes(qkv[:, :, 0], 1, 2)  # [b, H, W, dh]
    k = jnp.swapaxes(qkv[:, :, 1], 1, 2)
    v = jnp.swapaxes(qkv[:, :, 2], 1, 2)
    if active is None:
        ck = jax.vmap(lambda c, u, p: _kvc_update(c, u, (0, p, 0)))(ck, k, pos)
        cv = jax.vmap(lambda c, u, p: _kvc_update(c, u, (0, p, 0)))(cv, v, pos)
    else:
        def upd(c, u, p, a):
            if isinstance(c, dict):
                uq, us = _kv_quantize(u)
                cq = jax.lax.dynamic_slice(c["q"], (0, p, 0), uq.shape)
                cs = jax.lax.dynamic_slice(c["s"], (0, p), us.shape)
                return {"q": jax.lax.dynamic_update_slice(
                            c["q"], jnp.where(a, uq, cq), (0, p, 0)),
                        "s": jax.lax.dynamic_update_slice(
                            c["s"], jnp.where(a, us, cs), (0, p))}
            cur = jax.lax.dynamic_slice(c, (0, p, 0), u.shape)
            return jax.lax.dynamic_update_slice(c, jnp.where(a, u, cur), (0, p, 0))

        ck = jax.vmap(upd)(ck, k, pos, active)
        cv = jax.vmap(upd)(cv, v, pos, active)
    rk = _kvc_read(ck, h.dtype)
    rv = _kvc_read(cv, h.dtype)
    scale = jnp.asarray(1.0 / (hd ** 0.5), q.dtype)
    scores = jnp.einsum("bhqd,bhkd->bhqk", q * scale, rk,
                        preferred_element_type=jnp.float32)
    k_pos = jax.lax.broadcasted_iota(jnp.int32, (b, s, S), 2)
    q_pos = pos[:, None, None] + jax.lax.broadcasted_iota(jnp.int32, (b, s, S), 1)
    visible = k_pos <= q_pos  # [b, W, S]: row j sees its slot's prefix + itself
    scores = jnp.where(visible[:, None], scores, -jnp.inf)
    p = jax.nn.softmax(scores, axis=-1).astype(rv.dtype)
    att = jnp.einsum("bhqk,bhkd->bhqd", p, rv, preferred_element_type=jnp.float32)
    att = jnp.swapaxes(att.astype(h.dtype), 1, 2).reshape(b, s, d)
    h = h + att @ ow + ob
    x2 = ln(h, n2w, n2b)
    y = jax.nn.gelu(x2 @ f1w + f1b, approximate=True)
    h = h + y @ f2w + f2b
    return h, ck, cv


def _slot_window_forward(stacked, wte, wpe, fnw, fnb, toks, cache_k, cache_v, pos, *, num_heads, active=None):
    """W-token trunk forward with per-slot start positions: row j of
    ``toks`` [b, W] runs at absolute position ``pos[b] + j`` against the
    engine's big cache — the speculative-decoding verification program (the
    target model scores the whole drafted window in ONE forward). Returns
    (logits [b, W, V], cache_k, cache_v); per-row math identical to the
    W=1 decode step, so greedy accepted tokens stay bitwise equal to
    sequential decode."""
    params, idx = stacked
    num_layers = params[0].shape[0]
    b, W = toks.shape
    rows = pos[:, None] + jnp.arange(W, dtype=jnp.int32)[None]
    # a speculative window near the sequence limit can index past the
    # positional table; clamp (those rows are never emitted — an unclamped
    # jnp.take fills NaN, which the window's own KV writes would spread to
    # later rows). No-op at W=1, where pos < max_seq_len always holds.
    rows = jnp.minimum(rows, jnp.int32(wpe.shape[0] - 1))
    h = jnp.take(wte, toks, axis=0) + jnp.take(wpe, rows, axis=0)
    h = h.astype(wte.dtype)
    new_k, new_v = [], []
    for i in range(num_layers):
        lp = (tuple(p[i] for p in params), idx[i])
        h, ck, cv = _slot_cache_block(lp, h, _kv_layer(cache_k, i), _kv_layer(cache_v, i),
                                      pos, num_heads=num_heads, active=active)
        new_k.append(ck)  # noqa: PTA104 (static unroll, host loop bound)
        new_v.append(cv)  # noqa: PTA104 (static unroll, host loop bound)
    mean = jnp.mean(h, axis=-1, keepdims=True)
    var = jnp.var(h, axis=-1, keepdims=True)
    h = (h - mean) / jnp.sqrt(var + 1e-5) * fnw + fnb
    logits = jnp.einsum("bsd,vd->bsv", h, wte)
    return logits, _kv_stack(new_k), _kv_stack(new_v)


def _slot_decode_forward(stacked, wte, wpe, fnw, fnb, tok, cache_k, cache_v, pos, *, num_heads, active=None):
    """One-token trunk forward with per-slot positions: the decode-step
    program of the serving engine. ``tok`` [b] int32 (last token per slot),
    ``cache_k``/``cache_v`` [L, b, H, S, dh] (or int8 packs), ``pos`` [b]
    int32, ``active`` [b] bool (optional) gates cache writes per slot.
    Returns (logits [b, V], cache_k, cache_v) — exactly one compiled
    program serves every step of every request regardless of each slot's
    depth. The W=1 case of :func:`_slot_window_forward` (single shared
    definition, so the speculative window stays bitwise-aligned with it).
    """
    logits, cache_k, cache_v = _slot_window_forward(
        stacked, wte, wpe, fnw, fnb, tok[:, None], cache_k, cache_v, pos,
        num_heads=num_heads, active=active)
    return logits[:, 0], cache_k, cache_v


def _chunk_prefill_block(lp, h, ck, cv, slot, start, *, num_heads, epsilon=1e-5):
    """One decoder block over a CHUNK of one slot's prompt (chunked prefill).

    ``h`` [1, C, d] holds C consecutive prompt tokens for batch slot
    ``slot``; ``ck``/``cv`` [B, H, S, dh] are one layer of the engine's big
    cache. K/V for the chunk are written in place at ``(slot, start)`` and
    attention reads the slot's WHOLE cache row, masked to each row's own
    prefix — so the chunk attends to everything earlier chunks (or a
    prefix-cache insert) already wrote. One compiled program serves every
    chunk of every prompt at every depth; same per-row math as
    :func:`_cache_block`, so tokens stay bitwise equal to the bucketed
    prefill path (masked lanes contribute exact zeros).
    """
    (n1w, n1b, qkvw, qkvb, ow, ob, n2w, n2b, f1w, f1b, f2w, f2b), _ = lp

    def ln(v, w, bb):
        mean = jnp.mean(v, axis=-1, keepdims=True)
        var = jnp.var(v, axis=-1, keepdims=True)
        return (v - mean) / jnp.sqrt(var + epsilon) * w + bb

    _, s, d = h.shape
    raw = ck["q"] if isinstance(ck, dict) else ck
    H = raw.shape[1]
    S = raw.shape[2]
    hd = d // num_heads
    x1 = ln(h, n1w, n1b)
    qkv = (x1 @ qkvw + qkvb).reshape(1, s, 3, num_heads, hd)
    q = jnp.swapaxes(qkv[:, :, 0], 1, 2)  # [1, H, C, dh]
    k = jnp.swapaxes(qkv[:, :, 1], 1, 2)
    v = jnp.swapaxes(qkv[:, :, 2], 1, 2)
    ck = _kvc_update(ck, k, (slot, 0, start, 0))
    cv = _kvc_update(cv, v, (slot, 0, start, 0))
    rk = _kvc_read(_kvc_slice(ck, (slot, 0, 0, 0), (1, H, S, hd)), h.dtype)
    rv = _kvc_read(_kvc_slice(cv, (slot, 0, 0, 0), (1, H, S, hd)), h.dtype)
    scale = jnp.asarray(1.0 / (hd ** 0.5), q.dtype)
    scores = jnp.einsum("bhqd,bhkd->bhqk", q * scale, rk,
                        preferred_element_type=jnp.float32)
    q_pos = start + jax.lax.broadcasted_iota(jnp.int32, (s, S), 0)
    k_pos = jax.lax.broadcasted_iota(jnp.int32, (s, S), 1)
    scores = jnp.where((k_pos <= q_pos)[None, None], scores, -jnp.inf)
    p = jax.nn.softmax(scores, axis=-1).astype(rv.dtype)
    att = jnp.einsum("bhqk,bhkd->bhqd", p, rv, preferred_element_type=jnp.float32)
    att = jnp.swapaxes(att.astype(h.dtype), 1, 2).reshape(1, s, d)
    h = h + att @ ow + ob
    x2 = ln(h, n2w, n2b)
    y = jax.nn.gelu(x2 @ f1w + f1b, approximate=True)
    h = h + y @ f2w + f2b
    return h, ck, cv


def _chunk_prefill_forward(stacked, wte, wpe, fnw, fnb, ids, cache_k, cache_v,
                           slot, start, *, num_heads, last_row=None):
    """Trunk forward over one prompt chunk of one slot, directly against the
    engine's big [L, B, H, S, dh] cache. ``ids`` [1, C] (C fixed — long
    prompts run as a sequence of these dispatches, interleaved with decode);
    ``start`` is the chunk's first absolute position. With ``last_row`` a
    traced row index, also returns the final-norm logits of that row (the
    sampling row of the prompt's last chunk); intermediate chunks skip the
    logits work entirely. Returns (logits|None, cache_k, cache_v).
    """
    params, idx = stacked
    num_layers = params[0].shape[0]
    s = ids.shape[1]
    pos = start + jnp.arange(s, dtype=jnp.int32)
    h = jnp.take(wte, ids, axis=0) + jnp.take(wpe, pos, axis=0)[None]
    h = h.astype(wte.dtype)
    new_k, new_v = [], []
    for i in range(num_layers):
        lp = (tuple(p[i] for p in params), idx[i])
        h, ck, cv = _chunk_prefill_block(lp, h, _kv_layer(cache_k, i), _kv_layer(cache_v, i),
                                         slot, start, num_heads=num_heads)
        new_k.append(ck)  # noqa: PTA104 (static unroll, host loop bound)
        new_v.append(cv)  # noqa: PTA104 (static unroll, host loop bound)
    cache_k = _kv_stack(new_k)
    cache_v = _kv_stack(new_v)
    if last_row is None:
        return None, cache_k, cache_v
    hl = jax.lax.dynamic_slice(h, (0, last_row, 0), (1, 1, h.shape[2]))
    mean = jnp.mean(hl, axis=-1, keepdims=True)
    var = jnp.var(hl, axis=-1, keepdims=True)
    hl = (hl - mean) / jnp.sqrt(var + 1e-5) * fnw + fnb
    logits = jnp.einsum("bsd,vd->bsv", hl, wte)[:, 0]  # [1, V]
    return logits, cache_k, cache_v


def _filtered_logits(logits, temperature, top_k, top_p):
    """Temperature/top-k/top-p filtered f32 logits over [b, V] — the exact
    transform :func:`_select_token` samples from, factored out so
    speculative decoding's residual-resampling acceptance test works on the
    SAME filtered distribution the sequential sampler would draw from."""
    logits = logits.astype(jnp.float32) / jnp.maximum(temperature, 1e-6)
    if top_k and top_k > 0:
        k_eff = min(int(top_k), logits.shape[-1])  # top_k > vocab = keep all
        kth = jnp.sort(logits, axis=-1)[..., -k_eff][..., None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p < 1.0:
        sl = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sl, axis=-1)
        keep = jnp.cumsum(probs, axis=-1) - probs < top_p  # always keep top-1
        threshold = jnp.min(jnp.where(keep, sl, jnp.inf), axis=-1, keepdims=True)
        logits = jnp.where(logits < threshold, -jnp.inf, logits)
    return logits


def _select_token(logits, key, do_sample, temperature, top_k, top_p):
    """Greedy or temperature/top-k/top-p sampling over [b, V] logits."""
    if not do_sample:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = _filtered_logits(logits, temperature, top_k, top_p)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


def _select_token_rows(logits, keys, do_sample, temperature, top_k, top_p):
    """Per-row variant of :func:`_select_token` for slot-masked sampling:
    ``keys`` carries one PRNG key PER batch slot so a request's sample stream
    depends only on its own (seed, position) — never on which slot it landed
    in or what its batch neighbours are doing (no cross-request leakage)."""
    if not do_sample:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    pick = lambda lg, k: _select_token(lg[None], k, True, temperature, top_k, top_p)[0]  # noqa: E731
    return jax.vmap(pick)(logits, keys)


@functools.partial(jax.jit, static_argnames=("num_heads", "num_layers", "head_dim", "max_new", "do_sample", "temperature", "top_k", "top_p", "eos", "mesh"))
def _generate_jit(params, ids, key, *, num_heads, num_layers, head_dim, max_new, do_sample, temperature, top_k, top_p, eos, mesh=None):
    """Prefill + lax.scan single-token decode loop, one XLA computation."""
    stacked_tree, wte, wpe, fnw, fnb = params
    b, s0 = ids.shape
    S = s0 + max_new
    dt = wte.dtype
    cache_k = jnp.zeros((num_layers, b, num_heads, S, head_dim), dt)
    cache_v = jnp.zeros((num_layers, b, num_heads, S, head_dim), dt)
    if mesh is not None and mesh.shape.get("mp", 1) > 1:
        from jax.sharding import NamedSharding

        csh = NamedSharding(mesh, P(None, None, "mp"))
        cache_k = jax.lax.with_sharding_constraint(cache_k, csh)
        cache_v = jax.lax.with_sharding_constraint(cache_v, csh)
    logits, cache_k, cache_v = _cache_forward(
        stacked_tree, wte, wpe, fnw, fnb, ids, cache_k, cache_v, jnp.int32(0), num_heads=num_heads, mesh=mesh)
    first = _select_token(logits[:, -1].astype(jnp.float32), key, do_sample, temperature, top_k, top_p)
    done0 = jnp.zeros((b,), bool) if eos is None else (first == eos)

    def step(carry, i):
        tok, ck, cv, done, key = carry
        key, sub = jax.random.split(key)
        lg, ck, cv = _cache_forward(
            stacked_tree, wte, wpe, fnw, fnb, tok[:, None], ck, cv, s0 + i, num_heads=num_heads, mesh=mesh)
        nxt = _select_token(lg[:, -1].astype(jnp.float32), sub, do_sample, temperature, top_k, top_p)
        if eos is not None:
            nxt = jnp.where(done, jnp.int32(eos), nxt)
            done = done | (nxt == eos)
        return (nxt, ck, cv, done, key), nxt

    (_, _, _, _, _), rest = jax.lax.scan(step, (first, cache_k, cache_v, done0, key), jnp.arange(max_new - 1, dtype=jnp.int32))
    return jnp.concatenate([ids, first[:, None], rest.T.astype(jnp.int32)], axis=1)


class GPTEmbeddings(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.word_embeddings = VocabParallelEmbedding(cfg.vocab_size, cfg.hidden_size)
        self.position_embeddings = nn.Embedding(cfg.max_seq_len, cfg.hidden_size, weight_attr=I.Normal(0.0, cfg.initializer_range))
        self.dropout = nn.Dropout(cfg.dropout)

    def forward(self, input_ids, position_ids=None):
        if position_ids is None:
            from ..tensor.creation import arange

            position_ids = arange(0, input_ids.shape[1], dtype="int32")
        return self.dropout(self.word_embeddings(input_ids) + self.position_embeddings(position_ids))


class GPTModel(nn.Layer):
    """Embedding + N decoder blocks + final LN → hidden states."""

    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        self.embeddings = GPTEmbeddings(cfg)
        if cfg.stacked:
            self.layers = GPTBlockStack(cfg)  # noqa: PTA104 (host-side, never traced)
        else:
            self.layers = nn.LayerList([  # noqa: PTA104 (host-side, never traced)
                GPTBlock(cfg, use_moe=bool(cfg.moe_num_experts)
                         and (i + 1) % cfg.moe_every == 0)
                for i in range(cfg.num_layers)])
        self.final_norm = nn.LayerNorm(cfg.hidden_size)

    def forward(self, input_ids, position_ids=None):
        h = self.embeddings(input_ids, position_ids)
        if isinstance(self.layers, GPTBlockStack):
            h = self.layers(h)
        else:
            for blk in self.layers:
                h = self._block_maybe_remat(blk, h)
        return self.final_norm(h)

    def _block_maybe_remat(self, blk, h):
        # honor cfg.recompute on the per-layer trunk too (the stacked path
        # remats inside GPTBlockStack); granularity maps as in _stack_forward
        if not self.cfg.recompute:
            return blk(h)
        from ..distributed.recompute import recompute as _rc

        policy = ("dots_saveable" if self.cfg.recompute_granularity == "selective"
                  else "nothing_saveable")
        return _rc(blk, h, policy=policy)

    @property
    def moe_aux_loss(self):
        """Sum of the MoE gates' load-balancing losses from the last
        forward (GPT-MoE blocks only); add `model.moe_aux_loss * coef` to
        the training loss (GShard aux objective)."""
        total = None
        if not isinstance(self.layers, GPTBlockStack):
            for blk in self.layers:
                if getattr(blk, "moe", None) is not None:
                    total = blk.moe.aux_loss if total is None else total + blk.moe.aux_loss
        return total

    # per-layer GPTBlock param path <-> stacked GPTBlockStack param name
    _PER_LAYER_TO_STACKED = {
        "norm1.weight": "norm1_w", "norm1.bias": "norm1_b",
        "attn.qkv_proj.weight": "qkv_w", "attn.qkv_proj.bias": "qkv_b",
        "attn.out_proj.weight": "out_w", "attn.out_proj.bias": "out_b",
        "norm2.weight": "norm2_w", "norm2.bias": "norm2_b",
        "ffn1.weight": "ffn1_w", "ffn1.bias": "ffn1_b",
        "ffn2.weight": "ffn2_w", "ffn2.bias": "ffn2_b",
    }

    def set_state_dict(self, state_dict, use_structured_name=True):
        """Accepts both trunk layouts: ``layers.N.attn.qkv_proj.weight``
        (per-layer GPTBlock checkpoints, incl. ones converted from the
        reference's auto_parallel_gpt_model naming) and ``layers.qkv_w``
        ([L, ...]-stacked). Mismatched layouts are converted by
        stacking/unstacking along the layer axis."""
        import re

        import numpy as np

        from ..framework.core import Tensor as _T

        def val(v):
            return np.asarray(v._value) if isinstance(v, _T) else np.asarray(v)

        L = self.cfg.num_layers
        if isinstance(self.layers, GPTBlockStack):
            groups, rest = {}, {}
            for k, v in state_dict.items():  # noqa: PTA102 (host-side, never traced)
                m = re.match(r"layers\.(\d+)\.(.+)$", k)
                if m and m.group(2) in self._PER_LAYER_TO_STACKED:
                    groups.setdefault(self._PER_LAYER_TO_STACKED[m.group(2)], {})[int(m.group(1))] = v  # noqa: PTA104 (host-side, never traced)
                else:
                    rest[k] = v  # noqa: PTA104 (host-side, never traced)
            if groups:
                state_dict = rest
                inv = {v: k for k, v in self._PER_LAYER_TO_STACKED.items()}
                for stacked_name, per in groups.items():  # noqa: PTA102 (host-side, never traced)
                    if len(per) == L and sorted(per) == list(range(L)):
                        state_dict[f"layers.{stacked_name}"] = np.stack([val(per[i]) for i in range(L)])  # noqa: PTA104 (host-side, never traced)
                    else:
                        # incomplete group: restore the original keys so the
                        # base class reports them as unexpected (no silent drop)
                        for i, v in per.items():  # noqa: PTA102 (host-side, never traced)
                            state_dict[f"layers.{i}.{inv[stacked_name]}"] = v  # noqa: PTA104 (host-side, never traced)
        else:
            inv = {v: k for k, v in self._PER_LAYER_TO_STACKED.items()}
            converted = {}
            for k, v in state_dict.items():  # noqa: PTA102 (host-side, never traced)
                m = re.match(r"layers\.([a-z0-9_]+)$", k)
                if m and m.group(1) in inv:
                    arr = val(v)
                    if arr.shape[0] != L:
                        converted[k] = v  # wrong layer count: surface as unexpected  # noqa: PTA104 (host-side, never traced)
                        continue
                    for i in range(L):
                        converted[f"layers.{i}.{inv[m.group(1)]}"] = arr[i]  # noqa: PTA104 (host-side, never traced)
                else:
                    converted[k] = v  # noqa: PTA104 (host-side, never traced)
            state_dict = converted
        return super().set_state_dict(state_dict, use_structured_name)


class GPTForPretraining(nn.Layer):
    """LM head tied to the (vocab-sharded) word embedding — logits over 'mp'."""

    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.gpt = GPTModel(cfg)

    def forward(self, input_ids, position_ids=None):
        h = self.gpt(input_ids, position_ids)
        from ..tensor.linalg import matmul

        # tied head: h @ wte^T; vocab axis stays mp-sharded for the
        # vocab-parallel loss (c_softmax_with_cross_entropy parity)
        logits = matmul(h, self.gpt.embeddings.word_embeddings.weight, transpose_y=True)
        if self.gpt.cfg.moe_num_experts:
            # GPT-MoE: the GShard balancing loss rides the outputs so the
            # criterion (and any compiled step) sees it — no side channel
            return logits, self.gpt.moe_aux_loss
        return logits

    def generate(self, input_ids, max_new_tokens=32, do_sample=False, temperature=1.0, top_k=0, top_p=1.0, seed=0, eos_token_id=None):
        """Autoregressive decoding over a fixed-size KV cache, compiled as
        one XLA computation (prefill + lax.scan token loop).

        Parity: the reference decodes through gen_cache/Cache plumbing
        (python/paddle/nn/layer/transformer.py:284) or the fused decoder
        (fused_multi_transformer_op.cu); here the cache has a static
        [L, b, s0+max_new, H, dh] shape so the whole loop jits once.
        Greedy by default; ``do_sample`` enables temperature / top-k /
        top-p sampling. Returns [b, s0 + max_new_tokens] token ids.
        """
        from ..framework.core import _wrap_value, unwrap
        from ..tensor._helpers import ensure_tensor

        cfg = self.gpt.cfg
        if not isinstance(self.gpt.layers, GPTBlockStack):
            raise NotImplementedError("generate() requires the stacked trunk (GPTConfig(stacked=True))")
        ids = unwrap(ensure_tensor(input_ids)).astype(jnp.int32)
        if ids.ndim == 1:
            ids = ids[None]
        if ids.shape[1] + max_new_tokens > cfg.max_seq_len:
            raise ValueError(f"prompt {ids.shape[1]} + max_new_tokens {max_new_tokens} exceeds max_seq_len {cfg.max_seq_len}")
        params = self._decode_params()
        # tensor-parallel decode: when the fleet mesh has mp>1 (and no pp),
        # place the trunk stack per its dist_spec annotations and thread the
        # mesh so caches/logits stay mp-sharded through the token loop
        from ..distributed.fleet import fleet as _fleet

        mesh = None
        if _fleet._hcg is not None:
            fm = _fleet.mesh
            if fm is not None and fm.shape.get("mp", 1) > 1 and fm.shape.get("pp", 1) == 1:
                from jax.sharding import NamedSharding

                mesh = fm
                stack = self.gpt.layers
                specs = [getattr(getattr(stack, n), "dist_spec", None) for n in stack._order]
                placed = tuple(
                    jax.device_put(arr, NamedSharding(mesh, sp if sp is not None else P()))
                    for arr, sp in zip(params[0][0], specs))
                wte_spec = getattr(self.gpt.embeddings.word_embeddings.weight, "dist_spec", None)
                params = (
                    (placed, params[0][1]),
                    jax.device_put(params[1], NamedSharding(mesh, wte_spec if wte_spec is not None else P())),
                    jax.device_put(params[2], NamedSharding(mesh, P())),
                    jax.device_put(params[3], NamedSharding(mesh, P())),
                    jax.device_put(params[4], NamedSharding(mesh, P())),
                )
        out = _generate_jit(
            params, ids, jax.random.key(seed),
            num_heads=cfg.num_heads, num_layers=cfg.num_layers,
            head_dim=cfg.hidden_size // cfg.num_heads,
            max_new=int(max_new_tokens), do_sample=bool(do_sample),
            temperature=float(temperature), top_k=int(top_k), top_p=float(top_p),
            eos=None if eos_token_id is None else int(eos_token_id), mesh=mesh)
        return _wrap_value(out)

    def _decode_params(self):
        """The decode-loop parameter pack (single definition shared by
        generate() and export_decoder — layout matches GPTBlockStack._order)."""
        from ..framework.core import unwrap

        cfg = self.gpt.cfg
        stack = self.gpt.layers
        stacked = (tuple(unwrap(getattr(stack, n)) for n in stack._order),
                   jnp.arange(cfg.num_layers, dtype=jnp.int32))
        return (
            stacked,
            unwrap(self.gpt.embeddings.word_embeddings.weight),
            unwrap(self.gpt.embeddings.position_embeddings.weight),
            unwrap(self.gpt.final_norm.weight),
            unwrap(self.gpt.final_norm.bias),
        )

    def export_decoder(self, path, prompt_len, max_new_tokens=32, do_sample=False,
                       temperature=1.0, top_k=0, top_p=1.0, eos_token_id=None):
        """Export the whole decode loop (prefill + KV-cache token scan +
        sampling) as a deployable StableHLO artifact servable by
        ``paddle.inference.create_predictor``.

        Parity: the reference deploys decoding through the fused decoder op
        inside an inference program (fused_multi_transformer_op.cu consumed
        by AnalysisPredictor); here the artifact IS the compiled loop. The
        batch dimension is symbolic; ``prompt_len`` is fixed at export (the
        KV cache is static-shape). Feeds: ids [b, prompt_len] int32, seed []
        int32. Fetch: tokens [b, prompt_len + max_new_tokens] int32.
        """
        import pickle
        from pathlib import Path

        cfg = self.gpt.cfg
        if not isinstance(self.gpt.layers, GPTBlockStack):
            raise NotImplementedError("export_decoder requires the stacked trunk")
        if prompt_len + max_new_tokens > cfg.max_seq_len:
            raise ValueError("prompt_len + max_new_tokens exceeds max_seq_len")
        Path(str(path)).parent.mkdir(parents=True, exist_ok=True)
        params = self._decode_params()

        def decode(ids, seed):
            return _generate_jit(
                params, ids, jax.random.key(seed),
                num_heads=cfg.num_heads, num_layers=cfg.num_layers,
                head_dim=cfg.hidden_size // cfg.num_heads,
                max_new=int(max_new_tokens), do_sample=bool(do_sample),
                temperature=float(temperature), top_k=int(top_k),
                top_p=float(top_p),
                eos=None if eos_token_id is None else int(eos_token_id))

        scope = jax.export.SymbolicScope()
        b = jax.export.symbolic_shape("b", scope=scope)[0]
        exported = jax.export.export(jax.jit(decode))(
            jax.ShapeDtypeStruct((b, int(prompt_len)), jnp.int32),
            jax.ShapeDtypeStruct((), jnp.int32))
        Path(str(path) + ".pdmodel").write_bytes(exported.serialize())
        meta = {
            "feed_names": ["ids", "seed"],
            "fetch_names": ["tokens"],
            "feed_shapes": [[-1, int(prompt_len)], []],
            "feed_dtypes": ["int32", "int32"],
            "decoder": {"prompt_len": int(prompt_len), "max_new_tokens": int(max_new_tokens)},
            "format": "stablehlo",
            "producer": f"paddle_tpu/jax {jax.__version__}",
        }
        Path(str(path) + ".pdiparams").write_bytes(pickle.dumps(meta))
        return str(path)


class GPTPretrainingCriterion(nn.Layer):
    """Next-token cross entropy with optional loss mask, mean over tokens.
    For GPT-MoE outputs ``(logits, aux)`` the GShard balancing loss is added
    with ``moe_aux_coef`` (reference MoE training objective)."""

    def __init__(self, moe_aux_coef=0.01):
        super().__init__()
        self.parallel_ce = ParallelCrossEntropy()
        self.moe_aux_coef = moe_aux_coef

    def forward(self, logits, labels, loss_mask=None):
        from ..tensor.math import mean, multiply, sum as t_sum
        from ..tensor.manipulation import reshape

        aux = None
        if isinstance(logits, (tuple, list)):
            logits, aux = logits
        per_tok = self.parallel_ce(logits, labels)
        if aux is not None:
            if loss_mask is not None:
                m = reshape(loss_mask, per_tok.shape)
                return t_sum(multiply(per_tok, m)) / t_sum(m) + aux * self.moe_aux_coef
            return mean(per_tok) + aux * self.moe_aux_coef
        if loss_mask is not None:
            m = reshape(loss_mask, per_tok.shape)
            return t_sum(multiply(per_tok, m)) / t_sum(m)
        return mean(per_tok)
