"""GPT decoder-only language model — the flagship pretraining model.

Parity: the reference ships GPT as its auto-parallel/fleet workhorse
(python/paddle/fluid/tests/unittests/auto_parallel_gpt_model.py;
ppfleetx-style GPT built from paddle.nn.TransformerDecoder + the TP layers in
fleet/meta_parallel/parallel_layers/mp_layers.py:30,95,171,251).

TPU-first: every parallelism is a sharding annotation, not a wrapper —
  * vocab over 'mp' (VocabParallelEmbedding),
  * attention heads + ffn hidden over 'mp' (Column/RowParallelLinear),
  * batch over 'dp'×'sdp' (fleet.distributed_step input sharding),
  * sequence over 'sep' for long context (ring/Ulysses attention in
    distributed/ring_attention.py can replace the core here),
  * layers stackable over 'pp' via distributed/pipeline.spmd_pipeline.
The attention core dispatches to the Pallas flash kernel on TPU
(ops/flash_attention.py), replacing fused_attention_op.cu /
fused_multi_transformer_op.cu.
"""
from __future__ import annotations

from .. import nn
from ..distributed.mp_layers import (
    ColumnParallelLinear,
    ParallelCrossEntropy,
    RowParallelLinear,
    VocabParallelEmbedding,
)
from ..nn import functional as F
from ..nn import initializer as I
from ..tensor import manipulation as M


class GPTConfig:
    """Hyperparameters. ``gpt3_1p3b()`` is the BASELINE.json config #4 model."""

    def __init__(
        self,
        vocab_size=50304,
        hidden_size=768,
        num_layers=12,
        num_heads=12,
        ffn_hidden_size=None,
        max_seq_len=1024,
        dropout=0.0,
        attn_dropout=0.0,
        initializer_range=0.02,
        use_flash=True,
    ):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.ffn_hidden_size = ffn_hidden_size or 4 * hidden_size
        self.max_seq_len = max_seq_len
        self.dropout = dropout
        self.attn_dropout = attn_dropout
        self.initializer_range = initializer_range
        self.use_flash = use_flash

    @staticmethod
    def gpt3_1p3b(**kw):
        cfg = dict(vocab_size=50304, hidden_size=2048, num_layers=24, num_heads=16, max_seq_len=2048)
        cfg.update(kw)
        return GPTConfig(**cfg)

    @staticmethod
    def tiny(**kw):
        cfg = dict(vocab_size=512, hidden_size=64, num_layers=2, num_heads=4, max_seq_len=128)
        cfg.update(kw)
        return GPTConfig(**cfg)


class GPTAttention(nn.Layer):
    """Causal self-attention, heads sharded over 'mp' via column/row linears."""

    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.num_heads = cfg.num_heads
        self.head_dim = cfg.hidden_size // cfg.num_heads
        assert self.head_dim * cfg.num_heads == cfg.hidden_size
        init = I.Normal(0.0, cfg.initializer_range)
        self.qkv_proj = ColumnParallelLinear(cfg.hidden_size, 3 * cfg.hidden_size, weight_attr=init, gather_output=False)
        self.out_proj = RowParallelLinear(cfg.hidden_size, cfg.hidden_size, weight_attr=init, input_is_parallel=True)
        self.attn_dropout = cfg.attn_dropout

    def forward(self, x):
        b, s = x.shape[0], x.shape[1]
        qkv = self.qkv_proj(x)
        qkv = M.reshape(qkv, [b, s, 3, self.num_heads, self.head_dim])
        q, k, v = (M.squeeze(t, 2) for t in M.split(qkv, 3, axis=2))
        out = F.scaled_dot_product_attention(q, k, v, is_causal=True, dropout_p=self.attn_dropout, training=self.training)
        out = M.reshape(out, [b, s, self.num_heads * self.head_dim])
        return self.out_proj(out)


class GPTBlock(nn.Layer):
    """Pre-LN decoder block (attn + gelu MLP), mp-sharded."""

    def __init__(self, cfg: GPTConfig):
        super().__init__()
        init = I.Normal(0.0, cfg.initializer_range)
        self.norm1 = nn.LayerNorm(cfg.hidden_size)
        self.attn = GPTAttention(cfg)
        self.norm2 = nn.LayerNorm(cfg.hidden_size)
        self.ffn1 = ColumnParallelLinear(cfg.hidden_size, cfg.ffn_hidden_size, weight_attr=init, gather_output=False)
        self.ffn2 = RowParallelLinear(cfg.ffn_hidden_size, cfg.hidden_size, weight_attr=init, input_is_parallel=True)
        self.dropout = nn.Dropout(cfg.dropout)

    def forward(self, x):
        x = x + self.dropout(self.attn(self.norm1(x)))
        x = x + self.dropout(self.ffn2(F.gelu(self.ffn1(self.norm2(x)), approximate=True)))
        return x


class GPTEmbeddings(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.word_embeddings = VocabParallelEmbedding(cfg.vocab_size, cfg.hidden_size)
        self.position_embeddings = nn.Embedding(cfg.max_seq_len, cfg.hidden_size, weight_attr=I.Normal(0.0, cfg.initializer_range))
        self.dropout = nn.Dropout(cfg.dropout)

    def forward(self, input_ids, position_ids=None):
        if position_ids is None:
            from ..tensor.creation import arange

            position_ids = arange(0, input_ids.shape[1], dtype="int32")
        return self.dropout(self.word_embeddings(input_ids) + self.position_embeddings(position_ids))


class GPTModel(nn.Layer):
    """Embedding + N decoder blocks + final LN → hidden states."""

    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        self.embeddings = GPTEmbeddings(cfg)
        self.layers = nn.LayerList([GPTBlock(cfg) for _ in range(cfg.num_layers)])
        self.final_norm = nn.LayerNorm(cfg.hidden_size)

    def forward(self, input_ids, position_ids=None):
        h = self.embeddings(input_ids, position_ids)
        for blk in self.layers:
            h = blk(h)
        return self.final_norm(h)


class GPTForPretraining(nn.Layer):
    """LM head tied to the (vocab-sharded) word embedding — logits over 'mp'."""

    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.gpt = GPTModel(cfg)

    def forward(self, input_ids, position_ids=None):
        h = self.gpt(input_ids, position_ids)
        from ..tensor.linalg import matmul

        # tied head: h @ wte^T; vocab axis stays mp-sharded for the
        # vocab-parallel loss (c_softmax_with_cross_entropy parity)
        return matmul(h, self.gpt.embeddings.word_embeddings.weight, transpose_y=True)


class GPTPretrainingCriterion(nn.Layer):
    """Next-token cross entropy with optional loss mask, mean over tokens."""

    def __init__(self):
        super().__init__()
        self.parallel_ce = ParallelCrossEntropy()

    def forward(self, logits, labels, loss_mask=None):
        from ..tensor.math import mean, multiply, sum as t_sum
        from ..tensor.manipulation import reshape

        per_tok = self.parallel_ce(logits, labels)
        if loss_mask is not None:
            m = reshape(loss_mask, per_tok.shape)
            return t_sum(multiply(per_tok, m)) / t_sum(m)
        return mean(per_tok)
