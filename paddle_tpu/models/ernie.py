"""ERNIE model family — the hybrid-parallel workhorse of BASELINE config #5.

Parity anchors: the ERNIE encoder exercised throughout the reference's
distributed tests (e.g. python/paddle/fluid/tests/unittests/
static_model_parallel_fused_attention.py and the fleet hybrid suites train
ERNIE-shaped transformers): a BERT-style bidirectional encoder with an
extra TASK-TYPE embedding table, pretrained with masked-LM plus
sentence-order prediction. Architecture reuses the mp-annotated BERT
blocks (models/bert.py) — same TPU-first sharding story: vocab-parallel
embeddings, column/row-parallel attention/FFN, fused flash path.
"""
from __future__ import annotations

from .. import nn
from ..nn import functional as F
from ..nn import initializer as I
from .bert import BertConfig, BertEmbeddings, BertLayer, BertPretrainingCriterion


class ErnieConfig(BertConfig):
    def __init__(self, task_type_vocab_size=3, use_task_id=True, **kw):
        kw.setdefault("vocab_size", 18000)  # ERNIE 1.0 zh vocab
        super().__init__(**kw)
        self.task_type_vocab_size = task_type_vocab_size
        self.use_task_id = use_task_id

    # base/large/tiny inherit from BertConfig's classmethod factories

    @classmethod
    def ernie3_xbase(cls, **kw):
        """ERNIE 3.0 hybrid-benchmark shape (BASELINE config #5 dense
        trunk: h=3072, L=12)."""
        cfg = dict(hidden_size=3072, num_layers=12, num_heads=24, max_seq_len=512)
        cfg.update(kw)
        return cls(**cfg)


class ErnieEmbeddings(BertEmbeddings):
    """BERT embeddings + the ERNIE task-type table."""

    def __init__(self, cfg: ErnieConfig):
        super().__init__(cfg)
        self.task_type_embeddings = None
        if cfg.use_task_id:
            self.task_type_embeddings = nn.Embedding(  # noqa: PTA104 (host-side, never traced)
                cfg.task_type_vocab_size, cfg.hidden_size,
                weight_attr=I.Normal(0.0, cfg.initializer_range))

    def forward(self, input_ids, token_type_ids=None, position_ids=None, task_type_ids=None):
        from ..tensor.creation import arange, zeros_like

        if position_ids is None:
            position_ids = arange(0, input_ids.shape[1], dtype="int32")
        if token_type_ids is None:
            token_type_ids = zeros_like(input_ids)
        h = (self.word_embeddings(input_ids) + self.position_embeddings(position_ids)
             + self.token_type_embeddings(token_type_ids))
        if self.task_type_embeddings is not None:
            if task_type_ids is None:
                task_type_ids = zeros_like(input_ids)
            h = h + self.task_type_embeddings(task_type_ids)
        return self.dropout(self.norm(h))


class ErnieModel(nn.Layer):
    def __init__(self, cfg: ErnieConfig):
        super().__init__()
        self.cfg = cfg
        self.embeddings = ErnieEmbeddings(cfg)
        self.layers = nn.LayerList([BertLayer(cfg) for _ in range(cfg.num_layers)])
        self.pooler = nn.Linear(cfg.hidden_size, cfg.hidden_size)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attn_mask=None, task_type_ids=None):
        from ..tensor.math import tanh

        h = self.embeddings(input_ids, token_type_ids, position_ids, task_type_ids)
        for layer in self.layers:
            h = layer(h, attn_mask)
        pooled = tanh(self.pooler(h[:, 0]))
        return h, pooled


class ErnieForPretraining(nn.Layer):
    """Masked-LM head (tied decoder) + sentence-order-prediction head."""

    def __init__(self, cfg: ErnieConfig):
        super().__init__()
        self.ernie = ErnieModel(cfg)
        self.transform = nn.Linear(cfg.hidden_size, cfg.hidden_size)
        self.transform_norm = nn.LayerNorm(cfg.hidden_size)
        self.sop = nn.Linear(cfg.hidden_size, 2)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attn_mask=None, task_type_ids=None):
        from ..tensor.linalg import matmul

        h, pooled = self.ernie(input_ids, token_type_ids, position_ids, attn_mask, task_type_ids)
        h = self.transform_norm(F.gelu(self.transform(h), approximate=True))
        mlm_logits = matmul(h, self.ernie.embeddings.word_embeddings.weight, transpose_y=True)
        sop_logits = self.sop(pooled)
        return mlm_logits, sop_logits


class ErniePretrainingCriterion(BertPretrainingCriterion):
    """MLM CE + SOP CE — same structure as the BERT criterion (the SOP
    target replaces NSP)."""
