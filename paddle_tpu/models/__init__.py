from .lenet import LeNet  # noqa: F401
from .gpt import (  # noqa: F401
    GPTConfig,
    GPTModel,
    GPTForPretraining,
    GPTPretrainingCriterion,
)
