from .lenet import LeNet  # noqa: F401
from .bert import (  # noqa: F401
    BertConfig,
    BertModel,
    BertForPretraining,
    BertPretrainingCriterion,
)
from .ernie import (  # noqa: F401
    ErnieConfig,
    ErnieForPretraining,
    ErnieModel,
    ErniePretrainingCriterion,
)
from .gpt import (  # noqa: F401
    GPTConfig,
    GPTModel,
    GPTForPretraining,
    GPTPretrainingCriterion,
)
from .dlrm import (  # noqa: F401
    DLRM,
    DLRMConfig,
    DLRMCriterion,
)
