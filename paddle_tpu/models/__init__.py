from .lenet import LeNet  # noqa: F401
