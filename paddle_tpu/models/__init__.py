from .lenet import LeNet  # noqa: F401
from .bert import (  # noqa: F401
    BertConfig,
    BertModel,
    BertForPretraining,
    BertPretrainingCriterion,
)
from .gpt import (  # noqa: F401
    GPTConfig,
    GPTModel,
    GPTForPretraining,
    GPTPretrainingCriterion,
)
