"""DLRM-style recommender model over mesh-sharded embedding tables.

The recsys workload SURVEY §7 scopes for the TPU port: a dense bottom MLP,
N sparse-feature embedding bags served by ONE fused
:class:`~paddle_tpu.distributed.embedding.ShardedEmbedding` table (the
per-feature tables concatenate row-wise with static offsets — one
``all_to_all`` exchange per step instead of N), the pairwise dot-product
feature interaction, and a top MLP ending in a single logit. Training runs
through the ordinary ``jit.TrainStep`` path — ``run_steps`` keeps the
K-step one-dispatch scan — with :class:`paddle_tpu.optimizer.RowSparseAdam`
supplying the per-step partial (touched-rows-only) embedding updates.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .. import nn
from ..distributed.embedding import ShardedEmbedding
from ..tensor._helpers import ensure_tensor, op


class DLRMConfig:
    """num_dense continuous features; one vocab size per sparse feature;
    mlp tuples are hidden widths (bottom ends at embedding_dim, top at 1)."""

    def __init__(self, num_dense=4, vocab_sizes=(64, 32, 128), embedding_dim=8,
                 bottom_mlp=(16,), top_mlp=(16,), axis="dp", capacity=None,
                 pad_multiple=8):
        self.num_dense = int(num_dense)
        self.vocab_sizes = tuple(int(v) for v in vocab_sizes)
        self.embedding_dim = int(embedding_dim)
        self.bottom_mlp = tuple(bottom_mlp)
        self.top_mlp = tuple(top_mlp)
        self.axis = axis
        self.capacity = capacity
        self.pad_multiple = int(pad_multiple)

    @property
    def num_sparse(self):
        return len(self.vocab_sizes)

    @property
    def total_vocab(self):
        return sum(self.vocab_sizes)

    @staticmethod
    def tiny():
        """CPU-test scale: 3 features, 224 fused rows, D=8."""
        return DLRMConfig()


def _mlp(sizes):
    pairs = zip(sizes[:-1], sizes[1:])
    layers = [l for i, o in pairs for l in (nn.Linear(i, o), nn.ReLU())]
    return nn.Sequential(*layers)


class DLRM(nn.Layer):
    """forward(dense [B, num_dense] f32, ids [B, F] int) -> logits [B, 1]."""

    def __init__(self, config: DLRMConfig, mesh=None):
        super().__init__()
        self.config = config
        d = config.embedding_dim
        self.bottom = _mlp((config.num_dense,) + config.bottom_mlp + (d,))
        self.embedding = ShardedEmbedding(
            config.total_vocab, d, axis=config.axis, mesh=mesh,
            capacity=config.capacity, pad_multiple=config.pad_multiple)
        f = config.num_sparse
        n_inter = (f + 1) * f // 2
        top_sizes = (d + n_inter,) + config.top_mlp
        hidden = [l for i, o in zip(top_sizes[:-1], top_sizes[1:])
                  for l in (nn.Linear(i, o), nn.ReLU())]
        self.top = nn.Sequential(*hidden, nn.Linear(top_sizes[-1], 1))
        # per-feature row offsets into the fused table (static host ints)
        self._offsets = tuple(int(x) for x in
                              np.cumsum((0,) + config.vocab_sizes[:-1]))

    def sparse_param_names(self):
        """The fused-table param keys, as ``TrainStep`` state / optimizer
        cores see them — the ``RowSparseAdam(sparse_params=...)`` input."""
        return ["embedding.weight"]

    def forward(self, dense, ids):
        offsets = self._offsets

        def shift(i):
            return i + jnp.asarray(offsets, i.dtype)[None, :]

        fused_ids = op(shift, ensure_tensor(ids), _name="dlrm_offsets")
        bot = self.bottom(dense)                  # [B, D]
        emb = self.embedding(fused_ids)           # [B, F, D]

        def interact(d, e):
            z = jnp.concatenate([d[:, None, :], e], axis=1)   # [B, F+1, D]
            zz = jnp.einsum("bfd,bgd->bfg", z, z)
            iu = jnp.triu_indices(z.shape[1], k=1)
            return zz[:, iu[0], iu[1]]                        # [B, (F+1)F/2]

        inter = op(interact, bot, emb, _name="dlrm_interact")

        def cat(a, b):
            return jnp.concatenate([a, b], axis=-1)

        feats = op(cat, bot, inter, _name="dlrm_concat")
        return self.top(feats)


class DLRMCriterion:
    """Binary cross-entropy with logits, mean over the batch (the CTR
    objective); numerically stable log1p(exp) form, reductions in f32."""

    def __call__(self, logits, labels):
        def fn(x, y):
            x = x.astype(jnp.float32).reshape(-1)
            y = y.astype(jnp.float32).reshape(-1)
            return jnp.mean(jnp.maximum(x, 0.0) - x * y
                            + jnp.log1p(jnp.exp(-jnp.abs(x))))

        return op(fn, ensure_tensor(logits), ensure_tensor(labels),
                  _name="dlrm_bce")
