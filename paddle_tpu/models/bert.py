"""BERT encoder family — BASELINE.json config #3 (BERT-base MLM, DP over ICI).

Parity: the reference exercises BERT through its transformer API
(python/paddle/nn/layer/transformer.py) and fleet DP; ERNIE-style models are
the same encoder with different pretraining data. TP sharding via the same
mp-annotated layers as GPT (distributed/mp_layers.py).
"""
from __future__ import annotations

from .. import nn
from ..distributed.mp_layers import (
    ColumnParallelLinear,
    ParallelCrossEntropy,
    RowParallelLinear,
    VocabParallelEmbedding,
)
from ..nn import functional as F
from ..nn import initializer as I
from ..tensor import manipulation as M


class BertConfig:
    def __init__(
        self,
        vocab_size=30522,
        hidden_size=768,
        num_layers=12,
        num_heads=12,
        ffn_hidden_size=None,
        max_seq_len=512,
        type_vocab_size=2,
        dropout=0.0,
        initializer_range=0.02,
    ):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.ffn_hidden_size = ffn_hidden_size or 4 * hidden_size
        self.max_seq_len = max_seq_len
        self.type_vocab_size = type_vocab_size
        self.dropout = dropout
        self.initializer_range = initializer_range

    # classmethods so subclasses (ErnieConfig) inherit the family shapes
    @classmethod
    def base(cls, **kw):
        return cls(**kw)

    @classmethod
    def large(cls, **kw):
        cfg = dict(hidden_size=1024, num_layers=24, num_heads=16)
        cfg.update(kw)
        return cls(**cfg)

    @classmethod
    def tiny(cls, **kw):
        cfg = dict(vocab_size=512, hidden_size=64, num_layers=2, num_heads=4, max_seq_len=128)
        cfg.update(kw)
        return cls(**cfg)


class BertSelfAttention(nn.Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.num_heads = cfg.num_heads
        self.head_dim = cfg.hidden_size // cfg.num_heads
        init = I.Normal(0.0, cfg.initializer_range)
        self.qkv_proj = ColumnParallelLinear(cfg.hidden_size, 3 * cfg.hidden_size, weight_attr=init, gather_output=False)
        self.out_proj = RowParallelLinear(cfg.hidden_size, cfg.hidden_size, weight_attr=init, input_is_parallel=True)

    def forward(self, x, attn_mask=None):
        b, s = x.shape[0], x.shape[1]
        qkv = M.reshape(self.qkv_proj(x), [b, s, 3, self.num_heads, self.head_dim])
        q, k, v = (M.squeeze(t, 2) for t in M.split(qkv, 3, axis=2))
        out = F.scaled_dot_product_attention(q, k, v, attn_mask=attn_mask)
        return self.out_proj(M.reshape(out, [b, s, self.num_heads * self.head_dim]))


class BertLayer(nn.Layer):
    """Post-LN encoder block (original BERT ordering)."""

    def __init__(self, cfg: BertConfig):
        super().__init__()
        init = I.Normal(0.0, cfg.initializer_range)
        self.attn = BertSelfAttention(cfg)
        self.norm1 = nn.LayerNorm(cfg.hidden_size)
        self.ffn1 = ColumnParallelLinear(cfg.hidden_size, cfg.ffn_hidden_size, weight_attr=init, gather_output=False)
        self.ffn2 = RowParallelLinear(cfg.ffn_hidden_size, cfg.hidden_size, weight_attr=init, input_is_parallel=True)
        self.norm2 = nn.LayerNorm(cfg.hidden_size)
        self.dropout = nn.Dropout(cfg.dropout)

    def forward(self, x, attn_mask=None):
        x = self.norm1(x + self.dropout(self.attn(x, attn_mask)))
        x = self.norm2(x + self.dropout(self.ffn2(F.gelu(self.ffn1(x), approximate=True))))
        return x


class BertEmbeddings(nn.Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        init = I.Normal(0.0, cfg.initializer_range)
        self.word_embeddings = VocabParallelEmbedding(cfg.vocab_size, cfg.hidden_size)
        self.position_embeddings = nn.Embedding(cfg.max_seq_len, cfg.hidden_size, weight_attr=init)
        self.token_type_embeddings = nn.Embedding(cfg.type_vocab_size, cfg.hidden_size, weight_attr=init)
        self.norm = nn.LayerNorm(cfg.hidden_size)
        self.dropout = nn.Dropout(cfg.dropout)

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        from ..tensor.creation import arange, zeros_like

        if position_ids is None:
            position_ids = arange(0, input_ids.shape[1], dtype="int32")
        if token_type_ids is None:
            token_type_ids = zeros_like(input_ids)
        h = self.word_embeddings(input_ids) + self.position_embeddings(position_ids) + self.token_type_embeddings(token_type_ids)
        return self.dropout(self.norm(h))


class BertModel(nn.Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.cfg = cfg
        self.embeddings = BertEmbeddings(cfg)
        self.layers = nn.LayerList([BertLayer(cfg) for _ in range(cfg.num_layers)])
        self.pooler = nn.Linear(cfg.hidden_size, cfg.hidden_size)

    def forward(self, input_ids, token_type_ids=None, position_ids=None, attn_mask=None):
        h = self.embeddings(input_ids, token_type_ids, position_ids)
        for layer in self.layers:
            h = layer(h, attn_mask)
        from ..tensor.math import tanh

        pooled = tanh(self.pooler(h[:, 0]))
        return h, pooled


class BertForPretraining(nn.Layer):
    """MLM head (tied decoder) + NSP head."""

    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.bert = BertModel(cfg)
        self.transform = nn.Linear(cfg.hidden_size, cfg.hidden_size)
        self.transform_norm = nn.LayerNorm(cfg.hidden_size)
        self.nsp = nn.Linear(cfg.hidden_size, 2)

    def forward(self, input_ids, token_type_ids=None, position_ids=None, attn_mask=None):
        h, pooled = self.bert(input_ids, token_type_ids, position_ids, attn_mask)
        from ..tensor.linalg import matmul

        h = self.transform_norm(F.gelu(self.transform(h), approximate=True))
        mlm_logits = matmul(h, self.bert.embeddings.word_embeddings.weight, transpose_y=True)
        nsp_logits = self.nsp(pooled)
        return mlm_logits, nsp_logits


class BertPretrainingCriterion(nn.Layer):
    """Masked-LM CE (ignore_index=-100 for unmasked) + NSP CE."""

    def __init__(self):
        super().__init__()
        self.mlm_ce = ParallelCrossEntropy(ignore_index=-100)

    def forward(self, mlm_logits, nsp_logits, mlm_labels, nsp_labels=None):
        from ..tensor.math import mean, sum as t_sum
        from ..tensor.logic import not_equal
        from ..tensor.manipulation import reshape

        per_tok = self.mlm_ce(mlm_logits, mlm_labels)
        mask = not_equal(mlm_labels, -100).astype("float32")
        mask = reshape(mask, per_tok.shape)
        denom = t_sum(mask) + 1e-6
        loss = t_sum(per_tok * mask) / denom
        if nsp_labels is not None:
            loss = loss + mean(F.cross_entropy(nsp_logits, nsp_labels, reduction="none"))
        return loss
