"""paddle_tpu.tensor — the tensor op namespace.

Parity: python/paddle/tensor/__init__.py, which also monkey-patches ~300
methods onto Tensor (reference: python/paddle/tensor/__init__.py tensor_method_func
list). Here the same patching wires methods + operator dunders.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, _wrap_value, primitive, unwrap
from . import creation, linalg, logic, manipulation, math, search, stat
from .creation import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .logic import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .search import *  # noqa: F401,F403
from .stat import *  # noqa: F401,F403

_MODULES = (creation, math, manipulation, logic, search, stat, linalg)


def _public_funcs():
    out = {}
    for m in _MODULES:
        for name in dir(m):
            if name.startswith("_"):
                continue
            fn = getattr(m, name)
            if callable(fn) and getattr(fn, "__module__", "").startswith("paddle_tpu.tensor"):
                out.setdefault(name, fn)
    return out


def _getitem(self, idx):
    def norm(i):
        if isinstance(i, Tensor):
            return i._value
        if isinstance(i, (list, np.ndarray)):
            return jnp.asarray(i)
        return i

    if isinstance(idx, tuple):
        jidx = tuple(norm(i) for i in idx)
    else:
        jidx = norm(idx)
    return primitive(lambda v: v[jidx], self, _name="getitem")


def _setitem(self, idx, value):
    from ..framework.static_trace import guard_inplace

    guard_inplace("Tensor.__setitem__", self, value if isinstance(value, Tensor) else None)

    def norm(i):
        if isinstance(i, Tensor):
            return i._value
        if isinstance(i, (list, np.ndarray)):
            return jnp.asarray(i)
        return i

    jidx = tuple(norm(i) for i in idx) if isinstance(idx, tuple) else norm(idx)
    val = unwrap(value)
    self._value = self._value.at[jidx].set(val)


def _binop(fn_name, reverse=False):
    def method(self, other):
        fn = getattr(math, fn_name)
        if reverse:
            return fn(other, self)
        return fn(self, other)

    return method


def _cmpop(fn_name):
    def method(self, other):
        return getattr(logic, fn_name)(self, other)

    return method


def monkey_patch_tensor():
    funcs = _public_funcs()
    skip = {"Tensor", "to_tensor"}
    for name, fn in funcs.items():
        if name in skip or hasattr(Tensor, name):
            continue
        setattr(Tensor, name, fn)

    Tensor.__add__ = _binop("add")
    Tensor.__radd__ = _binop("add", reverse=True)
    Tensor.__sub__ = _binop("subtract")
    Tensor.__rsub__ = _binop("subtract", reverse=True)
    Tensor.__mul__ = _binop("multiply")
    Tensor.__rmul__ = _binop("multiply", reverse=True)
    Tensor.__truediv__ = _binop("divide")
    Tensor.__rtruediv__ = _binop("divide", reverse=True)
    Tensor.__floordiv__ = _binop("floor_divide")
    Tensor.__mod__ = _binop("remainder")
    Tensor.__pow__ = _binop("pow")
    Tensor.__rpow__ = _binop("pow", reverse=True)
    Tensor.__matmul__ = _binop("matmul")
    Tensor.__neg__ = lambda self: math.neg(self)
    Tensor.__abs__ = lambda self: math.abs(self)
    Tensor.__eq__ = _cmpop("equal")
    Tensor.__ne__ = _cmpop("not_equal")
    Tensor.__lt__ = _cmpop("less_than")
    Tensor.__le__ = _cmpop("less_equal")
    Tensor.__gt__ = _cmpop("greater_than")
    Tensor.__ge__ = _cmpop("greater_equal")
    Tensor.__and__ = _cmpop("logical_and")
    Tensor.__or__ = _cmpop("logical_or")
    Tensor.__invert__ = lambda self: logic.logical_not(self)
    Tensor.__getitem__ = _getitem
    Tensor.__setitem__ = _setitem
    Tensor.T = property(lambda self: manipulation.t(self))
    Tensor.dim = lambda self: self.ndim
    Tensor.cpu = lambda self: self
    Tensor.cuda = lambda self: self
    Tensor.pin_memory = lambda self: self


monkey_patch_tensor()
