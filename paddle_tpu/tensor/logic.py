"""Comparison / logical ops (parity: python/paddle/tensor/logic.py)."""
from __future__ import annotations

import jax.numpy as jnp

from ._helpers import Tensor, ensure_tensor, op, unwrap, _wrap_value


def _cmp(fn, x, y, name=""):
    return op(fn, ensure_tensor(x), ensure_tensor(y), _name=name)


def equal(x, y, name=None):
    return _cmp(jnp.equal, x, y, "equal")


def not_equal(x, y, name=None):
    return _cmp(jnp.not_equal, x, y, "not_equal")


def less_than(x, y, name=None):
    return _cmp(jnp.less, x, y, "less_than")


def less_equal(x, y, name=None):
    return _cmp(jnp.less_equal, x, y, "less_equal")


def greater_than(x, y, name=None):
    return _cmp(jnp.greater, x, y, "greater_than")


def greater_equal(x, y, name=None):
    return _cmp(jnp.greater_equal, x, y, "greater_equal")


def logical_and(x, y, out=None, name=None):
    return _cmp(jnp.logical_and, x, y, "logical_and")


def logical_or(x, y, out=None, name=None):
    return _cmp(jnp.logical_or, x, y, "logical_or")


def logical_xor(x, y, out=None, name=None):
    return _cmp(jnp.logical_xor, x, y, "logical_xor")


def logical_not(x, out=None, name=None):
    return op(jnp.logical_not, ensure_tensor(x), _name="logical_not")


def bitwise_and(x, y, out=None, name=None):
    return _cmp(jnp.bitwise_and, x, y, "bitwise_and")


def bitwise_or(x, y, out=None, name=None):
    return _cmp(jnp.bitwise_or, x, y, "bitwise_or")


def bitwise_xor(x, y, out=None, name=None):
    return _cmp(jnp.bitwise_xor, x, y, "bitwise_xor")


def bitwise_not(x, out=None, name=None):
    return op(jnp.bitwise_not, ensure_tensor(x), _name="bitwise_not")


def equal_all(x, y, name=None):
    return _wrap_value(jnp.array_equal(unwrap(ensure_tensor(x)), unwrap(ensure_tensor(y))))


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return _wrap_value(jnp.allclose(unwrap(ensure_tensor(x)), unwrap(ensure_tensor(y)), rtol=rtol, atol=atol, equal_nan=equal_nan))


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return _cmp(lambda a, b: jnp.isclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan), x, y, "isclose")


def is_empty(x, name=None):
    return _wrap_value(jnp.asarray(ensure_tensor(x).size == 0))


def is_tensor(x):
    return isinstance(x, Tensor)


def is_floating_point(x):
    return jnp.issubdtype(ensure_tensor(x)._value.dtype, jnp.floating)


def is_integer(x):
    return jnp.issubdtype(ensure_tensor(x)._value.dtype, jnp.integer)


def is_complex(x):
    return jnp.issubdtype(ensure_tensor(x)._value.dtype, jnp.complexfloating)
