"""Linear algebra ops (parity: python/paddle/tensor/linalg.py).

matmul/bmm live in math.py (re-exported); decompositions map to
jax.numpy.linalg which XLA lowers natively (no cuSOLVER dynload needed —
reference: paddle/fluid/platform/dynload/cusolver.h).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ._helpers import ensure_tensor, op, unwrap, _wrap_value
from .math import matmul, bmm, dot, mv, mm, addmm, einsum  # noqa: F401  (re-export)


def norm(x, p="fro", axis=None, keepdim=False, name=None):
    x = ensure_tensor(x)

    def fn(v):
        if p == "fro" and axis is None:
            return jnp.sqrt(jnp.sum(v * v))
        if axis is None:
            flat = v.reshape(-1)
            return jnp.linalg.norm(flat, ord=p)
        ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
        return jnp.linalg.norm(v, ord=p if p != "fro" else "fro" if isinstance(ax, tuple) else 2, axis=ax, keepdims=keepdim)

    return op(fn, x, _name="norm")


def dist(x, y, p=2, name=None):
    return op(lambda a, b: jnp.linalg.norm((a - b).reshape(-1), ord=p), ensure_tensor(x), ensure_tensor(y), _name="dist")


def cross(x, y, axis=9, name=None):
    x = ensure_tensor(x)
    ax = axis if axis != 9 else next(i for i, s in enumerate(x.shape) if s == 3)
    return op(lambda a, b: jnp.cross(a, b, axis=ax), x, ensure_tensor(y), _name="cross")


def cholesky(x, upper=False, name=None):
    def fn(v):
        l = jnp.linalg.cholesky(v)
        return jnp.swapaxes(l, -1, -2) if upper else l

    return op(fn, ensure_tensor(x), _name="cholesky")


def cholesky_solve(x, y, upper=False, name=None):
    def fn(b, l):
        lo = jnp.swapaxes(l, -1, -2) if upper else l
        z = jax.scipy.linalg.solve_triangular(lo, b, lower=True)
        return jax.scipy.linalg.solve_triangular(jnp.swapaxes(lo, -1, -2), z, lower=False)

    return op(fn, ensure_tensor(x), ensure_tensor(y), _name="cholesky_solve")


def inverse(x, name=None):
    return op(jnp.linalg.inv, ensure_tensor(x), _name="inverse")


inv = inverse


def det(x, name=None):
    return op(jnp.linalg.det, ensure_tensor(x), _name="det")


def slogdet(x, name=None):
    x = ensure_tensor(x)

    def fn(v):
        sign, logdet = jnp.linalg.slogdet(v)
        return jnp.stack([sign, logdet])

    return op(fn, x, _name="slogdet")


def svd(x, full_matrices=False, name=None):
    return op(lambda v: jnp.linalg.svd(v, full_matrices=full_matrices), ensure_tensor(x), _name="svd")


def qr(x, mode="reduced", name=None):
    return op(lambda v: jnp.linalg.qr(v, mode=mode), ensure_tensor(x), _name="qr")


def eig(x, name=None):
    # jax cannot differentiate non-symmetric eig; detach so primitive does
    # not build a vjp (grad was never available for this op)
    return op(lambda v: jnp.linalg.eig(v), ensure_tensor(x).detach(), _name="eig")


def eigh(x, UPLO="L", name=None):
    return op(lambda v: jnp.linalg.eigh(v, UPLO=UPLO), ensure_tensor(x), _name="eigh")


def eigvals(x, name=None):
    return op(jnp.linalg.eigvals, ensure_tensor(x).detach(), _name="eigvals")


def eigvalsh(x, UPLO="L", name=None):
    return op(lambda v: jnp.linalg.eigvalsh(v, UPLO=UPLO), ensure_tensor(x), _name="eigvalsh")


def solve(x, y, name=None):
    return op(jnp.linalg.solve, ensure_tensor(x), ensure_tensor(y), _name="solve")


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False, name=None):
    def fn(a, b):
        return jax.scipy.linalg.solve_triangular(a, b, lower=not upper, trans=1 if transpose else 0, unit_diagonal=unitriangular)

    return op(fn, ensure_tensor(x), ensure_tensor(y), _name="triangular_solve")


def lstsq(x, y, rcond=None, driver=None, name=None):
    return op(lambda v, w: jnp.linalg.lstsq(v, w, rcond=rcond),
              ensure_tensor(x), ensure_tensor(y), _name="lstsq")


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return op(lambda v: jnp.linalg.pinv(v, rtol=rcond, hermitian=hermitian), ensure_tensor(x), _name="pinv")


def matrix_power(x, n, name=None):
    return op(lambda v: jnp.linalg.matrix_power(v, n), ensure_tensor(x), _name="matrix_power")


def matrix_rank(x, tol=None, hermitian=False, name=None):
    return op(lambda v: jnp.linalg.matrix_rank(v, rtol=tol), ensure_tensor(x), _name="matrix_rank")


def multi_dot(x, name=None):
    tensors = [ensure_tensor(t) for t in x]
    return op(lambda *vals: jnp.linalg.multi_dot(list(vals)), *tensors, _name="multi_dot")


def lu(x, pivot=True, get_infos=False, name=None):
    def fn(v):
        lu_, piv = jax.scipy.linalg.lu_factor(v)
        outs = (lu_, piv.astype(jnp.int32))
        if get_infos:
            outs = outs + (jnp.zeros((), jnp.int32),)
        return outs

    return op(fn, ensure_tensor(x), _name="lu")


def cond(x, p=None, name=None):
    """Condition number (reference paddle.linalg.cond): norm(x)·norm(x⁻¹)
    for p in {None/'fro', 2, -2, 1, -1, inf, -inf, 'nuc'}."""
    x = ensure_tensor(x)

    def fn(v):
        a = v.astype(jnp.float32)
        if p in (None, 2, -2, "nuc"):
            s = jnp.linalg.svd(a, compute_uv=False)
            if p == "nuc":
                return jnp.sum(s, -1) * jnp.sum(1.0 / s, -1)
            return (s[..., 0] / s[..., -1]) if p != -2 else (s[..., -1] / s[..., 0])
        inv = jnp.linalg.inv(a)
        if p == "fro":
            nrm = lambda m: jnp.sqrt(jnp.sum(m * m, axis=(-2, -1)))
        elif p in (1, -1):
            red = (jnp.max if p == 1 else jnp.min)
            nrm = lambda m: red(jnp.sum(jnp.abs(m), axis=-2), axis=-1)
        elif p in (float("inf"), float("-inf")):
            red = (jnp.max if p == float("inf") else jnp.min)
            nrm = lambda m: red(jnp.sum(jnp.abs(m), axis=-1), axis=-1)
        else:
            raise ValueError(f"unsupported p={p!r}")
        return nrm(a) * nrm(inv)

    return op(fn, x, _name="cond")


def lu_unpack(x, y, unpack_ludata=True, unpack_pivots=True, name=None):
    """Unpack lu()'s packed LU + pivots into (P, L, U) (reference
    paddle.linalg.lu_unpack)."""
    lu_t, piv = ensure_tensor(x), ensure_tensor(y)

    def one(lv, pv):
        m, n = lv.shape[-2], lv.shape[-1]
        k = min(m, n)
        L = jnp.tril(lv[:, :k], -1) + jnp.eye(m, k, dtype=lv.dtype)
        U = jnp.triu(lv[:k, :])
        # pivots are 0-based sequential swaps (jax.scipy lu_factor — what
        # this repo's lu() returns): row i swapped with row pv[i]
        perm = jnp.arange(m)
        for i in range(pv.shape[-1]):
            j = pv[i]
            pi, pj = perm[i], perm[j]
            perm = perm.at[i].set(pj).at[j].set(pi)
        P = jax.nn.one_hot(perm, m, dtype=lv.dtype).T
        return P, L, U

    def fn(lv, pv):
        if lv.ndim == 2:
            return one(lv, pv)
        lead = lv.shape[:-2]
        lf = lv.reshape((-1,) + lv.shape[-2:])
        pf = pv.reshape((-1, pv.shape[-1]))
        P, L, U = jax.vmap(one)(lf, pf)
        return (P.reshape(lead + P.shape[1:]), L.reshape(lead + L.shape[1:]),
                U.reshape(lead + U.shape[1:]))

    return op(fn, lu_t, piv, _name="lu_unpack")
