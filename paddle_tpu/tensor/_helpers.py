"""Shared dispatch helpers for the tensor op namespace.

Every public op is a thin wrapper: normalize arguments, then route through
:func:`paddle_tpu.framework.core.primitive` which executes with jax.numpy and
records the autograd tape. Paddle parity: the per-op branching in
python/paddle/tensor/* (``in_dygraph_mode() -> _C_ops...``) collapses to this
single path because there is no legacy/static split — jit tracing reuses the
same jnp implementations.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, primitive, unwrap, _wrap_value, _to_array
from ..framework.dtype import to_jax_dtype, convert_dtype, get_default_dtype

__all__ = [
    "Tensor",
    "primitive",
    "unwrap",
    "_wrap_value",
    "_to_array",
    "to_jax_dtype",
    "convert_dtype",
    "get_default_dtype",
    "ensure_tensor",
    "op",
]


def ensure_tensor(x, dtype=None):
    if isinstance(x, Tensor):
        return x
    t = Tensor.__new__(Tensor)
    t._init(_to_array(x, dtype))
    return t


def op(fn, *args, _name="", **kwargs):
    return primitive(fn, *args, _name=_name or getattr(fn, "__name__", "op"), **kwargs)
