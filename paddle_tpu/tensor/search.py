"""Search/sort ops (parity: python/paddle/tensor/search.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ._helpers import ensure_tensor, op, to_jax_dtype, unwrap, _wrap_value


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    def fn(v):
        out = jnp.argmax(v if axis is not None else v.reshape(-1), axis=axis if axis is not None else 0)
        if keepdim and axis is not None:
            out = jnp.expand_dims(out, axis)
        return out.astype(to_jax_dtype(dtype))

    return op(fn, ensure_tensor(x), _name="argmax")


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    def fn(v):
        out = jnp.argmin(v if axis is not None else v.reshape(-1), axis=axis if axis is not None else 0)
        if keepdim and axis is not None:
            out = jnp.expand_dims(out, axis)
        return out.astype(to_jax_dtype(dtype))

    return op(fn, ensure_tensor(x), _name="argmin")


def argsort(x, axis=-1, descending=False, name=None):
    def fn(v):
        return jnp.argsort(-v if descending else v, axis=axis).astype(to_jax_dtype("int64"))

    return op(fn, ensure_tensor(x), _name="argsort")


def sort(x, axis=-1, descending=False, name=None):
    def fn(v):
        out = jnp.sort(v, axis=axis)
        return jnp.flip(out, axis=axis) if descending else out

    return op(fn, ensure_tensor(x), _name="sort")


def topk(x, k, axis=None, largest=True, sorted=True, name=None):
    x = ensure_tensor(x)
    k = int(unwrap(k))
    ax = -1 if axis is None else axis

    def fn(v):
        vv = jnp.moveaxis(v, ax, -1)
        vals, idx = jax.lax.top_k(vv if largest else -vv, k)
        if not largest:
            vals = -vals
        return jnp.moveaxis(vals, -1, ax), jnp.moveaxis(idx, -1, ax).astype(to_jax_dtype("int64"))

    return op(fn, x, _name="topk")


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    x = ensure_tensor(x)

    def fn(v):
        s = jnp.sort(v, axis=axis)
        i = jnp.argsort(v, axis=axis)
        vals = jnp.take(s, k - 1, axis=axis)
        idx = jnp.take(i, k - 1, axis=axis)
        if keepdim:
            vals = jnp.expand_dims(vals, axis)
            idx = jnp.expand_dims(idx, axis)
        return vals, idx.astype(to_jax_dtype("int64"))

    return op(fn, x, _name="kthvalue")


def mode(x, axis=-1, keepdim=False, name=None):
    import numpy as np
    from scipy import stats  # available via numpy ecosystem

    v = np.asarray(unwrap(ensure_tensor(x)))
    m = stats.mode(v, axis=axis, keepdims=keepdim)
    return _wrap_value(jnp.asarray(m.mode)), _wrap_value(jnp.asarray(m.count))


def nonzero(x, as_tuple=False):
    v = unwrap(ensure_tensor(x))
    idx = jnp.nonzero(v)
    if as_tuple:
        return tuple(_wrap_value(i[:, None]) for i in idx)
    return _wrap_value(jnp.stack(idx, axis=1).astype(to_jax_dtype("int64")))


def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    side = "right" if right else "left"

    def fn(s, v):
        if s.ndim == 1:
            out = jnp.searchsorted(s, v, side=side)
        else:
            out = jax.vmap(lambda ss, vv: jnp.searchsorted(ss, vv, side=side))(s.reshape(-1, s.shape[-1]), v.reshape(-1, v.shape[-1])).reshape(v.shape)
        return out.astype(jnp.int32 if out_int32 else to_jax_dtype("int64"))

    return op(fn, ensure_tensor(sorted_sequence), ensure_tensor(values), _name="searchsorted")


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return searchsorted(sorted_sequence, x, out_int32=out_int32, right=right)


def index_put(x, indices, value, accumulate=False, name=None):
    def fn(v, val, *idx):
        if accumulate:
            return v.at[tuple(idx)].add(val)
        return v.at[tuple(idx)].set(val)

    return op(fn, ensure_tensor(x), ensure_tensor(value),
              *[ensure_tensor(i) for i in indices], _name="index_put")
