"""Shape/layout manipulation ops (parity: python/paddle/tensor/manipulation.py)."""
from __future__ import annotations

import builtins

import jax
import jax.numpy as jnp
import numpy as np

slice_builtin = builtins.slice

from ._helpers import Tensor, ensure_tensor, op, to_jax_dtype, unwrap, _wrap_value


def cast(x, dtype):
    dt = to_jax_dtype(dtype)
    x = ensure_tensor(x)
    src_float = jnp.issubdtype(x._value.dtype, jnp.floating)
    dst_float = jnp.issubdtype(np.dtype(dt), np.floating) or dt == jnp.bfloat16
    if src_float and dst_float:
        return op(lambda v: v.astype(dt), x, _name="cast")
    # non-differentiable cast: detached from the tape, but still an op when
    # the value is a static-trace symbol (SymbolicValue has no .astype)
    from ..framework.static_trace import is_symbolic

    if is_symbolic(x._value):
        return op(lambda v: v.astype(dt), x, _name="cast")
    return _wrap_value(x._value.astype(dt))


def reshape(x, shape, name=None):
    # coerce Tensor/array extents to ints; leave ints AND symbolic dims
    # (jax.export shape polymorphism) untouched
    shape = [int(unwrap(s)) if isinstance(s, (Tensor, np.ndarray, jnp.ndarray)) else s for s in shape]
    return op(lambda v: jnp.reshape(v, shape), ensure_tensor(x), _name="reshape")


def reshape_(x, shape, name=None):
    from ..framework.static_trace import guard_inplace

    guard_inplace("reshape_", x)
    x._value = jnp.reshape(x._value, shape)
    return x


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    x = ensure_tensor(x)
    nd = x.ndim

    def fn(v):
        s = start_axis % nd if nd else 0
        e = stop_axis % nd if nd else 0
        new_shape = list(v.shape[:s]) + [-1] + list(v.shape[e + 1 :])
        return jnp.reshape(v, new_shape)

    return op(fn, x, _name="flatten")


def transpose(x, perm=None, name=None):
    return op(lambda v: jnp.transpose(v, perm), ensure_tensor(x), _name="transpose")


def t(x, name=None):
    return op(lambda v: v.T, ensure_tensor(x), _name="t")


def moveaxis(x, source, destination, name=None):
    return op(lambda v: jnp.moveaxis(v, source, destination), ensure_tensor(x), _name="moveaxis")


def swapaxes(x, axis1, axis2, name=None):
    return op(lambda v: jnp.swapaxes(v, axis1, axis2), ensure_tensor(x), _name="swapaxes")


def squeeze(x, axis=None, name=None):
    def fn(v):
        if axis is None:
            return jnp.squeeze(v)
        axes = axis if isinstance(axis, (list, tuple)) else [axis]
        axes = tuple(a % v.ndim for a in axes if v.shape[a % v.ndim] == 1)
        return jnp.squeeze(v, axis=axes) if axes else v

    return op(fn, ensure_tensor(x), _name="squeeze")


def unsqueeze(x, axis, name=None):
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    return op(lambda v: jnp.expand_dims(v, tuple(axes)), ensure_tensor(x), _name="unsqueeze")


def concat(x, axis=0, name=None):
    tensors = [ensure_tensor(t) for t in x]
    ax = int(unwrap(axis))
    return op(lambda *vals: jnp.concatenate(vals, axis=ax), *tensors, _name="concat")


def stack(x, axis=0, name=None):
    tensors = [ensure_tensor(t) for t in x]
    return op(lambda *vals: jnp.stack(vals, axis=axis), *tensors, _name="stack")


def split(x, num_or_sections, axis=0, name=None):
    x = ensure_tensor(x)
    ax = int(unwrap(axis))
    dim = x.shape[ax]
    if isinstance(num_or_sections, int):
        if dim % num_or_sections != 0:
            raise ValueError(
                f"split: dimension {ax} of size {dim} is not divisible by num_or_sections={num_or_sections}"
            )
        sections = [dim // num_or_sections] * num_or_sections
    else:
        sections = [int(unwrap(s)) for s in num_or_sections]
        neg = [i for i, s in enumerate(sections) if s < 0]
        if neg:
            sections[neg[0]] = dim - sum(s for s in sections if s >= 0)
    offsets = np.cumsum([0] + sections)

    def fn(v):
        return tuple(jax.lax.slice_in_dim(v, int(offsets[i]), int(offsets[i + 1]), axis=ax) for i in range(len(sections)))

    return list(op(fn, x, _name="split"))


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def unbind(x, axis=0, name=None):
    x = ensure_tensor(x)
    n = x.shape[axis]

    def fn(v):
        return tuple(jnp.squeeze(s, axis=axis) for s in jnp.split(v, n, axis=axis))

    return list(op(fn, x, _name="unbind"))


def tile(x, repeat_times, name=None):
    reps = [int(unwrap(r)) for r in repeat_times] if isinstance(repeat_times, (list, tuple)) else int(repeat_times)
    return op(lambda v: jnp.tile(v, reps), ensure_tensor(x), _name="tile")


def expand(x, shape, name=None):
    x = ensure_tensor(x)
    shape = [int(unwrap(s)) for s in shape]

    def fn(v):
        tgt = list(shape)
        for i in range(len(tgt)):
            if tgt[i] == -1:
                tgt[i] = v.shape[i - len(tgt) + v.ndim] if i - len(tgt) + v.ndim >= 0 else 1
        return jnp.broadcast_to(v, tgt)

    return op(fn, x, _name="expand")


def expand_as(x, y, name=None):
    y = ensure_tensor(y)
    return expand(x, y.shape)


def broadcast_to(x, shape, name=None):
    return expand(x, shape)


def broadcast_tensors(inputs, name=None):
    tensors = [ensure_tensor(t) for t in inputs]
    shape = jnp.broadcast_shapes(*[tuple(t.shape) for t in tensors])
    return [op(lambda v: jnp.broadcast_to(v, shape), t) for t in tensors]


def gather(x, index, axis=0, name=None):
    ax = int(unwrap(axis))
    return op(lambda v, idx: jnp.take(v, idx, axis=ax), ensure_tensor(x), ensure_tensor(index), _name="gather")


def gather_nd(x, index, name=None):
    def fn(v, idx):
        return v[tuple(jnp.moveaxis(idx, -1, 0))]

    return op(fn, ensure_tensor(x), ensure_tensor(index), _name="gather_nd")


def take_along_axis(arr, indices, axis, name=None):
    return op(lambda v, idx: jnp.take_along_axis(v, idx, axis=axis),
              ensure_tensor(arr), ensure_tensor(indices), _name="take_along_axis")


def put_along_axis(arr, indices, values, axis, reduce="assign", name=None):
    def fn(v, val, idx):
        val = jnp.broadcast_to(val, idx.shape).astype(v.dtype)
        dims = list(range(v.ndim))
        ii = jnp.meshgrid(*[jnp.arange(s) for s in idx.shape], indexing="ij")
        ii[axis] = idx
        if reduce == "assign":
            return v.at[tuple(ii)].set(val)
        if reduce == "add":
            return v.at[tuple(ii)].add(val)
        if reduce == "multiply":
            return v.at[tuple(ii)].multiply(val)
        raise ValueError(reduce)

    return op(fn, ensure_tensor(arr), ensure_tensor(values), ensure_tensor(indices), _name="put_along_axis")


def scatter(x, index, updates, overwrite=True, name=None):
    def fn(v, u, idx):
        idx = idx.reshape(-1)
        if overwrite:
            return v.at[idx].set(u)
        return v.at[idx].add(u)

    return op(fn, ensure_tensor(x), ensure_tensor(updates), ensure_tensor(index), _name="scatter")


def scatter_nd_add(x, index, updates, name=None):
    def fn(v, u, idx):
        return v.at[tuple(jnp.moveaxis(idx, -1, 0))].add(u)

    return op(fn, ensure_tensor(x), ensure_tensor(updates), ensure_tensor(index), _name="scatter_nd_add")


def scatter_nd(index, updates, shape, name=None):
    def fn(u, idx):
        z = jnp.zeros(shape, u.dtype)
        return z.at[tuple(jnp.moveaxis(idx, -1, 0))].add(u)

    return op(fn, ensure_tensor(updates), ensure_tensor(index), _name="scatter_nd")


def index_select(x, index, axis=0, name=None):
    return gather(x, index, axis)


def index_sample(x, index, name=None):
    idx = unwrap(ensure_tensor(index))
    return op(lambda v: jnp.take_along_axis(v, idx, axis=1), ensure_tensor(x), _name="index_sample")


def masked_select(x, mask, name=None):
    m = unwrap(ensure_tensor(mask))
    # dynamic output shape: eager-only (documented; same restriction as XLA)
    return op(lambda v: v[m], ensure_tensor(x), _name="masked_select")


def masked_fill(x, mask, value, name=None):
    m = unwrap(ensure_tensor(mask))
    val = unwrap(value)
    return op(lambda v: jnp.where(m, jnp.asarray(val, v.dtype), v), ensure_tensor(x), _name="masked_fill")


def where(condition, x=None, y=None, name=None):
    cond = unwrap(ensure_tensor(condition))
    if x is None and y is None:
        return tuple(_wrap_value(i) for i in jnp.nonzero(cond))
    return op(lambda a, b: jnp.where(cond, a, b), ensure_tensor(x), ensure_tensor(y), _name="where")


def roll(x, shifts, axis=None, name=None):
    return op(lambda v: jnp.roll(v, shifts, axis=axis), ensure_tensor(x), _name="roll")


def flip(x, axis, name=None):
    return op(lambda v: jnp.flip(v, axis=axis), ensure_tensor(x), _name="flip")


def rot90(x, k=1, axes=(0, 1), name=None):
    return op(lambda v: jnp.rot90(v, k=k, axes=tuple(axes)), ensure_tensor(x), _name="rot90")


def repeat_interleave(x, repeats, axis=None, name=None):
    r = unwrap(repeats)
    return op(lambda v: jnp.repeat(v, r, axis=axis), ensure_tensor(x), _name="repeat_interleave")


def unique(x, return_index=False, return_inverse=False, return_counts=False, axis=None, dtype="int64", name=None):
    v = unwrap(ensure_tensor(x))
    res = jnp.unique(v, return_index=return_index, return_inverse=return_inverse, return_counts=return_counts, axis=axis)
    if isinstance(res, tuple):
        return tuple(_wrap_value(r) for r in res)
    return _wrap_value(res)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None, dtype="int64", name=None):
    v = np.asarray(unwrap(ensure_tensor(x)))
    if axis is None:
        v = v.reshape(-1)
    keep = np.concatenate([[True], v[1:] != v[:-1]]) if v.ndim == 1 else None
    out = v[keep]
    outs = [_wrap_value(jnp.asarray(out))]
    if return_inverse:
        inv = np.cumsum(keep) - 1
        outs.append(_wrap_value(jnp.asarray(inv)))
    if return_counts:
        idx = np.flatnonzero(keep)
        counts = np.diff(np.append(idx, len(v)))
        outs.append(_wrap_value(jnp.asarray(counts)))
    return tuple(outs) if len(outs) > 1 else outs[0]


def slice(input, axes, starts, ends, name=None):
    x = ensure_tensor(input)
    starts = [int(unwrap(s)) for s in starts]
    ends = [int(unwrap(e)) for e in ends]

    def fn(v):
        idx = [slice_builtin(None)] * v.ndim
        for ax, s, e in zip(axes, starts, ends):
            dim = v.shape[ax]
            s2 = max(s + dim, 0) if s < 0 else min(s, dim)
            e2 = max(e + dim, 0) if e < 0 else min(e, dim)
            idx[ax] = slice_builtin(s2, e2)
        return v[tuple(idx)]

    return op(fn, x, _name="slice")


def strided_slice(x, axes, starts, ends, strides, name=None):
    x = ensure_tensor(x)

    def fn(v):
        idx = [slice_builtin(None)] * v.ndim
        for ax, s, e, st in zip(axes, starts, ends, strides):
            idx[ax] = slice_builtin(int(unwrap(s)), int(unwrap(e)), int(unwrap(st)))
        return v[tuple(idx)]

    return op(fn, x, _name="strided_slice")


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    v = unwrap(ensure_tensor(input))
    shard_size = (index_num + nshards - 1) // nshards
    in_shard = (v // shard_size) == shard_id
    return _wrap_value(jnp.where(in_shard, v % shard_size, ignore_value))


def crop(x, shape=None, offsets=None, name=None):
    x = ensure_tensor(x)
    shape = [int(unwrap(s)) for s in shape]
    offsets = [int(unwrap(o)) for o in (offsets or [0] * len(shape))]

    def fn(v):
        idx = tuple(slice_builtin(o, o + (s if s != -1 else v.shape[i] - o)) for i, (o, s) in enumerate(zip(offsets, shape)))
        return v[idx]

    return op(fn, x, _name="crop")


def as_complex(x, name=None):
    return op(lambda v: jax.lax.complex(v[..., 0], v[..., 1]), ensure_tensor(x), _name="as_complex")


def as_real(x, name=None):
    return op(lambda v: jnp.stack([jnp.real(v), jnp.imag(v)], axis=-1), ensure_tensor(x), _name="as_real")


def tensordot(x, y, axes=2, name=None):
    return op(lambda a, b: jnp.tensordot(a, b, axes=axes), ensure_tensor(x), ensure_tensor(y), _name="tensordot")


def numel(x, name=None):
    return _wrap_value(jnp.asarray(int(np.prod(ensure_tensor(x).shape)) if ensure_tensor(x).shape else 1))


def rank(x):
    return _wrap_value(jnp.asarray(ensure_tensor(x).ndim))


def shape(x):
    return _wrap_value(jnp.asarray(ensure_tensor(x).shape, dtype=jnp.int32))


# -- round-4 API-diff tail (reference python/paddle/__init__.py names) ------


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return op(lambda v: jnp.diagonal(v, offset=offset, axis1=axis1, axis2=axis2),
              ensure_tensor(x), _name="diagonal")


def unstack(x, axis=0, num=None, name=None):
    """Split along ``axis`` into a list of tensors with that axis removed
    (reference fluid.layers.unstack)."""
    x = ensure_tensor(x)
    if num is not None and x.shape[axis] is not None and num != x.shape[axis]:
        raise ValueError(f"unstack num={num} mismatches axis extent {x.shape[axis]}")
    n = x.shape[axis] if num is None else num
    outs = []
    for i in range(n):
        outs.append(op(lambda v, i=i: jnp.take(v, i, axis=axis), x, _name="unstack"))
    return outs


def reverse(x, axis, name=None):
    """flip alias (the reference keeps both names)."""
    return flip(x, axis)


def multiplex(inputs, index, name=None):
    """Row-wise select: out[i] = inputs[index[i, 0]][i] (reference
    fluid.layers.multiplex)."""
    ins = [ensure_tensor(t) for t in inputs]
    idx = ensure_tensor(index)

    def fn(ix, *tensors):
        stacked = jnp.stack(tensors, axis=0)  # [n, batch, ...]
        sel = ix.reshape(-1).astype(jnp.int32)
        return jnp.take_along_axis(
            stacked, sel.reshape((1, -1) + (1,) * (stacked.ndim - 2)),
            axis=0)[0]

    return op(fn, idx, *ins, _name="multiplex")


def tolist(x):
    return np.asarray(ensure_tensor(x)._value).tolist()


def broadcast_shape(x_shape, y_shape):
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def _inplace(name, x, fn):
    """Apply ``fn`` to a snapshot of ``x`` and rebind ``x`` to the result —
    the paddle inplace-op contract (version-bumped reuse of the same python
    Tensor). The snapshot keeps the autograd edge pointing at the OLD
    producer, so rebinding cannot create a self-referential node; later
    reads of x see — and differentiate through — the new value. For a LEAF
    x, a hook on the snapshot mirrors accumulated grads back onto x.grad
    (reference: inplace ops on leaves still populate x.grad)."""
    from ..framework.autograd import _accum_grad
    from ..framework.static_trace import guard_inplace

    guard_inplace(name, x)
    old = _wrap_value(x._value, stop_gradient=x.stop_gradient)
    old._node, old._out_idx = x._node, x._out_idx
    if old._node is None and not old.stop_gradient:
        def _mirror(g):  # hooks receive a wrapped Tensor; grads store raw values
            _accum_grad(x, g._value if hasattr(g, "_value") else g)

        old.register_hook(_mirror)
    out = fn(old)
    x._value, x._node, x._out_idx = out._value, out._node, out._out_idx
    return x


def squeeze_(x, axis=None, name=None):
    x = ensure_tensor(x)
    return _inplace("squeeze_", x, lambda v: squeeze(v, axis))


def unsqueeze_(x, axis, name=None):
    x = ensure_tensor(x)
    return _inplace("unsqueeze_", x, lambda v: unsqueeze(v, axis))


def scatter_(x, index, updates, overwrite=True, name=None):
    x = ensure_tensor(x)
    return _inplace("scatter_", x, lambda v: scatter(v, index, updates, overwrite))


def put_along_axis_(arr, indices, values, axis, reduce="assign", name=None):
    x = ensure_tensor(arr)
    return _inplace("put_along_axis_", x,
                    lambda v: put_along_axis(v, indices, values, axis, reduce))


def flatten_(x, start_axis=0, stop_axis=-1, name=None):
    x = ensure_tensor(x)
    return _inplace("flatten_", x, lambda v: flatten(v, start_axis, stop_axis))
