"""Statistics ops (parity: python/paddle/tensor/stat.py)."""
from __future__ import annotations

import jax.numpy as jnp

from ._helpers import ensure_tensor, op, to_jax_dtype, unwrap, _wrap_value


def _norm_axis(axis):
    if axis is None:
        return None
    return tuple(axis) if isinstance(axis, (list, tuple)) else int(axis)


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    return op(
        lambda v: jnp.std(v, axis=_norm_axis(axis), ddof=1 if unbiased else 0, keepdims=keepdim),
        ensure_tensor(x),
        _name="std",
    )


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    return op(
        lambda v: jnp.var(v, axis=_norm_axis(axis), ddof=1 if unbiased else 0, keepdims=keepdim),
        ensure_tensor(x),
        _name="var",
    )


def median(x, axis=None, keepdim=False, name=None):
    return op(lambda v: jnp.median(v, axis=_norm_axis(axis), keepdims=keepdim), ensure_tensor(x), _name="median")


def nanmedian(x, axis=None, keepdim=False, name=None):
    return op(lambda v: jnp.nanmedian(v, axis=_norm_axis(axis), keepdims=keepdim), ensure_tensor(x), _name="nanmedian")


def quantile(x, q, axis=None, keepdim=False, name=None):
    return op(lambda v: jnp.quantile(v, jnp.asarray(q), axis=_norm_axis(axis), keepdims=keepdim), ensure_tensor(x), _name="quantile")


def nanquantile(x, q, axis=None, keepdim=False, name=None):
    return op(lambda v: jnp.nanquantile(v, jnp.asarray(q), axis=_norm_axis(axis), keepdims=keepdim), ensure_tensor(x), _name="nanquantile")


def histogram(input, bins=100, min=0, max=0, name=None):
    lo, hi = (None, None) if (min == 0 and max == 0) else (min, max)

    def fn(v):
        hist, _ = jnp.histogram(v, bins=bins, range=(lo, hi) if lo is not None else None)
        return hist.astype(to_jax_dtype("int64"))

    return op(fn, ensure_tensor(input), _name="histogram")


def bincount(x, weights=None, minlength=0, name=None):
    # output length is data-dependent; XLA needs it static. Eager: read it
    # from the values. Static capture: minlength must pin it.
    from ..framework.static_trace import is_symbolic

    v = unwrap(ensure_tensor(x))
    if is_symbolic(v):
        if minlength <= 0:
            raise ValueError(
                "bincount under static capture needs minlength>0: the output "
                "length is data-dependent, which XLA cannot compile")
        n = int(minlength)
    else:
        length = int(__import__("numpy").asarray(v).max(initial=-1)) + 1
        n = max(int(minlength), length)
    aux = [ensure_tensor(weights)] if weights is not None else []

    def fn(vv, *ws):
        return jnp.bincount(vv, weights=ws[0] if ws else None, minlength=n, length=n)

    return op(fn, ensure_tensor(x), *aux, _name="bincount")


def corrcoef(x, rowvar=True, name=None):
    return op(lambda v: jnp.corrcoef(v, rowvar=rowvar), ensure_tensor(x), _name="corrcoef")


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    return op(lambda v: jnp.cov(v, rowvar=rowvar, ddof=1 if ddof else 0), ensure_tensor(x), _name="cov")
