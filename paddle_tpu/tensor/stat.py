"""Statistics ops (parity: python/paddle/tensor/stat.py)."""
from __future__ import annotations

import jax.numpy as jnp

from ._helpers import ensure_tensor, op, to_jax_dtype, unwrap, _wrap_value


def _norm_axis(axis):
    if axis is None:
        return None
    return tuple(axis) if isinstance(axis, (list, tuple)) else int(axis)


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    return op(
        lambda v: jnp.std(v, axis=_norm_axis(axis), ddof=1 if unbiased else 0, keepdims=keepdim),
        ensure_tensor(x),
        _name="std",
    )


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    return op(
        lambda v: jnp.var(v, axis=_norm_axis(axis), ddof=1 if unbiased else 0, keepdims=keepdim),
        ensure_tensor(x),
        _name="var",
    )


def median(x, axis=None, keepdim=False, name=None):
    return op(lambda v: jnp.median(v, axis=_norm_axis(axis), keepdims=keepdim), ensure_tensor(x), _name="median")


def nanmedian(x, axis=None, keepdim=False, name=None):
    return op(lambda v: jnp.nanmedian(v, axis=_norm_axis(axis), keepdims=keepdim), ensure_tensor(x), _name="nanmedian")


def quantile(x, q, axis=None, keepdim=False, name=None):
    return op(lambda v: jnp.quantile(v, jnp.asarray(q), axis=_norm_axis(axis), keepdims=keepdim), ensure_tensor(x), _name="quantile")


def nanquantile(x, q, axis=None, keepdim=False, name=None):
    return op(lambda v: jnp.nanquantile(v, jnp.asarray(q), axis=_norm_axis(axis), keepdims=keepdim), ensure_tensor(x), _name="nanquantile")


def histogram(input, bins=100, min=0, max=0, name=None):
    v = unwrap(ensure_tensor(input))
    lo, hi = (None, None) if (min == 0 and max == 0) else (min, max)
    hist, _ = jnp.histogram(v, bins=bins, range=(lo, hi) if lo is not None else None)
    return _wrap_value(hist.astype(to_jax_dtype("int64")))


def bincount(x, weights=None, minlength=0, name=None):
    v = unwrap(ensure_tensor(x))
    w = unwrap(ensure_tensor(weights)) if weights is not None else None
    return _wrap_value(jnp.bincount(v, weights=w, minlength=minlength))


def corrcoef(x, rowvar=True, name=None):
    return op(lambda v: jnp.corrcoef(v, rowvar=rowvar), ensure_tensor(x), _name="corrcoef")


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    return op(lambda v: jnp.cov(v, rowvar=rowvar, ddof=1 if ddof else 0), ensure_tensor(x), _name="cov")
