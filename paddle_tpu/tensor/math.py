"""Elementwise + reduction math ops (parity: python/paddle/tensor/math.py).

Each op is a jnp lambda routed through the tape (`framework/core.primitive`);
XLA provides the kernel and its gradient. Reference kernel equivalents live in
paddle/phi/kernels/* — none of that is needed on TPU.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ._helpers import Tensor, ensure_tensor, op, to_jax_dtype, unwrap


def _scalar_or_tensor(x):
    # python scalars stay raw so JAX weak typing applies (bf16 + 1.0 -> bf16,
    # matching paddle's scalar-operand promotion); everything else wraps
    return x if isinstance(x, (bool, int, float)) else ensure_tensor(x)


def _binary(fn, x, y, name=""):
    return op(fn, _scalar_or_tensor(x), _scalar_or_tensor(y), _name=name)


def _unary(fn, x, name=""):
    return op(fn, ensure_tensor(x), _name=name)


# ---- elementwise binary ---------------------------------------------------

def add(x, y, name=None):
    return _binary(jnp.add, x, y, "add")


def subtract(x, y, name=None):
    return _binary(jnp.subtract, x, y, "subtract")


def multiply(x, y, name=None):
    return _binary(jnp.multiply, x, y, "multiply")


def divide(x, y, name=None):
    return _binary(jnp.divide, x, y, "divide")


def floor_divide(x, y, name=None):
    return _binary(jnp.floor_divide, x, y, "floor_divide")


def remainder(x, y, name=None):
    return _binary(jnp.remainder, x, y, "remainder")


mod = remainder
floor_mod = remainder


def pow(x, y, name=None):
    return _binary(jnp.power, x, y, "pow")


def maximum(x, y, name=None):
    return _binary(jnp.maximum, x, y, "maximum")


def minimum(x, y, name=None):
    return _binary(jnp.minimum, x, y, "minimum")


def fmax(x, y, name=None):
    return _binary(jnp.fmax, x, y, "fmax")


def fmin(x, y, name=None):
    return _binary(jnp.fmin, x, y, "fmin")


def atan2(x, y, name=None):
    return _binary(jnp.arctan2, x, y, "atan2")


def kron(x, y, name=None):
    return _binary(jnp.kron, x, y, "kron")


def inner(x, y, name=None):
    return _binary(jnp.inner, x, y, "inner")


def outer(x, y, name=None):
    return _binary(jnp.outer, x, y, "outer")


def logaddexp(x, y, name=None):
    return _binary(jnp.logaddexp, x, y, "logaddexp")


def heaviside(x, y, name=None):
    return _binary(jnp.heaviside, x, y, "heaviside")


def copysign(x, y, name=None):
    return _binary(jnp.copysign, x, y, "copysign")


def nextafter(x, y, name=None):
    return _binary(jnp.nextafter, x, y, "nextafter")


def hypot(x, y, name=None):
    return _binary(jnp.hypot, x, y, "hypot")


def gcd(x, y, name=None):
    return _binary(jnp.gcd, x, y, "gcd")


def lcm(x, y, name=None):
    return _binary(jnp.lcm, x, y, "lcm")


# ---- elementwise unary ----------------------------------------------------

def sqrt(x, name=None):
    return _unary(jnp.sqrt, x, "sqrt")


def rsqrt(x, name=None):
    return _unary(jax.lax.rsqrt, x, "rsqrt")


def exp(x, name=None):
    return _unary(jnp.exp, x, "exp")


def expm1(x, name=None):
    return _unary(jnp.expm1, x, "expm1")


def log(x, name=None):
    return _unary(jnp.log, x, "log")


def log2(x, name=None):
    return _unary(jnp.log2, x, "log2")


def log10(x, name=None):
    return _unary(jnp.log10, x, "log10")


def log1p(x, name=None):
    return _unary(jnp.log1p, x, "log1p")


def abs(x, name=None):
    return _unary(jnp.abs, x, "abs")


def neg(x, name=None):
    return _unary(jnp.negative, x, "neg")


def sign(x, name=None):
    return _unary(jnp.sign, x, "sign")


def floor(x, name=None):
    return _unary(jnp.floor, x, "floor")


def ceil(x, name=None):
    return _unary(jnp.ceil, x, "ceil")


def round(x, name=None):
    return _unary(jnp.round, x, "round")


def trunc(x, name=None):
    return _unary(jnp.trunc, x, "trunc")


def frac(x, name=None):
    return _unary(lambda v: v - jnp.trunc(v), x, "frac")


def sin(x, name=None):
    return _unary(jnp.sin, x, "sin")


def cos(x, name=None):
    return _unary(jnp.cos, x, "cos")


def tan(x, name=None):
    return _unary(jnp.tan, x, "tan")


def asin(x, name=None):
    return _unary(jnp.arcsin, x, "asin")


def acos(x, name=None):
    return _unary(jnp.arccos, x, "acos")


def atan(x, name=None):
    return _unary(jnp.arctan, x, "atan")


def sinh(x, name=None):
    return _unary(jnp.sinh, x, "sinh")


def cosh(x, name=None):
    return _unary(jnp.cosh, x, "cosh")


def tanh(x, name=None):
    return _unary(jnp.tanh, x, "tanh")


def asinh(x, name=None):
    return _unary(jnp.arcsinh, x, "asinh")


def acosh(x, name=None):
    return _unary(jnp.arccosh, x, "acosh")


def atanh(x, name=None):
    return _unary(jnp.arctanh, x, "atanh")


def reciprocal(x, name=None):
    return _unary(jnp.reciprocal, x, "reciprocal")


def square(x, name=None):
    return _unary(jnp.square, x, "square")


def erf(x, name=None):
    return _unary(jax.scipy.special.erf, x, "erf")


def erfinv(x, name=None):
    return _unary(jax.scipy.special.erfinv, x, "erfinv")


def lgamma(x, name=None):
    return _unary(jax.scipy.special.gammaln, x, "lgamma")


def digamma(x, name=None):
    return _unary(jax.scipy.special.digamma, x, "digamma")


def sigmoid(x, name=None):
    return _unary(jax.nn.sigmoid, x, "sigmoid")


def logit(x, eps=None, name=None):
    def fn(v):
        if eps is not None:
            v = jnp.clip(v, eps, 1.0 - eps)
        return jnp.log(v / (1.0 - v))

    return _unary(fn, x, "logit")


def isnan(x, name=None):
    return _unary(jnp.isnan, ensure_tensor(x), "isnan")


def isinf(x, name=None):
    return _unary(jnp.isinf, ensure_tensor(x), "isinf")


def isfinite(x, name=None):
    return _unary(jnp.isfinite, ensure_tensor(x), "isfinite")


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return _unary(lambda v: jnp.nan_to_num(v, nan=nan, posinf=posinf, neginf=neginf), x, "nan_to_num")


def clip(x, min=None, max=None, name=None):
    from ._helpers import ensure_tensor

    # Tensor bounds ride positionally (static-capturable, differentiable);
    # python scalars stay weakly typed so bf16/f16 inputs keep their dtype
    lo_is_t, hi_is_t = isinstance(min, Tensor), isinstance(max, Tensor)
    aux = [m for m in (min, max) if isinstance(m, Tensor)]

    def fn(v, *bounds):
        lo = bounds[0] if lo_is_t else min
        hi = bounds[-1] if hi_is_t else max
        return jnp.clip(v, lo, hi)

    return op(fn, ensure_tensor(x), *aux, _name="clip")


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    from ._helpers import ensure_tensor

    s_is_t, b_is_t = isinstance(scale, Tensor), isinstance(bias, Tensor)
    aux = [a for a in (scale, bias) if isinstance(a, Tensor)]

    def fn(v, *ab):
        s = ab[0] if s_is_t else scale
        b = ab[-1] if b_is_t else bias
        return v * s + b if bias_after_scale else (v + b) * s

    return op(fn, ensure_tensor(x), *aux, _name="scale")


def increment(x, value=1.0, name=None):
    from ..framework.static_trace import guard_inplace

    guard_inplace("increment", x)
    x._value = x._value + value
    return x


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return _unary(lambda v: scale_b * jnp.tanh(scale_a * v), x, "stanh")


def softplus_op(x, beta=1, threshold=20, name=None):
    return _unary(lambda v: jax.nn.softplus(beta * v) / beta, x, "softplus")


def angle(x, name=None):
    return _unary(jnp.angle, x, "angle")


def conj(x, name=None):
    return _unary(jnp.conj, x, "conj")


def real(x, name=None):
    return _unary(jnp.real, x, "real")


def imag(x, name=None):
    return _unary(jnp.imag, x, "imag")


def deg2rad(x, name=None):
    return _unary(jnp.deg2rad, x, "deg2rad")


def rad2deg(x, name=None):
    return _unary(jnp.rad2deg, x, "rad2deg")


def lerp(x, y, weight, name=None):
    w = ensure_tensor(weight) if isinstance(weight, Tensor) else weight
    if isinstance(w, Tensor):
        return op(lambda a, b, ww: a + ww * (b - a), ensure_tensor(x), ensure_tensor(y), w, _name="lerp")
    return op(lambda a, b: a + w * (b - a), ensure_tensor(x), ensure_tensor(y), _name="lerp")


# ---- reductions -----------------------------------------------------------

def _norm_axis(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    dt = to_jax_dtype(dtype) if dtype else None
    return _unary(lambda v: jnp.sum(v, axis=_norm_axis(axis), dtype=dt, keepdims=keepdim), x, "sum")


def mean(x, axis=None, keepdim=False, name=None):
    return _unary(lambda v: jnp.mean(v, axis=_norm_axis(axis), keepdims=keepdim), x, "mean")


def max(x, axis=None, keepdim=False, name=None):
    return _unary(lambda v: jnp.max(v, axis=_norm_axis(axis), keepdims=keepdim), x, "max")


def min(x, axis=None, keepdim=False, name=None):
    return _unary(lambda v: jnp.min(v, axis=_norm_axis(axis), keepdims=keepdim), x, "min")


def amax(x, axis=None, keepdim=False, name=None):
    return max(x, axis, keepdim)


def amin(x, axis=None, keepdim=False, name=None):
    return min(x, axis, keepdim)


def prod(x, axis=None, keepdim=False, dtype=None, name=None):
    dt = to_jax_dtype(dtype) if dtype else None
    return _unary(lambda v: jnp.prod(v, axis=_norm_axis(axis), dtype=dt, keepdims=keepdim), x, "prod")


def nansum(x, axis=None, dtype=None, keepdim=False, name=None):
    dt = to_jax_dtype(dtype) if dtype else None
    return _unary(lambda v: jnp.nansum(v, axis=_norm_axis(axis), dtype=dt, keepdims=keepdim), x, "nansum")


def nanmean(x, axis=None, keepdim=False, name=None):
    return _unary(lambda v: jnp.nanmean(v, axis=_norm_axis(axis), keepdims=keepdim), x, "nanmean")


def logsumexp(x, axis=None, keepdim=False, name=None):
    return _unary(lambda v: jax.scipy.special.logsumexp(v, axis=_norm_axis(axis), keepdims=keepdim), x, "logsumexp")


def all(x, axis=None, keepdim=False, name=None):
    return _unary(lambda v: jnp.all(v, axis=_norm_axis(axis), keepdims=keepdim), ensure_tensor(x), "all")


def any(x, axis=None, keepdim=False, name=None):
    return _unary(lambda v: jnp.any(v, axis=_norm_axis(axis), keepdims=keepdim), ensure_tensor(x), "any")


def count_nonzero(x, axis=None, keepdim=False, name=None):
    return _unary(lambda v: jnp.count_nonzero(v, axis=_norm_axis(axis), keepdims=keepdim), ensure_tensor(x), "count_nonzero")


def cumsum(x, axis=None, dtype=None, name=None):
    dt = to_jax_dtype(dtype) if dtype else None

    def fn(v):
        if axis is None:
            return jnp.cumsum(v.reshape(-1), dtype=dt)
        return jnp.cumsum(v, axis=int(axis), dtype=dt)

    return _unary(fn, x, "cumsum")


def cumprod(x, dim=None, dtype=None, name=None):
    dt = to_jax_dtype(dtype) if dtype else None
    return _unary(lambda v: jnp.cumprod(v, axis=dim, dtype=dt), x, "cumprod")


def _cum_extreme(x, axis, dtype, op_name):
    from ._helpers import to_jax_dtype

    x = ensure_tensor(x)
    cum = jax.lax.cummax if op_name == "cummax" else jax.lax.cummin

    def fn(v):
        ax = 0 if axis is None else int(axis)
        vv = v.reshape(-1) if axis is None else v
        vals = cum(vv, axis=ax)
        n = vv.shape[ax]
        iota = jax.lax.broadcasted_iota(jnp.int32, vv.shape, ax)
        # index of the running extreme: latest position where v equals it
        idx = jax.lax.cummax(jnp.where(vv == vals, iota, -1), axis=ax)
        return vals, idx.astype(to_jax_dtype(dtype))

    return op(fn, x, _name=op_name)


def cummax(x, axis=None, dtype="int64", name=None):
    """Returns (values, indices) like paddle.cummax."""
    return _cum_extreme(x, axis, dtype, "cummax")


def cummin(x, axis=None, dtype="int64", name=None):
    """Returns (values, indices) like paddle.cummin."""
    return _cum_extreme(x, axis, dtype, "cummin")


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return _unary(lambda v: jnp.trace(v, offset=offset, axis1=axis1, axis2=axis2), x, "trace")


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    from ._helpers import ensure_tensor

    pre_is_t, app_is_t = isinstance(prepend, Tensor), isinstance(append, Tensor)
    aux = [m for m in (prepend, append) if isinstance(m, Tensor)]

    def fn(v, *edges):
        pre = edges[0] if pre_is_t else (unwrap(prepend) if prepend is not None else None)
        app = edges[-1] if app_is_t else (unwrap(append) if append is not None else None)
        return jnp.diff(v, n=n, axis=axis, prepend=pre, append=app)

    return op(fn, ensure_tensor(x), *aux, _name="diff")


def add_n(inputs, name=None):
    if isinstance(inputs, Tensor):
        return inputs
    tensors = [ensure_tensor(t) for t in inputs]

    def fn(*vals):
        out = vals[0]
        for v in vals[1:]:
            out = out + v
        return out

    return op(fn, *tensors, _name="add_n")


# ---- matmul-family (parity: python/paddle/tensor/linalg.py:128) ----------

def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    def fn(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim > 1 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
        return jnp.matmul(a, b)

    return _binary(fn, x, y, "matmul")


def dot(x, y, name=None):
    return _binary(lambda a, b: jnp.sum(a * b, axis=-1), x, y, "dot")


def bmm(x, y, name=None):
    return _binary(jnp.matmul, x, y, "bmm")


def mv(x, vec, name=None):
    return _binary(jnp.matmul, x, vec, "mv")


def mm(x, y, name=None):
    return _binary(jnp.matmul, x, y, "mm")


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return op(
        lambda i, a, b: beta * i + alpha * jnp.matmul(a, b),
        ensure_tensor(input),
        ensure_tensor(x),
        ensure_tensor(y),
        _name="addmm",
    )


def einsum(equation, *operands):
    tensors = [ensure_tensor(t) for t in operands]
    return op(lambda *vals: jnp.einsum(equation, *vals), *tensors, _name="einsum")


def renorm(x, p, axis, max_norm, name=None):
    """Clamp each slice along ``axis`` to p-norm <= max_norm (reference
    paddle.renorm)."""
    x = ensure_tensor(x)

    def fn(v):
        axes = tuple(i for i in range(v.ndim) if i != axis % v.ndim)
        norms = jnp.sum(jnp.abs(v) ** p, axis=axes, keepdims=True) ** (1.0 / p)
        scale = jnp.where(norms > max_norm, max_norm / (norms + 1e-7), 1.0)
        return v * scale.astype(v.dtype)

    return op(fn, x, _name="renorm")


def tanh_(x, name=None):
    from .manipulation import _inplace

    x = ensure_tensor(x)
    return _inplace("tanh_", x, tanh)


# -- inplace tail (reference paddle.Tensor.add_ etc.) -----------------------


def _mk_inplace(name, fn):
    from .manipulation import _inplace

    def inplace(x, *args, **kwargs):
        return _inplace(name, ensure_tensor(x), lambda v: fn(v, *args, **kwargs))

    inplace.__name__ = name
    inplace.__doc__ = f"In-place {name[:-1]} (reference paddle.{name})."
    return inplace


add_ = _mk_inplace("add_", lambda v, y, name=None: add(v, y))
subtract_ = _mk_inplace("subtract_", lambda v, y, name=None: subtract(v, y))
ceil_ = _mk_inplace("ceil_", lambda v, name=None: ceil(v))
floor_ = _mk_inplace("floor_", lambda v, name=None: floor(v))
exp_ = _mk_inplace("exp_", lambda v, name=None: exp(v))
sqrt_ = _mk_inplace("sqrt_", lambda v, name=None: sqrt(v))
rsqrt_ = _mk_inplace("rsqrt_", lambda v, name=None: rsqrt(v))
reciprocal_ = _mk_inplace("reciprocal_", lambda v, name=None: reciprocal(v))
round_ = _mk_inplace("round_", lambda v, name=None: round(v))
clip_ = _mk_inplace("clip_", lambda v, min=None, max=None, name=None: clip(v, min, max))
scale_ = _mk_inplace("scale_", lambda v, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None: globals()["scale"](v, scale, bias, bias_after_scale, act))
erfinv_ = _mk_inplace("erfinv_", lambda v, name=None: erfinv(v))
lerp_ = _mk_inplace("lerp_", lambda v, y, weight, name=None: lerp(v, y, weight))
