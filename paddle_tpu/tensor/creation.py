"""Tensor creation ops (parity: python/paddle/tensor/creation.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ._helpers import (
    Tensor,
    _to_array,
    _wrap_value,
    ensure_tensor,
    get_default_dtype,
    op,
    to_jax_dtype,
    unwrap,
)
from ..framework import random as _random


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    t = Tensor(data, dtype=dtype)
    t.stop_gradient = stop_gradient
    return t


def _shape_list(shape):
    if isinstance(shape, Tensor):
        return [int(s) for s in np.asarray(shape._value)]
    if isinstance(shape, (int, np.integer)):
        return [int(shape)]
    return [int(unwrap(s)) if not isinstance(s, (int, np.integer)) else int(s) for s in shape]


def zeros(shape, dtype=None, name=None):
    return _wrap_value(jnp.zeros(_shape_list(shape), to_jax_dtype(dtype or get_default_dtype())))


def ones(shape, dtype=None, name=None):
    return _wrap_value(jnp.ones(_shape_list(shape), to_jax_dtype(dtype or get_default_dtype())))


def full(shape, fill_value, dtype=None, name=None):
    fill = unwrap(fill_value)
    dt = to_jax_dtype(dtype) if dtype else None
    return _wrap_value(jnp.full(_shape_list(shape), fill, dtype=dt))


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype=dtype)


def zeros_like(x, dtype=None, name=None):
    return op(lambda v: jnp.zeros_like(v, dtype=to_jax_dtype(dtype) if dtype else None), ensure_tensor(x))


def ones_like(x, dtype=None, name=None):
    return op(lambda v: jnp.ones_like(v, dtype=to_jax_dtype(dtype) if dtype else None), ensure_tensor(x))


def full_like(x, fill_value, dtype=None, name=None):
    return op(
        lambda v: jnp.full_like(v, unwrap(fill_value), dtype=to_jax_dtype(dtype) if dtype else None),
        ensure_tensor(x),
    )


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype=dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    if end is None:
        start, end = 0, start
    start, end, step = unwrap(start), unwrap(end), unwrap(step)
    dt = to_jax_dtype(dtype) if dtype else None
    return _wrap_value(jnp.arange(start, end, step, dtype=dt))


def linspace(start, stop, num, dtype=None, name=None):
    dt = to_jax_dtype(dtype) if dtype else None
    return _wrap_value(jnp.linspace(unwrap(start), unwrap(stop), int(unwrap(num)), dtype=dt))


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    dt = to_jax_dtype(dtype) if dtype else None
    return _wrap_value(jnp.logspace(unwrap(start), unwrap(stop), int(unwrap(num)), base=base, dtype=dt))


def eye(num_rows, num_columns=None, dtype=None, name=None):
    return _wrap_value(jnp.eye(num_rows, num_columns, dtype=to_jax_dtype(dtype or get_default_dtype())))


def diag(x, offset=0, padding_value=0, name=None):
    x = ensure_tensor(x)

    def fn(v):
        if v.ndim == 1 and padding_value != 0:
            n = v.shape[0] + abs(offset)
            base = jnp.full((n, n), padding_value, v.dtype)
            return base + jnp.diag(v, k=offset) - jnp.diag(jnp.full(v.shape, padding_value, v.dtype), k=offset)
        return jnp.diag(v, k=offset)

    return op(fn, x)


def diagflat(x, offset=0, name=None):
    return op(lambda v: jnp.diagflat(v, k=offset), ensure_tensor(x))


def tril(x, diagonal=0, name=None):
    return op(lambda v: jnp.tril(v, k=diagonal), ensure_tensor(x))


def triu(x, diagonal=0, name=None):
    return op(lambda v: jnp.triu(v, k=diagonal), ensure_tensor(x))


def meshgrid(*args, **kwargs):
    tensors = args[0] if len(args) == 1 and isinstance(args[0], (list, tuple)) else args
    vals = jnp.meshgrid(*[unwrap(ensure_tensor(t)) for t in tensors], indexing="ij")
    return [_wrap_value(v) for v in vals]


def clone(x, name=None):
    return op(lambda v: v + jnp.zeros((), v.dtype), ensure_tensor(x))


def assign(x, output=None):
    val = _to_array(unwrap(x))
    if output is not None:
        output.set_value(val)
        return output
    return _wrap_value(val)


def tril_indices(row, col, offset=0, dtype="int64"):
    r, c = jnp.tril_indices(row, k=offset, m=col)
    return _wrap_value(jnp.stack([r, c]).astype(to_jax_dtype(dtype)))


def triu_indices(row, col=None, offset=0, dtype="int64"):
    r, c = jnp.triu_indices(row, k=offset, m=col or row)
    return _wrap_value(jnp.stack([r, c]).astype(to_jax_dtype(dtype)))


def complex(real, imag, name=None):
    return op(lambda r, i: jax.lax.complex(r, i), ensure_tensor(real), ensure_tensor(imag))


# ---- random creation (parity: python/paddle/tensor/random.py) ------------


def rand(shape, dtype=None, name=None):
    return uniform(shape, dtype=dtype, min=0.0, max=1.0)


def randn(shape, dtype=None, name=None):
    return standard_normal(shape, dtype=dtype)


def standard_normal(shape, dtype=None, name=None):
    key = _random.split_key()
    dt = to_jax_dtype(dtype or get_default_dtype())
    return _wrap_value(jax.random.normal(key, _shape_list(shape), dtype=dt))


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m, s = ensure_tensor(mean), ensure_tensor(std)
        shp = jnp.broadcast_shapes(tuple(m.shape), tuple(s.shape))
        key = _random.split_key()
        noise_dt = m._value.dtype if jnp.issubdtype(m._value.dtype, jnp.floating) else jnp.float32
        noise = jax.random.normal(key, shp, dtype=noise_dt)
        return op(lambda mv, sv: mv + sv * noise, m, s)
    key = _random.split_key()
    dt = to_jax_dtype(get_default_dtype())
    return _wrap_value(mean + std * jax.random.normal(key, _shape_list(shape or [1]), dtype=dt))


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    key = jax.random.key(seed) if seed else _random.split_key()
    dt = to_jax_dtype(dtype or get_default_dtype())
    return _wrap_value(jax.random.uniform(key, _shape_list(shape), dtype=dt, minval=min, maxval=max))


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None):
    if high is None:
        low, high = 0, low
    key = _random.split_key()
    return _wrap_value(jax.random.randint(key, _shape_list(shape), low, high, dtype=to_jax_dtype(dtype)))


def randint_like(x, low=0, high=None, dtype=None, name=None):
    x = ensure_tensor(x)
    return randint(low, high, shape=x.shape, dtype=dtype or x.dtype)


def randperm(n, dtype="int64", name=None):
    key = _random.split_key()
    return _wrap_value(jax.random.permutation(key, n).astype(to_jax_dtype(dtype)))


def bernoulli(x, name=None):
    x = ensure_tensor(x)
    key = _random.split_key()
    return _wrap_value(jax.random.bernoulli(key, unwrap(x)).astype(x._value.dtype))


def poisson(x, name=None):
    x = ensure_tensor(x)
    key = _random.split_key()
    return _wrap_value(jax.random.poisson(key, unwrap(x)).astype(x._value.dtype))


def multinomial(x, num_samples=1, replacement=False, name=None):
    x = ensure_tensor(x)
    key = _random.split_key()
    v = unwrap(x)
    logits = jnp.log(jnp.maximum(v, 1e-30))
    if replacement:
        out = jax.random.categorical(key, logits, axis=-1, shape=(*v.shape[:-1], num_samples))
    else:
        # Gumbel top-k trick for sampling without replacement
        g = jax.random.gumbel(key, v.shape)
        _, out = jax.lax.top_k(logits + g, num_samples)
    return _wrap_value(out.astype(to_jax_dtype("int64")))


def uniform_(x, min=-1.0, max=1.0, seed=0, name=None):
    """In-place uniform refill (reference paddle.Tensor.uniform_)."""
    from .manipulation import _inplace

    x = ensure_tensor(x)
    return _inplace("uniform_", x,
                    lambda v: uniform(tuple(v.shape), str(v._value.dtype), min, max, seed))


def exponential_(x, lam=1.0, name=None):
    """In-place Exponential(lam) refill (reference paddle.Tensor.exponential_)."""
    from ..framework import random as _random
    from .manipulation import _inplace

    x = ensure_tensor(x)

    def fill(v):
        import jax

        key = _random.split_key()
        u = jax.random.uniform(key, tuple(v.shape), jnp.float32, 1e-7, 1.0)
        return op(lambda _: (-jnp.log(u) / lam).astype(v._value.dtype), v, _name="exponential_")

    return _inplace("exponential_", x, fill)
