"""Deterministic fault injection for the fault-tolerance runtime.

Production code calls the hooks below at its failure seams (checkpoint
publish, TCPStore ops, elastic heartbeats, the supervised train loop).
With ``FLAGS_chaos`` off — the default — every hook is a single dict
lookup; tests turn individual failure modes on through flags (or the
``inject`` context manager) and get the *same* failure on every run:
no randomness, no timing races.

Injection points:

- **crash-at-step**: ``crash_if_due(point, step)`` raises ``ChaosCrash``
  when ``FLAGS_chaos_crash_point`` matches and ``FLAGS_chaos_crash_at_step``
  is the current step (-1 = first hit). Each (point, step) fires at most
  once per process, so a supervisor that restarts the step can make
  progress — exactly the preempted-worker shape.
- **corrupt-checkpoint-on-disk**: ``corrupt_due()`` tells the
  CheckpointManager to flip bytes in the checkpoint it just published.
- **drop/delay store ops**: ``store_op(op, key)`` raises ``ChaosError``
  for ops matching ``FLAGS_chaos_store_drop_ops`` ('op' or
  'op:key-prefix' specs), healing after ``FLAGS_chaos_store_drop_count``
  failures; ``FLAGS_chaos_store_delay_s`` adds latency to every op.
- **freeze heartbeat**: ``heartbeat_frozen(node_id)`` silences an
  ElasticNode's refresh thread — the node stays up but looks dead to
  the membership view (a zombie/partitioned host).
- **NaN gradients in-graph**: ``nan_grads_due()`` tells a compiling
  ``jit.TrainStep`` to fuse a deterministic non-finite-gradient
  injection into its program (``FLAGS_chaos_nan_at_step``; an armed
  budget carried in the step state makes it fire exactly once per
  process, even across ``run_steps`` scans and divergence rollbacks).
- **kill / slow a serving replica**: ``replica_kill_due(rid, tick)`` is
  True exactly once when ``FLAGS_chaos_replica_kill_at`` ('R:K') names
  replica R and it has served K decode ticks — the fleet turns it into a
  mid-stream replica death (drain + requeue); ``replica_slow_ms(rid)``
  reads ``FLAGS_chaos_replica_slow_ms`` ('MS' or 'R:MS') as per-tick
  injected latency (a straggler the heartbeat tracker must catch).
- **real-process replica faults** (the cross-process fleet):
  ``replica_sigkill_due(rid, tick)`` is True exactly once when
  ``FLAGS_chaos_replica_sigkill_at`` ('R:K') names replica R and the
  parent has harvested K of its tick messages — the ProcServingFleet
  supervisor answers True by sending the child a real SIGKILL (no
  exception, no cleanup: the kill -9 the requeue ledger must survive);
  ``replica_hang_due_ms(rid)`` reads ``FLAGS_chaos_replica_hang_ms``
  ('MS' or 'R:MS') exactly once as a heartbeat blackout — the child stays
  alive but stops beating for MS milliseconds (a zombie only the parent's
  stale-beat sweep can catch, since the process never exits).
- **network faults** (ingress + socket fast path, PR 20):
  ``socket_drop_due(rid, nsent)`` is True exactly once when
  ``FLAGS_chaos_socket_drop_at`` ('R:K' or 'K') says the fast-path socket
  should die right before replica R's K-th socket send — the
  SocketChannel answers True by killing the connection, which must
  degrade to the store transport with zero chunk loss or duplication;
  ``ingress_disconnect_due(nchunks)`` is True exactly once per process
  when ``FLAGS_chaos_ingress_disconnect_at`` chunks have been streamed —
  the ingress answers True by dropping the client socket (the
  disconnect -> mid-decode cancel path); ``net_delay_ms()`` adds
  deterministic latency before every fast-path frame send.
"""
from __future__ import annotations

import contextlib
import time

from ..framework.flags import flag, get_flags, set_flags


class ChaosError(OSError):
    """Injected transient store failure (an OSError so production retry
    paths treat it exactly like a real socket error)."""


class ChaosCrash(RuntimeError):
    """Injected process death. Raised (not os._exit) so single-process
    tests can drive multi-process recovery protocols end-to-end."""


_fired: set = set()  # (point, step) crash points that already fired
_dropped: dict = {}  # drop spec -> count of failures injected so far


def _emit_inject(step=None, **payload):
    """Record the injected fault in the structured run log (chaos fires are
    exactly the events a postmortem wants timestamped)."""
    from ..observability import runlog

    runlog.emit("chaos_inject", step=step, **payload)


def reset():
    """Forget fired crash points and drop counters (fresh experiment)."""
    _fired.clear()
    _dropped.clear()


def enabled() -> bool:
    return bool(flag("FLAGS_chaos"))


def crash_if_due(point: str, step=None):
    """Raise ChaosCrash when ``point`` is armed (at most once per
    (point, step) per process)."""
    if not enabled() or flag("FLAGS_chaos_crash_point") != point:
        return
    at = flag("FLAGS_chaos_crash_at_step")
    if at >= 0 and step is not None and step != at:
        return
    # keyed by the ARMED step (not the current one) so '-1: first hit'
    # fires exactly once per point instead of once per visited step
    key = (point, at if at >= 0 else None)
    if key in _fired:
        return
    _fired.add(key)
    _emit_inject(kind="crash", point=point, step=step)
    raise ChaosCrash(f"chaos: injected crash at point {point!r} step {step}")


def corrupt_due() -> bool:
    return enabled() and bool(flag("FLAGS_chaos_corrupt_ckpt"))


def store_op(op: str, key: str):
    """Called by TCPStore before each wire op; may delay or fail it."""
    if not enabled():
        return
    delay = flag("FLAGS_chaos_store_delay_s")
    if delay > 0:
        time.sleep(delay)
    specs = [s for s in flag("FLAGS_chaos_store_drop_ops").split(",") if s]
    limit = flag("FLAGS_chaos_store_drop_count")
    for spec in specs:
        sop, _, prefix = spec.partition(":")
        if sop != op or (prefix and not key.startswith(prefix)):
            continue
        n = _dropped.get(spec, 0)
        if limit >= 0 and n >= limit:
            return  # healed: budget of injected failures spent
        _dropped[spec] = n + 1
        _emit_inject(kind="store_drop", op=op, key=key)
        raise ChaosError(f"chaos: dropped store op {op}({key!r}) "
                         f"[{n + 1}{'/' + str(limit) if limit >= 0 else ''}]")


def nan_grads_due():
    """``(step, n_steps)`` when the in-graph NaN-gradient injection is armed
    (FLAGS_chaos + FLAGS_chaos_nan_at_step >= 0), else None. Read by
    ``jit.TrainStep`` at construction — the injection compiles into the step
    program, so arming after the TrainStep is built has no effect."""
    if not enabled():
        return None
    at = flag("FLAGS_chaos_nan_at_step")
    if at < 0:
        return None
    n = max(int(flag("FLAGS_chaos_nan_steps")), 1)
    _emit_inject(step=at, kind="nan_grads", n_steps=n)
    return int(at), n


def replica_kill_due(replica_id, tick) -> bool:
    """True — exactly once per (replica, process) — when
    ``FLAGS_chaos_replica_kill_at`` ('R:K') names ``replica_id`` and it has
    served at least K decode ticks. The serving fleet answers True with a
    :class:`ChaosCrash` replica death (mark dead, drain, requeue)."""
    if not enabled():
        return False
    spec = flag("FLAGS_chaos_replica_kill_at")
    if not spec:
        return False
    rid, _, at = spec.partition(":")
    if str(replica_id) != rid or int(tick) < int(at or 0):
        return False
    key = ("replica_kill", str(replica_id))
    if key in _fired:
        return False
    _fired.add(key)
    _emit_inject(kind="replica_kill", replica=replica_id, tick=int(tick))
    return True


def replica_sigkill_due(replica_id, tick) -> bool:
    """True — exactly once per (replica, process) — when
    ``FLAGS_chaos_replica_sigkill_at`` ('R:K') names ``replica_id`` and the
    parent supervisor has harvested at least K of its tick messages. The
    cross-process fleet answers True with a real ``SIGKILL`` to the child:
    no exception path, no drain — the process is simply gone, which is the
    failure the exactly-once requeue ledger exists for."""
    if not enabled():
        return False
    spec = flag("FLAGS_chaos_replica_sigkill_at")
    if not spec:
        return False
    rid, _, at = spec.partition(":")
    if str(replica_id) != rid or int(tick) < int(at or 0):
        return False
    key = ("replica_sigkill", str(replica_id))
    if key in _fired:
        return False
    _fired.add(key)
    _emit_inject(kind="replica_sigkill", replica=replica_id, tick=int(tick))
    return True


def replica_hang_due_ms(replica_id) -> float:
    """Heartbeat blackout in milliseconds — nonzero exactly once per
    (replica, process) — when ``FLAGS_chaos_replica_hang_ms`` ('MS' for
    every replica, 'R:MS' for one) names ``replica_id``. The subprocess
    replica answers a nonzero return by suppressing its heartbeat
    publications for that long WITHOUT exiting: process liveness stays
    green, only the stale-beat sweep can tell it's wedged."""
    if not enabled():
        return 0.0
    spec = flag("FLAGS_chaos_replica_hang_ms")
    if not spec:
        return 0.0
    rid, sep, ms = spec.partition(":")
    if sep:
        if str(replica_id) != rid:
            return 0.0
        ms = float(ms)
    else:
        ms = float(rid)
    key = ("replica_hang", str(replica_id))
    if ms <= 0 or key in _fired:
        return 0.0
    _fired.add(key)
    _emit_inject(kind="replica_hang", replica=replica_id, hang_ms=ms)
    return ms


def replica_slow_ms(replica_id) -> float:
    """Injected per-tick latency in milliseconds for ``replica_id``:
    ``FLAGS_chaos_replica_slow_ms`` is 'MS' (every replica) or 'R:MS' (one).
    0.0 when chaos is off or the spec names another replica."""
    if not enabled():
        return 0.0
    spec = flag("FLAGS_chaos_replica_slow_ms")
    if not spec:
        return 0.0
    rid, sep, ms = spec.partition(":")
    if not sep:
        return float(rid)
    return float(ms) if str(replica_id) == rid else 0.0


def socket_drop_due(replica_id, nsent) -> bool:
    """True — exactly once per (replica, process) — when
    ``FLAGS_chaos_socket_drop_at`` ('R:K' for replica R, bare 'K' for any
    replica) says the fast-path socket should die right before the K-th
    socket send. The SocketChannel writer answers True by killing its
    connection mid-stream — the degradation the store fallback must
    absorb without losing or duplicating a chunk."""
    if not enabled():
        return False
    spec = flag("FLAGS_chaos_socket_drop_at")
    if not spec:
        return False
    rid, sep, at = spec.partition(":")
    if sep:
        if str(replica_id) != rid:
            return False
        at = int(at or 0)
    else:
        at = int(rid)
    if int(nsent) < at:
        return False
    key = ("socket_drop", str(replica_id))
    if key in _fired:
        return False
    _fired.add(key)
    _emit_inject(kind="socket_drop", replica=replica_id, nsent=int(nsent))
    return True


def ingress_disconnect_due(nchunks) -> bool:
    """True — exactly once per process — when the ingress has streamed
    ``FLAGS_chaos_ingress_disconnect_at`` chunks to a client. The ingress
    answers True by force-closing the client socket, which must turn into
    a mid-decode ``cancel()`` that frees the slot."""
    if not enabled():
        return False
    at = int(flag("FLAGS_chaos_ingress_disconnect_at"))
    if at < 0 or int(nchunks) < at:
        return False
    key = ("ingress_disconnect",)
    if key in _fired:
        return False
    _fired.add(key)
    _emit_inject(kind="ingress_disconnect", chunks=int(nchunks))
    return True


def net_delay_ms() -> float:
    """Deterministic latency (milliseconds) injected before every
    fast-path socket frame send; 0.0 when chaos is off."""
    if not enabled():
        return 0.0
    return float(flag("FLAGS_chaos_net_delay_ms"))


def heartbeat_frozen(node_id) -> bool:
    if not enabled():
        return False
    frozen = flag("FLAGS_chaos_freeze_heartbeat")
    return frozen != "" and str(node_id) in frozen.split(",")


@contextlib.contextmanager
def inject(**overrides):
    """Temporarily set chaos flags (FLAGS_chaos is implied on), e.g.::

        with chaos.inject(FLAGS_chaos_store_drop_ops="get"):
            ...

    Restores previous flag values and resets counters on exit.
    """
    overrides.setdefault("FLAGS_chaos", True)
    prev = get_flags(list(overrides))
    reset()
    set_flags(overrides)
    try:
        yield
    finally:
        set_flags(prev)
        reset()
