"""paddle_tpu.testing — test-support utilities (deterministic fault
injection lives in ``testing.chaos``)."""
from . import chaos  # noqa: F401
