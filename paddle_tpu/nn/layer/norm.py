"""Normalization layers (parity: python/paddle/nn/layer/norm.py)."""
from __future__ import annotations

import jax.numpy as jnp

from ...framework.core import _wrap_value
from .. import functional as F
from .. import initializer as I
from .base import Layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        ns = normalized_shape if isinstance(normalized_shape, (list, tuple)) else [normalized_shape]
        self.normalized_shape = list(ns)
        self.epsilon = epsilon
        self.weight = None if weight_attr is False else self.create_parameter(ns, attr=weight_attr, default_initializer=I.Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(ns, attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.layer_norm(x, self.normalized_shape, self.weight, self.bias, self.epsilon)


class RMSNorm(Layer):
    """Not in the reference snapshot; standard for modern LLM blocks."""

    def __init__(self, hidden_size, epsilon=1e-6, name=None):
        super().__init__()
        self.epsilon = epsilon
        self.weight = self.create_parameter([hidden_size], default_initializer=I.Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, self.epsilon)


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, weight_attr=None, bias_attr=None, data_format="NCHW", use_global_stats=None, name=None):
        super().__init__()
        self.num_features, self.momentum, self.epsilon = num_features, momentum, epsilon
        self.data_format, self.use_global_stats = data_format, use_global_stats
        self.weight = None if weight_attr is False else self.create_parameter([num_features], attr=weight_attr, default_initializer=I.Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter([num_features], attr=bias_attr, is_bias=True)
        self.register_buffer("_mean", _wrap_value(jnp.zeros([num_features])))
        self.register_buffer("_variance", _wrap_value(jnp.ones([num_features])))

    def forward(self, x):
        return F.batch_norm(
            x, self._mean, self._variance, self.weight, self.bias,
            training=self.training, momentum=self.momentum, epsilon=self.epsilon,
            data_format=self.data_format, use_global_stats=self.use_global_stats,
        )


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, weight_attr=None, bias_attr=None, data_format="NCL", use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr, bias_attr, "NCL", use_global_stats)


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, weight_attr=None, bias_attr=None, data_format="NCDHW", use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr, bias_attr, "NCDHW", use_global_stats)


class SyncBatchNorm(_BatchNormBase):
    """On TPU under pjit, batch-stat reductions over a sharded batch axis
    compile to psums across the mesh — sync is automatic (see
    nn/functional/norm.py docstring). This class exists for API parity with
    paddle.nn.SyncBatchNorm (python/paddle/nn/layer/norm.py:1059)."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        # parity helper: swap BatchNorm* instances for SyncBatchNorm
        for name, sub in list(layer._sub_layers.items()):
            if isinstance(sub, _BatchNormBase) and not isinstance(sub, SyncBatchNorm):
                new = SyncBatchNorm(sub.num_features, sub.momentum, sub.epsilon, data_format=sub.data_format)
                new.weight, new.bias = sub.weight, sub.bias
                new._buffers = sub._buffers
                layer._sub_layers[name] = new
            else:
                cls.convert_sync_batchnorm(sub)
        return layer


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5, weight_attr=None, bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self.num_groups, self.epsilon, self.data_format = num_groups, epsilon, data_format
        self.weight = None if weight_attr is False else self.create_parameter([num_channels], attr=weight_attr, default_initializer=I.Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter([num_channels], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self.num_groups, self.epsilon, self.weight, self.bias, self.data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9, weight_attr=None, bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self.epsilon = epsilon
        self.weight = None if weight_attr is False else self.create_parameter([num_features], attr=weight_attr, default_initializer=I.Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter([num_features], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias, eps=self.epsilon)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW", name=None):
        super().__init__()
        self.size, self.alpha, self.beta, self.k = size, alpha, beta, k
        self.data_format = data_format

    def forward(self, x):
        return F.local_response_norm(x, self.size, self.alpha, self.beta, self.k, self.data_format)


class SpectralNorm(Layer):
    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12, name=None):
        super().__init__()
        raise NotImplementedError("SpectralNorm: planned (round 2)")
