"""Recurrent layers (parity: python/paddle/nn/layer/rnn.py).

TPU-first: the time loop is a ``jax.lax.scan`` inside one primitive — XLA
compiles the whole sequence as a single fused loop (the reference's cuDNN RNN
kernels, paddle/phi/kernels/gpu/rnn_kernel.cu, have no other TPU analog).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.core import Tensor, _wrap_value
from ...tensor._helpers import ensure_tensor, op, unwrap
from .. import initializer as I
from .base import Layer


def _rnn_scan(cell_step, x, h0, time_major=False):
    # x: [B, T, I] (batch-major) -> scan over T
    def step(carry, xt):
        new_carry, out = cell_step(carry, xt)
        return new_carry, out

    xs = x if time_major else jnp.swapaxes(x, 0, 1)
    carry, outs = jax.lax.scan(step, h0, xs)
    outs = outs if time_major else jnp.swapaxes(outs, 0, 1)
    return outs, carry


class SimpleRNNCell(Layer):
    def __init__(self, input_size, hidden_size, activation="tanh", weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.hidden_size = hidden_size
        std = 1.0 / hidden_size**0.5
        u = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter([hidden_size, input_size], attr=weight_ih_attr, default_initializer=u)
        self.weight_hh = self.create_parameter([hidden_size, hidden_size], attr=weight_hh_attr, default_initializer=u)
        self.bias_ih = self.create_parameter([hidden_size], attr=bias_ih_attr, default_initializer=u)
        self.bias_hh = self.create_parameter([hidden_size], attr=bias_hh_attr, default_initializer=u)
        self.activation = activation

    def forward(self, inputs, states=None):
        h = states
        if h is None:
            from ...tensor.creation import zeros

            h = zeros([inputs.shape[0], self.hidden_size])
        act = jnp.tanh if self.activation == "tanh" else jax.nn.relu

        def fn(x, hh, wih, whh, bih, bhh):
            return act(x @ wih.T + bih + hh @ whh.T + bhh)

        out = op(fn, ensure_tensor(inputs), ensure_tensor(h), self.weight_ih, self.weight_hh, self.bias_ih, self.bias_hh, _name="rnn_cell")
        return out, out


class LSTMCell(Layer):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.hidden_size = hidden_size
        std = 1.0 / hidden_size**0.5
        u = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter([4 * hidden_size, input_size], attr=weight_ih_attr, default_initializer=u)
        self.weight_hh = self.create_parameter([4 * hidden_size, hidden_size], attr=weight_hh_attr, default_initializer=u)
        self.bias_ih = self.create_parameter([4 * hidden_size], attr=bias_ih_attr, default_initializer=u)
        self.bias_hh = self.create_parameter([4 * hidden_size], attr=bias_hh_attr, default_initializer=u)

    def forward(self, inputs, states=None):
        if states is None:
            from ...tensor.creation import zeros

            b = inputs.shape[0]
            states = (zeros([b, self.hidden_size]), zeros([b, self.hidden_size]))
        h, c = states

        def fn(x, hh, cc, wih, whh, bih, bhh):
            gates = x @ wih.T + bih + hh @ whh.T + bhh
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
            g = jnp.tanh(g)
            c2 = f * cc + i * g
            h2 = o * jnp.tanh(c2)
            return h2, c2

        h2, c2 = op(fn, ensure_tensor(inputs), ensure_tensor(h), ensure_tensor(c), self.weight_ih, self.weight_hh, self.bias_ih, self.bias_hh, _name="lstm_cell")
        return h2, (h2, c2)


class GRUCell(Layer):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.hidden_size = hidden_size
        std = 1.0 / hidden_size**0.5
        u = I.Uniform(-std, std)
        self.weight_ih = self.create_parameter([3 * hidden_size, input_size], attr=weight_ih_attr, default_initializer=u)
        self.weight_hh = self.create_parameter([3 * hidden_size, hidden_size], attr=weight_hh_attr, default_initializer=u)
        self.bias_ih = self.create_parameter([3 * hidden_size], attr=bias_ih_attr, default_initializer=u)
        self.bias_hh = self.create_parameter([3 * hidden_size], attr=bias_hh_attr, default_initializer=u)

    def forward(self, inputs, states=None):
        if states is None:
            from ...tensor.creation import zeros

            states = zeros([inputs.shape[0], self.hidden_size])
        h = states

        def fn(x, hh, wih, whh, bih, bhh):
            gi = x @ wih.T + bih
            gh = hh @ whh.T + bhh
            ir, iz, ic = jnp.split(gi, 3, axis=-1)
            hr, hz, hc = jnp.split(gh, 3, axis=-1)
            r = jax.nn.sigmoid(ir + hr)
            z = jax.nn.sigmoid(iz + hz)
            c = jnp.tanh(ic + r * hc)
            return (1 - z) * c + z * hh

        h2 = op(fn, ensure_tensor(inputs), ensure_tensor(h), self.weight_ih, self.weight_hh, self.bias_ih, self.bias_hh, _name="gru_cell")
        return h2, h2


class _RNNBase(Layer):
    MODE = "RNN"

    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward", time_major=False, dropout=0.0, **kwargs):
        super().__init__()
        self.input_size, self.hidden_size, self.num_layers = input_size, hidden_size, num_layers
        self.time_major = time_major
        self.direction = direction
        self.bidirect = direction in ("bidirect", "bidirectional")
        ndir = 2 if self.bidirect else 1
        self.ndir = ndir
        std = 1.0 / hidden_size**0.5
        u = I.Uniform(-std, std)
        g = {"RNN": 1, "GRU": 3, "LSTM": 4}[self.MODE]
        self._g = g
        for l in range(num_layers):
            for d in range(ndir):
                in_sz = input_size if l == 0 else hidden_size * ndir
                self.add_parameter(f"weight_ih_l{l}_d{d}", self.create_parameter([g * hidden_size, in_sz], default_initializer=u))
                self.add_parameter(f"weight_hh_l{l}_d{d}", self.create_parameter([g * hidden_size, hidden_size], default_initializer=u))
                self.add_parameter(f"bias_ih_l{l}_d{d}", self.create_parameter([g * hidden_size], default_initializer=u))
                self.add_parameter(f"bias_hh_l{l}_d{d}", self.create_parameter([g * hidden_size], default_initializer=u))

    def _cell(self, gates_fn, wih, whh, bih, bhh):
        def step(carry, xt):
            return gates_fn(carry, xt, wih, whh, bih, bhh)

        return step

    def _gates(self, carry, xt, wih, whh, bih, bhh):
        if self.MODE == "RNN":
            h = carry
            h2 = jnp.tanh(xt @ wih.T + bih + h @ whh.T + bhh)
            return h2, h2
        if self.MODE == "GRU":
            h = carry
            gi = xt @ wih.T + bih
            gh = h @ whh.T + bhh
            ir, iz, ic = jnp.split(gi, 3, axis=-1)
            hr, hz, hc = jnp.split(gh, 3, axis=-1)
            r = jax.nn.sigmoid(ir + hr)
            z = jax.nn.sigmoid(iz + hz)
            c = jnp.tanh(ic + r * hc)
            h2 = (1 - z) * c + z * h
            return h2, h2
        h, c = carry
        gates = xt @ wih.T + bih + h @ whh.T + bhh
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        g = jnp.tanh(g)
        c2 = f * c + i * g
        h2 = o * jnp.tanh(c2)
        return (h2, c2), h2

    def forward(self, inputs, initial_states=None, sequence_length=None):
        x = ensure_tensor(inputs)
        b = x.shape[0] if not self.time_major else x.shape[1]
        params = []
        for l in range(self.num_layers):
            for d in range(self.ndir):
                params += [
                    getattr(self, f"weight_ih_l{l}_d{d}"),
                    getattr(self, f"weight_hh_l{l}_d{d}"),
                    getattr(self, f"bias_ih_l{l}_d{d}"),
                    getattr(self, f"bias_hh_l{l}_d{d}"),
                ]

        mode = self.MODE
        nl, nd, hs, tm = self.num_layers, self.ndir, self.hidden_size, self.time_major

        def fn(xv, *pv):
            def zero_state():
                if mode == "LSTM":
                    return (jnp.zeros((b, hs), xv.dtype), jnp.zeros((b, hs), xv.dtype))
                return jnp.zeros((b, hs), xv.dtype)

            out = xv
            final_h, final_c = [], []
            pi = 0
            for l in range(nl):
                dir_outs = []
                for d in range(nd):
                    wih, whh, bih, bhh = pv[pi : pi + 4]
                    pi += 4
                    seq = out if tm else jnp.swapaxes(out, 0, 1)
                    if d == 1:
                        seq = jnp.flip(seq, axis=0)

                    def step(carry, xt, wih=wih, whh=whh, bih=bih, bhh=bhh):
                        return self._gates(carry, xt, wih, whh, bih, bhh)

                    carry, outs = jax.lax.scan(step, zero_state(), seq)
                    if d == 1:
                        outs = jnp.flip(outs, axis=0)
                    dir_outs.append(outs if tm else jnp.swapaxes(outs, 0, 1))
                    if mode == "LSTM":
                        final_h.append(carry[0])
                        final_c.append(carry[1])
                    else:
                        final_h.append(carry)
                out = dir_outs[0] if nd == 1 else jnp.concatenate(dir_outs, axis=-1)
            hstack = jnp.stack(final_h, axis=0)
            if mode == "LSTM":
                return out, hstack, jnp.stack(final_c, axis=0)
            return out, hstack

        res = op(fn, x, *params, _name=f"{mode.lower()}")
        if mode == "LSTM":
            out, h, c = res
            return out, (h, c)
        out, h = res
        return out, h


class SimpleRNN(_RNNBase):
    MODE = "RNN"


class LSTM(_RNNBase):
    MODE = "LSTM"


class GRU(_RNNBase):
    MODE = "GRU"


class RNN(Layer):
    """Wrap a cell into a scan-based runner (parity: paddle.nn.RNN)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse, self.time_major = is_reverse, time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        x = ensure_tensor(inputs)
        T = x.shape[0] if self.time_major else x.shape[1]
        outs = []
        states = initial_states
        rng = range(T - 1, -1, -1) if self.is_reverse else range(T)
        for tstep in rng:
            xt = x[tstep] if self.time_major else x[:, tstep]
            out, states = self.cell(xt, states)
            outs.append(out)
        if self.is_reverse:
            outs = outs[::-1]
        from ...tensor.manipulation import stack

        return stack(outs, axis=0 if self.time_major else 1), states
