"""Layer tail closing the paddle.nn API diff: pixel/channel shuffles, Fold,
MaxUnPool, Softmax2D, ThresholdedReLU, PairwiseDistance, CTCLoss,
HSigmoidLoss, BiRNN, RNNCellBase, BeamSearchDecoder + dynamic_decode.

Parity anchors: python/paddle/nn/layer/{vision,common,activation,distance,
loss,rnn}.py and fluid/layers/rnn.py (BeamSearchDecoder/dynamic_decode).
"""
from __future__ import annotations

import numpy as np

from ...tensor._helpers import ensure_tensor
from .base import Layer


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.r, self.df = upscale_factor, data_format

    def forward(self, x):
        from .. import functional as F

        return F.pixel_shuffle(x, self.r, self.df)


class PixelUnshuffle(Layer):
    def __init__(self, downscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.r, self.df = downscale_factor, data_format

    def forward(self, x):
        from .. import functional as F

        return F.pixel_unshuffle(x, self.r, self.df)


class ChannelShuffle(Layer):
    def __init__(self, groups, data_format="NCHW", name=None):
        super().__init__()
        self.g, self.df = groups, data_format

    def forward(self, x):
        from .. import functional as F

        return F.channel_shuffle(x, self.g, self.df)


class Fold(Layer):
    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
        super().__init__()
        self.a = (output_sizes, kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        from .. import functional as F

        return F.fold(x, *self.a)


class _MaxUnPoolBase(Layer):
    _fn = None

    def __init__(self, kernel_size, stride=None, padding=0, data_format=None, output_size=None, name=None):
        super().__init__()
        self.kernel_size, self.stride, self.padding = kernel_size, stride, padding
        self.output_size = output_size

    def forward(self, x, indices):
        from .. import functional as F

        return getattr(F, self._fn)(x, indices, self.kernel_size, self.stride,
                                    self.padding, output_size=self.output_size)


class MaxUnPool1D(_MaxUnPoolBase):
    _fn = "max_unpool1d"


class MaxUnPool2D(_MaxUnPoolBase):
    _fn = "max_unpool2d"


class MaxUnPool3D(_MaxUnPoolBase):
    _fn = "max_unpool3d"


class Softmax2D(Layer):
    """Softmax over the channel axis of NCHW input (reference Softmax2D)."""

    def forward(self, x):
        from .. import functional as F

        return F.softmax(x, axis=-3)


class ThresholdedReLU(Layer):
    def __init__(self, threshold=1.0, name=None):
        super().__init__()
        self.threshold = threshold

    def forward(self, x):
        from .. import functional as F

        return F.thresholded_relu(x, self.threshold)


class PairwiseDistance(Layer):
    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p, self.eps, self.keepdim = p, epsilon, keepdim

    def forward(self, x, y):
        import jax.numpy as jnp

        from ...tensor._helpers import op

        return op(lambda a, b: jnp.sum(jnp.abs(a - b + self.eps) ** self.p, axis=-1,
                                       keepdims=self.keepdim) ** (1.0 / self.p),
                  ensure_tensor(x), ensure_tensor(y), _name="pairwise_distance")


class CTCLoss(Layer):
    def __init__(self, blank=0, reduction="mean"):
        super().__init__()
        self.blank, self.reduction = blank, reduction

    def forward(self, logits, labels, input_lengths, label_lengths, norm_by_times=False):
        from .. import functional as F

        return F.ctc_loss(logits, labels, input_lengths, label_lengths,
                          self.blank, self.reduction)


class HSigmoidLoss(Layer):
    def __init__(self, feature_size, num_classes, weight_attr=None, bias_attr=None, is_custom=False, is_sparse=False, name=None):
        super().__init__()
        if is_custom:
            raise NotImplementedError("custom trees not supported; default tree only")
        self.num_classes = num_classes
        self.weight = self.create_parameter([num_classes - 1, feature_size], attr=weight_attr)
        self.bias = None if bias_attr is False else self.create_parameter(
            [num_classes - 1, 1], attr=bias_attr, is_bias=True)

    def forward(self, input, label):
        from .. import functional as F

        return F.hsigmoid_loss(input, label, self.num_classes, self.weight, self.bias)


# -- RNN tail ----------------------------------------------------------------

from .rnn import RNN  # noqa: E402


class RNNCellBase(Layer):
    """Base for user cells (reference rnn.py RNNCellBase): provides
    get_initial_states."""

    def get_initial_states(self, batch_ref, shape=None, dtype=None, init_value=0.0, batch_dim_idx=0):
        import jax.numpy as jnp

        from ...framework.core import _wrap_value

        batch = ensure_tensor(batch_ref).shape[batch_dim_idx]
        hidden = shape if shape is not None else getattr(self, "state_shape", None)

        def build(shp):
            return _wrap_value(jnp.full((batch,) + tuple(int(d) for d in shp), init_value,
                                        jnp.float32))

        if isinstance(hidden, (list, tuple)) and hidden and isinstance(hidden[0], (list, tuple)):
            return tuple(build(s) for s in hidden)
        return build(tuple(hidden))


class BiRNN(Layer):
    """Bidirectional cell wrapper (reference nn.BiRNN): runs forward and
    reverse cells, concatenates outputs on the feature axis."""

    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.fw = RNN(cell_fw, is_reverse=False, time_major=time_major)
        self.bw = RNN(cell_bw, is_reverse=True, time_major=time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ...tensor.manipulation import concat

        st_fw, st_bw = (initial_states if initial_states is not None else (None, None))
        out_fw, s_fw = self.fw(inputs, st_fw, sequence_length)
        out_bw, s_bw = self.bw(inputs, st_bw, sequence_length)
        return concat([out_fw, out_bw], axis=-1), (s_fw, s_bw)


class BeamSearchDecoder:
    """Beam-search decoding over a cell (reference fluid/layers/rnn.py
    BeamSearchDecoder). Host-driven loop (decode lengths are data
    dependent); the cell/embedding/output projections run on device."""

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start, self.end, self.beam = int(start_token), int(end_token), int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn


def dynamic_decode(decoder, inits=None, max_step_num=32, **kwargs):
    """Greedy-beam decode loop (reference fluid/layers/rnn.py
    dynamic_decode): returns (ids [B, beam, T], per-beam scores)."""
    import jax.numpy as jnp

    from ...framework.core import _wrap_value

    cell, K = decoder.cell, decoder.beam
    state = inits
    # infer batch from state leaves
    leaves = state if isinstance(state, (list, tuple)) else [state]
    B = ensure_tensor(leaves[0]).shape[0]

    def logits_of(tok, st):
        x = tok
        if decoder.embedding_fn is not None:
            x = decoder.embedding_fn(x)
        out, new_st = cell(x, st)
        if decoder.output_fn is not None:
            out = decoder.output_fn(out)
        return out, new_st

    import jax

    # step 0: expand each batch item into K beams
    tok0 = _wrap_value(jnp.full((B,), decoder.start, jnp.int32))
    out, state = logits_of(tok0, state)
    lp0 = jax.nn.log_softmax(jnp.asarray(ensure_tensor(out)._value, jnp.float32), axis=-1)
    scores, toks = jax.lax.top_k(lp0, K)  # [B, K]
    seqs = [[[int(toks[b, k])] for k in range(K)] for b in range(B)]
    beam_scores = np.asarray(scores)
    # replicate the POST-start-token state per beam
    def rep(t):
        v = ensure_tensor(t)._value
        return _wrap_value(jnp.repeat(v, K, axis=0))

    state = tuple(rep(s) for s in state) if isinstance(state, (list, tuple)) else rep(state)
    finished = np.zeros((B, K), bool)

    for _ in range(max_step_num - 1):
        if finished.all():
            break
        flat_tok = _wrap_value(jnp.asarray(
            [seqs[b][k][-1] for b in range(B) for k in range(K)], jnp.int32))
        out, state = logits_of(flat_tok, state)
        lp = jax.nn.log_softmax(jnp.asarray(ensure_tensor(out)._value, jnp.float32), axis=-1)
        V = lp.shape[-1]
        lp = np.asarray(lp).reshape(B, K, V)
        new_seqs, new_scores, sel_beams = [], [], []
        for b in range(B):
            cand = []
            for k in range(K):
                if finished[b, k]:
                    cand.append((beam_scores[b, k], k, decoder.end))
                    continue
                top = np.argsort(lp[b, k])[-K:]
                for t in top:
                    cand.append((beam_scores[b, k] + lp[b, k, t], k, int(t)))
            cand.sort(key=lambda c: -c[0])
            picked = cand[:K]
            new_seqs.append([seqs[b][k] + ([t] if not finished[b, k] else []) for _, k, t in picked])
            new_scores.append([s for s, _, _ in picked])
            sel_beams.append([k for _, k, _ in picked])
        # reorder states to the selected beams
        idx = jnp.asarray([b * K + k for b in range(B) for k in sel_beams[b]])

        def reorder(t):
            return _wrap_value(jnp.take(ensure_tensor(t)._value, idx, axis=0))

        state = tuple(reorder(s) for s in state) if isinstance(state, tuple) else reorder(state)
        seqs = new_seqs
        beam_scores = np.asarray(new_scores)
        for b in range(B):
            for k in range(K):
                if seqs[b][k] and seqs[b][k][-1] == decoder.end:
                    finished[b, k] = True

    T = max(len(s) for bs in seqs for s in bs)
    ids = np.full((B, K, T), decoder.end, np.int64)
    for b in range(B):
        for k in range(K):
            ids[b, k, : len(seqs[b][k])] = seqs[b][k]
    return _wrap_value(jnp.asarray(ids)), _wrap_value(jnp.asarray(beam_scores, jnp.float32))
