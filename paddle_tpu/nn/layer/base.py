"""Layer base class.

Parity: ``paddle.nn.Layer`` (reference:
python/paddle/fluid/dygraph/layers.py:84) — parameters, buffers, sublayers,
state_dict, train/eval, forward hooks. TPU-first addition: every Layer is
also a *functional* module — ``layer.functional()`` returns
``(apply_fn, params)`` where apply_fn is pure and jit/pjit-able; parameters
carry optional ``dist_spec`` (a PartitionSpec) consumed by the distributed
jit path (GSPMD), replacing the reference's per-layer collective calls.
"""
from __future__ import annotations

import contextlib
from collections import OrderedDict
from typing import Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

from ...framework.core import Tensor, _wrap_value
from ...framework.dtype import get_default_dtype
from .. import initializer as I


class Parameter(Tensor):
    """Trainable tensor (parity: paddle.fluid.framework.Parameter)."""

    def _init_from_value(self, value, name=""):
        self._init(value, stop_gradient=False, name=name)
        self.dist_spec = None  # optional jax PartitionSpec for pjit sharding
        self.is_distributed = False


def _make_param(value, name=""):
    p = Parameter.__new__(Parameter)
    p._init_from_value(value, name)
    return p


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        self._parameters: "OrderedDict[str, Parameter]" = OrderedDict()
        self._buffers: "OrderedDict[str, Tensor]" = OrderedDict()
        self._sub_layers: "OrderedDict[str, Layer]" = OrderedDict()
        self._non_persistable_buffer_names = set()
        self._forward_pre_hooks: "OrderedDict[int, Callable]" = OrderedDict()
        self._forward_post_hooks: "OrderedDict[int, Callable]" = OrderedDict()
        self.training = True
        self._dtype = dtype
        self._name = name_scope or type(self).__name__

    # -- construction -----------------------------------------------------
    def create_parameter(self, shape, dtype=None, default_initializer=None, attr=None, is_bias=False):
        dtype = dtype or self._dtype or get_default_dtype()
        # precedence (reference set_global_initializer semantics): explicit
        # ParamAttr.initializer > global initializer > layer default
        init = None
        if attr is not None and getattr(attr, "initializer", None) is not None:
            init = attr.initializer
        if init is None:
            init = I._global_initializer(is_bias)
        if init is None:
            init = default_initializer
        if init is None:
            init = I.Constant(0.0) if is_bias else I.XavierNormal()
        value = init(shape, dtype)
        p = _make_param(value)
        if attr is not None:
            if getattr(attr, "name", None):
                p.name = attr.name
            if getattr(attr, "trainable", True) is False:
                p.stop_gradient = True
                p.trainable = False
        return p

    def create_tensor(self, value=None, dtype=None):
        import jax.numpy as jnp

        from ...framework.dtype import to_jax_dtype

        if value is None:
            value = jnp.zeros((), to_jax_dtype(dtype or self._dtype))
        return _wrap_value(value)

    def add_parameter(self, name, parameter):
        if parameter is None:
            self._parameters[name] = None
        else:
            self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer):
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def register_buffer(self, name, tensor, persistable=True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    # -- attribute routing -------------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter) and params is not None:
            for d in (layers, buffers):
                if d is not None and name in d:
                    del d[name]
            self.__dict__.pop(name, None)
            params[name] = value
        elif isinstance(value, Layer) and layers is not None:
            for d in (params, buffers):
                if d is not None and name in d:
                    del d[name]
            self.__dict__.pop(name, None)
            layers[name] = value
        else:
            for d in (params, layers, buffers):
                if d is not None and name in d:
                    del d[name]
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(f"{type(self).__name__!r} object has no attribute {name!r}")

    def __delattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    # -- iteration ---------------------------------------------------------
    def named_parameters(self, prefix="", include_sublayers=True) -> Iterator[Tuple[str, Parameter]]:
        for name, p in self._parameters.items():
            if p is not None:
                yield (f"{prefix}.{name}" if prefix else name), p
        if include_sublayers:
            for lname, layer in self._sub_layers.items():
                if layer is None:
                    continue
                sub_prefix = f"{prefix}.{lname}" if prefix else lname
                yield from layer.named_parameters(prefix=sub_prefix)

    def parameters(self, include_sublayers=True) -> List[Parameter]:
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        for name, b in self._buffers.items():
            if b is not None:
                yield (f"{prefix}.{name}" if prefix else name), b
        if include_sublayers:
            for lname, layer in self._sub_layers.items():
                if layer is None:
                    continue
                sub_prefix = f"{prefix}.{lname}" if prefix else lname
                yield from layer.named_buffers(prefix=sub_prefix)

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def named_sublayers(self, prefix="", include_self=False):
        if include_self:
            yield prefix, self
        for name, layer in self._sub_layers.items():
            if layer is None:
                continue
            sub_prefix = f"{prefix}.{name}" if prefix else name
            yield sub_prefix, layer
            yield from layer.named_sublayers(prefix=sub_prefix)

    def sublayers(self, include_self=False):
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def children(self):
        return iter(l for l in self._sub_layers.values() if l is not None)

    def named_children(self):
        return iter((n, l) for n, l in self._sub_layers.items() if l is not None)

    def apply(self, fn):
        for layer in self.children():
            layer.apply(fn)
        fn(self)
        return self

    # -- mode --------------------------------------------------------------
    def train(self):
        self.training = True
        for layer in self.children():
            layer.train()
        return self

    def eval(self):
        self.training = False
        for layer in self.children():
            layer.eval()
        return self

    # -- state dict --------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True, structured_name_prefix=""):
        dest = destination if destination is not None else OrderedDict()
        for name, p in self.named_parameters(prefix=structured_name_prefix.rstrip(".")):
            dest[name] = p
        for name, b in self.named_buffers(prefix=structured_name_prefix.rstrip(".")):
            short = name.rsplit(".", 1)[-1]
            # skip non-persistable buffers (parity: layers.py state_dict)
            owner = self._locate_owner(name)
            if owner is not None and short in owner._non_persistable_buffer_names:
                continue
            dest[name] = b
        return dest

    def _locate_owner(self, qualified_name):
        parts = qualified_name.split(".")[:-1]
        layer = self
        for p in parts:
            nxt = layer._sub_layers.get(p)
            if nxt is None:
                return None
            layer = nxt
        return layer

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing, unexpected = [], []
        for name, t in own.items():
            if name in state_dict:
                src = state_dict[name]
                value = src._value if isinstance(src, Tensor) else np.asarray(src)
                t.set_value(value)
            else:
                missing.append(name)
        for name in state_dict:
            if name not in own:
                unexpected.append(name)
        return missing, unexpected

    load_dict = set_state_dict

    # -- dtype/device ------------------------------------------------------
    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            self._cast_all(dtype)
        return self

    def astype(self, dtype):
        self._cast_all(dtype)
        return self

    def float(self):
        return self.astype("float32")

    def bfloat16(self):
        return self.astype("bfloat16")

    def half(self):
        return self.astype("float16")

    def _cast_all(self, dtype):
        from ...framework.dtype import to_jax_dtype
        import jax.numpy as jnp

        jdt = to_jax_dtype(dtype)
        for p in self.parameters():
            if jnp.issubdtype(p._value.dtype, jnp.floating):
                p._value = p._value.astype(jdt)
        for b in self.buffers():
            if jnp.issubdtype(b._value.dtype, jnp.floating):
                b._value = b._value.astype(jdt)

    # -- hooks -------------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        handle = _HookHandle(self._forward_pre_hooks)
        self._forward_pre_hooks[handle.id] = hook
        return handle

    def register_forward_post_hook(self, hook):
        handle = _HookHandle(self._forward_post_hooks)
        self._forward_post_hooks[handle.id] = hook
        return handle

    # -- call --------------------------------------------------------------
    def forward(self, *args, **kwargs):
        raise NotImplementedError

    def __call__(self, *args, **kwargs):
        for hook in self._forward_pre_hooks.values():
            result = hook(self, args)
            if result is not None:
                args = result if isinstance(result, tuple) else (result,)
        out = self.forward(*args, **kwargs)
        for hook in self._forward_post_hooks.values():
            result = hook(self, args, out)
            if result is not None:
                out = result
        return out

    # -- functional bridge (TPU-first; see module docstring) ---------------
    def raw_state(self) -> Dict[str, "np.ndarray"]:
        """name -> raw jax.Array for all params+buffers (the jit pytree)."""
        out = {}
        for name, p in self.named_parameters():
            out[name] = p._value
        for name, b in self.named_buffers():
            out[name] = b._value
        return out

    def param_arrays(self) -> Dict[str, "np.ndarray"]:
        return {name: p._value for name, p in self.named_parameters()}

    def buffer_arrays(self) -> Dict[str, "np.ndarray"]:
        return {name: b._value for name, b in self.named_buffers()}

    def dist_specs(self):
        """name -> PartitionSpec (or None) for every parameter."""
        return {name: getattr(p, "dist_spec", None) for name, p in self.named_parameters()}

    @contextlib.contextmanager
    def bind(self, arrays: Dict[str, object]):
        """Temporarily replace param/buffer values with ``arrays`` (tracers
        under jit). The layer's forward then runs functionally."""
        handles = {}
        for name, p in self.named_parameters():
            if name in arrays:
                handles[name] = (p, p._value)
                p._value = arrays[name]
        for name, b in self.named_buffers():
            if name in arrays:
                handles[name] = (b, b._value)
                b._value = arrays[name]
        try:
            yield self
        finally:
            for t, old in handles.values():
                t._value = old

    def functional(self):
        """Return ``(apply_fn, params, buffers)``; ``apply_fn(params, buffers,
        *args, training=False, rng=None)`` is pure and jit-able."""
        from ..functional_api import functional_call

        params = self.param_arrays()
        buffers = self.buffer_arrays()

        def apply_fn(params, buffers, *args, training=False, rng=None, **kwargs):
            return functional_call(self, {**params, **buffers}, *args, training=training, rng=rng, **kwargs)

        return apply_fn, params, buffers

    def __repr__(self):
        extra = []
        for name, layer in self._sub_layers.items():
            extra.append(f"  ({name}): {layer!r}".replace("\n", "\n  "))
        head = type(self).__name__
        if not extra:
            return f"{head}()"
        return head + "(\n" + "\n".join(extra) + "\n)"


class _HookHandle:
    _next_id = [0]

    def __init__(self, store):
        self.id = _HookHandle._next_id[0]
        _HookHandle._next_id[0] += 1
        self._store = store

    def remove(self):
        self._store.pop(self.id, None)
