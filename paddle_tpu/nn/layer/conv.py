"""Convolution layers (parity: python/paddle/nn/layer/conv.py)."""
from __future__ import annotations

import numpy as np

from .. import functional as F
from .. import initializer as I
from .base import Layer


class _ConvNd(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, n, stride=1, padding=0, dilation=1, groups=1, padding_mode="zeros", weight_attr=None, bias_attr=None, data_format="NCHW", transposed=False, output_padding=0):
        super().__init__()
        ks = kernel_size if isinstance(kernel_size, (list, tuple)) else [kernel_size] * n
        self.in_channels, self.out_channels = in_channels, out_channels
        self.kernel_size, self.stride, self.padding = ks, stride, padding
        self.dilation, self.groups, self.data_format = dilation, groups, data_format
        self.output_padding = output_padding
        self._n = n
        self._transposed = transposed
        if transposed:
            shape = [in_channels, out_channels // groups, *ks]
        else:
            shape = [out_channels, in_channels // groups, *ks]
        fan_in = in_channels // groups * int(np.prod(ks))
        std = (2.0 / fan_in) ** 0.5
        self.weight = self.create_parameter(shape, attr=weight_attr, default_initializer=I.Normal(0.0, std))
        self.bias = None
        if bias_attr is not False:
            self.bias = self.create_parameter([out_channels], attr=bias_attr, is_bias=True)


class Conv1D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0, dilation=1, groups=1, padding_mode="zeros", weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, stride, padding, dilation, groups, padding_mode, weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F.conv1d(x, self.weight, self.bias, self.stride, self.padding, self.dilation, self.groups, self.data_format)


class Conv2D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0, dilation=1, groups=1, padding_mode="zeros", weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride, padding, dilation, groups, padding_mode, weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F.conv2d(x, self.weight, self.bias, self.stride, self.padding, self.dilation, self.groups, self.data_format)


class Conv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0, dilation=1, groups=1, padding_mode="zeros", weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride, padding, dilation, groups, padding_mode, weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F.conv3d(x, self.weight, self.bias, self.stride, self.padding, self.dilation, self.groups, self.data_format)


class Conv1DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0, output_padding=0, groups=1, dilation=1, weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, stride, padding, dilation, groups, "zeros", weight_attr, bias_attr, data_format, transposed=True, output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv1d_transpose(x, self.weight, self.bias, self.stride, self.padding, self.output_padding, self.dilation, self.groups, output_size, self.data_format)


class Conv2DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0, output_padding=0, groups=1, dilation=1, weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride, padding, dilation, groups, "zeros", weight_attr, bias_attr, data_format, transposed=True, output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv2d_transpose(x, self.weight, self.bias, self.stride, self.padding, self.output_padding, self.dilation, self.groups, output_size, self.data_format)


class Conv3DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0, output_padding=0, groups=1, dilation=1, weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride, padding, dilation, groups, "zeros", weight_attr, bias_attr, data_format, transposed=True, output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv3d_transpose(x, self.weight, self.bias, self.stride, self.padding, self.output_padding, self.dilation, self.groups, output_size, self.data_format)
