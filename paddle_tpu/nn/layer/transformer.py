"""Transformer layers (parity: python/paddle/nn/layer/transformer.py).

The attention core routes through F.scaled_dot_product_attention, which
dispatches to the Pallas flash kernel on TPU — this subsumes the reference's
fused CUDA attention (paddle/fluid/operators/fused/fused_attention_op.cu) and
the incubate FusedMultiHeadAttention wrapper
(python/paddle/incubate/nn/layer/fused_transformer.py:136).

Incremental decoding (reference transformer.py:284 ``gen_cache`` /
``Cache``/``StaticCache``): every layer accepts ``cache=`` and, when given
one, returns ``(output, updated_cache)`` with the newly projected K/V
concatenated on the sequence axis — the reference's fused_multi_transformer
decode semantics. The concat grows the cache by one position per token: a
NEW shape (and, under jit, a new compiled program) every step. For serving,
``gen_cache(..., static=True, max_seq=N)`` returns a :class:`FixedCache`
instead — a preallocated ``[b, max_seq, h, d]`` device buffer updated via
``lax.dynamic_update_slice`` at a traced position index, so every decode
step has identical shapes and one compiled program serves the whole
sequence. For the fully-compiled decode loop see ``models/gpt.py
GPTForPretraining.generate`` and ``paddle_tpu.inference.DecodeEngine``.
"""
from __future__ import annotations

import collections

from ...tensor import manipulation as M
from ...tensor._helpers import op as _op
from .. import functional as F
from .. import initializer as I
from .base import Layer
from .common import Dropout, Linear
from .container import LayerList
from .norm import LayerNorm


def _fixed_cache_write(cache, k_new, v_new):
    """Write ``k_new``/``v_new`` [b, s, h, d] into a :class:`FixedCache` at
    its position index (``lax.dynamic_update_slice`` at a traced scalar —
    shapes never change, so a jitted decode step compiles once)."""
    import jax.lax as lax

    upd = lambda c, u, p: lax.dynamic_update_slice(c, u, (0, p, 0, 0))  # noqa: E731
    k = _op(upd, cache.k, k_new, cache.pos, _name="kv_cache_update")
    v = _op(upd, cache.v, v_new, cache.pos, _name="kv_cache_update")
    return k, v


def _quant_cache_write(qbuf, sbuf, u_new, pos):
    """Quantize ``u_new`` [b, s, h, d] to int8 with per-(b, s, h) abs_max
    scales and write BOTH planes of a :class:`QuantizedFixedCache` buffer
    pair at ``pos`` — the cache stores the quantized representation only
    (int8 payload + f32 scale plane), never a full-precision copy."""
    import jax.lax as lax
    import jax.numpy as jnp

    def scales(u):
        f = u.astype(jnp.float32)
        return jnp.maximum(jnp.max(jnp.abs(f), axis=-1), 1e-8) / 127.0

    def wq(qb, u, p):
        s = scales(u)
        q = jnp.clip(jnp.round(u.astype(jnp.float32) / s[..., None]), -127, 127)
        return lax.dynamic_update_slice(qb, q.astype(jnp.int8), (0, p, 0, 0))

    def ws(sb, u, p):
        return lax.dynamic_update_slice(sb, scales(u), (0, p, 0))

    return (_op(wq, qbuf, u_new, pos, _name="kv_cache_update"),
            _op(ws, sbuf, u_new, pos, _name="kv_cache_update"))


def _quant_cache_read(qbuf, sbuf, dt):
    """Dequantized [b, max_seq, h, d] view of a quantized cache plane pair
    in compute dtype ``dt`` (XLA folds the scale multiply into the consuming
    attention matmul — HBM only ever holds the int8 payload + scales)."""
    import jax.numpy as jnp

    def rd(q, s):
        return (q.astype(jnp.float32) * s[..., None]).astype(jnp.dtype(dt))

    return _op(rd, qbuf, sbuf, _name="kv_cache_dequant")


def _fixed_cache_mask(pos, s, max_seq):
    """Bool [s, max_seq] attention mask for a FixedCache read: query row i
    (absolute position pos+i) sees keys at positions <= pos+i; preallocated
    positions beyond the write frontier stay invisible."""
    import jax.lax as lax
    import jax.numpy as jnp

    def build(p):
        k_pos = lax.broadcasted_iota(jnp.int32, (s, max_seq), 1)
        q_pos = p + lax.broadcasted_iota(jnp.int32, (s, max_seq), 0)
        return k_pos <= q_pos

    return _op(build, pos, _name="kv_cache_mask")


class MultiHeadAttention(Layer):
    """Parity: paddle.nn.MultiHeadAttention (transformer.py:77)."""

    Cache = collections.namedtuple("Cache", ["k", "v"])
    StaticCache = collections.namedtuple("StaticCache", ["k", "v"])
    # Serving-path incremental cache: preallocated [b, max_seq, h, d] K/V
    # plus the scalar write position. Unlike Cache (concat-grown), shapes
    # are constant for the whole decode, so exactly one compiled program
    # serves every step.
    FixedCache = collections.namedtuple("FixedCache", ["k", "v", "pos"])
    # int8-quantized serving cache: same fixed-shape discipline as
    # FixedCache but HBM holds int8 payloads (qk/qv) + per-(b, pos, h)
    # f32 abs_max scale planes (sk/sv) — ~4x smaller at large head_dim;
    # dequant happens on read, folded into the attention matmul.
    QuantizedFixedCache = collections.namedtuple(
        "QuantizedFixedCache", ["qk", "sk", "qv", "sv", "pos"])

    def __init__(self, embed_dim, num_heads, dropout=0.0, kdim=None, vdim=None, need_weights=False, weight_attr=None, bias_attr=None):
        super().__init__()
        self.embed_dim, self.num_heads = embed_dim, num_heads
        self.head_dim = embed_dim // num_heads
        assert self.head_dim * num_heads == embed_dim
        self.dropout = dropout
        self.need_weights = need_weights
        kdim = kdim or embed_dim
        vdim = vdim or embed_dim
        self.q_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)
        self.k_proj = Linear(kdim, embed_dim, weight_attr, bias_attr)
        self.v_proj = Linear(vdim, embed_dim, weight_attr, bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)

    def _proj_kv(self, key, value):
        b = key.shape[0]
        k = M.reshape(self.k_proj(key), [b, -1, self.num_heads, self.head_dim])
        v = M.reshape(self.v_proj(value), [b, -1, self.num_heads, self.head_dim])
        return k, v

    def gen_cache(self, key, value=None, type=None, static=False, max_seq=None,
                  kv_dtype=None):
        """Parity: transformer.py:284. ``type=StaticCache`` precomputes the
        cross-attention K/V from ``key``/``value``; ``type=Cache`` (default)
        starts an empty incremental self-attention cache.

        ``static=True`` starts a :class:`FixedCache` instead: K/V are
        preallocated ``[b, max_seq, h, d]`` zeros written in place at the
        carried position — every decode step keeps identical shapes, so the
        dygraph loop (or a jitted step over it) compiles exactly once
        instead of once per sequence length. ``kv_dtype="int8"`` (static
        only) starts a :class:`QuantizedFixedCache` — the buffers hold int8
        payloads + f32 abs_max scale planes instead of compute-dtype K/V."""
        if static:
            if max_seq is None:
                raise ValueError("gen_cache(static=True) needs max_seq=")
            b = key.shape[0]
            from ...tensor.creation import zeros

            if kv_dtype is not None:
                if str(kv_dtype) != "int8":
                    raise ValueError(f"kv_dtype must be None or 'int8', got {kv_dtype!r}")
                qz = lambda: zeros([b, int(max_seq), self.num_heads, self.head_dim], dtype="int8")  # noqa: E731
                sz = lambda: zeros([b, int(max_seq), self.num_heads], dtype="float32")  # noqa: E731
                return self.QuantizedFixedCache(qz(), sz(), qz(), sz(),
                                                zeros([], dtype="int32"))
            dt = key.dtype
            empty = lambda: zeros([b, int(max_seq), self.num_heads, self.head_dim], dtype=dt)  # noqa: E731
            return self.FixedCache(empty(), empty(), zeros([], dtype="int32"))
        type = type or self.Cache
        if type is self.StaticCache:
            k, v = self._proj_kv(key, value if value is not None else key)
            return self.StaticCache(k, v)
        b = key.shape[0]
        from ...tensor.creation import zeros

        dt = key.dtype
        empty = lambda: zeros([b, 0, self.num_heads, self.head_dim], dtype=dt)
        return self.Cache(empty(), empty())

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        key = query if key is None else key
        value = key if value is None else value
        b = query.shape[0]
        q = M.reshape(self.q_proj(query), [b, -1, self.num_heads, self.head_dim])
        if isinstance(cache, self.StaticCache):
            k, v = cache.k, cache.v
        elif isinstance(cache, self.FixedCache):
            # static-shape incremental decode: write the new K/V at the
            # carried position, attend over the full buffer under the
            # position mask (attn_mask is ignored on this path — the cache
            # mask IS the causal structure)
            k_new, v_new = self._proj_kv(key, value)
            s = q.shape[1]
            k, v = _fixed_cache_write(cache, k_new, v_new)
            attn_mask = _fixed_cache_mask(cache.pos, s, k.shape[1])
            out = F.scaled_dot_product_attention(q, k, v, attn_mask=attn_mask, dropout_p=self.dropout, training=self.training)
            out = M.reshape(out, [b, -1, self.embed_dim])
            return self.out_proj(out), self.FixedCache(k, v, cache.pos + s)
        elif isinstance(cache, self.QuantizedFixedCache):
            # quantized static decode: quantize-on-write (both planes),
            # dequantize-on-read into the attention matmul — the cache
            # round-trips int8 end-to-end, never holding f32 K/V in HBM
            k_new, v_new = self._proj_kv(key, value)
            s = q.shape[1]
            qk, sk = _quant_cache_write(cache.qk, cache.sk, k_new, cache.pos)
            qv, sv = _quant_cache_write(cache.qv, cache.sv, v_new, cache.pos)
            k = _quant_cache_read(qk, sk, q.dtype)
            v = _quant_cache_read(qv, sv, q.dtype)
            attn_mask = _fixed_cache_mask(cache.pos, s, k.shape[1])
            out = F.scaled_dot_product_attention(q, k, v, attn_mask=attn_mask, dropout_p=self.dropout, training=self.training)
            out = M.reshape(out, [b, -1, self.embed_dim])
            return self.out_proj(out), self.QuantizedFixedCache(qk, sk, qv, sv, cache.pos + s)
        else:
            k, v = self._proj_kv(key, value)
            if isinstance(cache, self.Cache):
                if cache.k.shape[1] > 0:
                    k = M.concat([cache.k, k], axis=1)
                    v = M.concat([cache.v, v], axis=1)
                cache = self.Cache(k, v)
        out = F.scaled_dot_product_attention(q, k, v, attn_mask=attn_mask, dropout_p=self.dropout, training=self.training)
        out = M.reshape(out, [b, -1, self.embed_dim])
        out = self.out_proj(out)
        if cache is not None:
            return out, cache
        return out


class TransformerEncoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1, activation="relu", attn_dropout=None, act_dropout=None, normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, dropout=attn_dropout if attn_dropout is not None else dropout)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.dropout_act = Dropout(act_dropout if act_dropout is not None else dropout)
        self.activation = activation

    def gen_cache(self, src):
        return self.self_attn.gen_cache(src)

    def forward(self, src, src_mask=None, cache=None):
        residual = src
        if self.normalize_before:
            src = self.norm1(src)
        if cache is None:
            src = self.self_attn(src, src, src, attn_mask=src_mask)
        else:
            src, cache = self.self_attn(src, src, src, attn_mask=src_mask, cache=cache)
        src = residual + self.dropout1(src)
        if not self.normalize_before:
            src = self.norm1(src)
        residual = src
        if self.normalize_before:
            src = self.norm2(src)
        act = getattr(F, self.activation)
        src = self.linear2(self.dropout_act(act(self.linear1(src))))
        src = residual + self.dropout2(src)
        if not self.normalize_before:
            src = self.norm2(src)
        if cache is not None:
            return src, cache
        return src


class TransformerEncoder(Layer):
    def __init__(self, encoder_layer, num_layers, norm=None):
        super().__init__()
        import copy

        self.layers = LayerList([encoder_layer] + [copy.deepcopy(encoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def gen_cache(self, src):
        return [layer.gen_cache(src) for layer in self.layers]

    def forward(self, src, src_mask=None, cache=None):
        out = src
        new_caches = []
        for i, layer in enumerate(self.layers):
            if cache is None:
                out = layer(out, src_mask=src_mask)
            else:
                out, c = layer(out, src_mask=src_mask, cache=cache[i])
                new_caches.append(c)
        if self.norm is not None:
            out = self.norm(out)
        if cache is not None:
            return out, new_caches
        return out


class TransformerDecoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1, activation="relu", attn_dropout=None, act_dropout=None, normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, dropout=attn_dropout if attn_dropout is not None else dropout)
        self.cross_attn = MultiHeadAttention(d_model, nhead, dropout=attn_dropout if attn_dropout is not None else dropout)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.norm3 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.dropout3 = Dropout(dropout)
        self.dropout_act = Dropout(act_dropout if act_dropout is not None else dropout)
        self.activation = activation

    def gen_cache(self, memory):
        """Parity: transformer.py:610 — (incremental self-attn cache,
        static cross-attn cache built from the encoder memory)."""
        incremental = self.self_attn.gen_cache(memory)
        static = self.cross_attn.gen_cache(memory, memory, type=MultiHeadAttention.StaticCache)
        return incremental, static

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None, cache=None):
        inc_cache, static_cache = cache if cache is not None else (None, None)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm1(tgt)
        if inc_cache is None:
            tgt = self.self_attn(tgt, tgt, tgt, attn_mask=tgt_mask)
        else:
            tgt, inc_cache = self.self_attn(tgt, tgt, tgt, attn_mask=tgt_mask, cache=inc_cache)
        tgt = residual + self.dropout1(tgt)
        if not self.normalize_before:
            tgt = self.norm1(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm2(tgt)
        if static_cache is None:
            tgt = self.cross_attn(tgt, memory, memory, attn_mask=memory_mask)
        else:
            tgt, _ = self.cross_attn(tgt, memory, memory, attn_mask=memory_mask, cache=static_cache)
        tgt = residual + self.dropout2(tgt)
        if not self.normalize_before:
            tgt = self.norm2(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm3(tgt)
        act = getattr(F, self.activation)
        tgt = self.linear2(self.dropout_act(act(self.linear1(tgt))))
        tgt = residual + self.dropout3(tgt)
        if not self.normalize_before:
            tgt = self.norm3(tgt)
        if cache is not None:
            return tgt, (inc_cache, static_cache)
        return tgt


class TransformerDecoder(Layer):
    def __init__(self, decoder_layer, num_layers, norm=None):
        super().__init__()
        import copy

        self.layers = LayerList([decoder_layer] + [copy.deepcopy(decoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def gen_cache(self, memory, do_zip=False):
        """Parity: transformer.py:721. ``do_zip`` transposes the per-layer
        (incremental, static) pairs for the reference's decoding loop."""
        cache = [layer.gen_cache(memory) for layer in self.layers]
        if do_zip:
            return list(zip(*cache))
        return cache

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None, cache=None):
        out = tgt
        new_caches = []
        for i, layer in enumerate(self.layers):
            if cache is None:
                out = layer(out, memory, tgt_mask=tgt_mask, memory_mask=memory_mask)
            else:
                out, c = layer(out, memory, tgt_mask=tgt_mask, memory_mask=memory_mask, cache=cache[i])
                new_caches.append(c)
        if self.norm is not None:
            out = self.norm(out)
        if cache is not None:
            return out, new_caches
        return out


class Transformer(Layer):
    def __init__(self, d_model=512, nhead=8, num_encoder_layers=6, num_decoder_layers=6, dim_feedforward=2048, dropout=0.1, activation="relu", attn_dropout=None, act_dropout=None, normalize_before=False, weight_attr=None, bias_attr=None, custom_encoder=None, custom_decoder=None):
        super().__init__()
        self.d_model, self.nhead = d_model, nhead
        if custom_encoder is not None:
            self.encoder = custom_encoder
        else:
            enc_layer = TransformerEncoderLayer(d_model, nhead, dim_feedforward, dropout, activation, attn_dropout, act_dropout, normalize_before, weight_attr, bias_attr)
            self.encoder = TransformerEncoder(enc_layer, num_encoder_layers, LayerNorm(d_model) if normalize_before else None)
        if custom_decoder is not None:
            self.decoder = custom_decoder
        else:
            dec_layer = TransformerDecoderLayer(d_model, nhead, dim_feedforward, dropout, activation, attn_dropout, act_dropout, normalize_before, weight_attr, bias_attr)
            self.decoder = TransformerDecoder(dec_layer, num_decoder_layers, LayerNorm(d_model) if normalize_before else None)

    def forward(self, src, tgt, src_mask=None, tgt_mask=None, memory_mask=None):
        memory = self.encoder(src, src_mask=src_mask)
        return self.decoder(tgt, memory, tgt_mask=tgt_mask, memory_mask=memory_mask)

    @staticmethod
    def generate_square_subsequent_mask(length):
        import jax.numpy as jnp

        from ...framework.core import _wrap_value

        mask = jnp.where(jnp.tril(jnp.ones((length, length), bool)), 0.0, -1e9)
        return _wrap_value(mask)
