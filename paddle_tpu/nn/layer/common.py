"""Common layers (parity: python/paddle/nn/layer/common.py)."""
from __future__ import annotations

import math

from ...framework.core import Tensor
from .. import functional as F
from .. import initializer as I
from .base import Layer


class Linear(Layer):
    """Parity: paddle.nn.Linear (weight [in, out])."""

    def __init__(self, in_features, out_features, weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.in_features, self.out_features = in_features, out_features
        self.weight = self.create_parameter([in_features, out_features], attr=weight_attr, default_initializer=I.XavierNormal())
        self.bias = None
        if bias_attr is not False:
            self.bias = self.create_parameter([out_features], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return f"in={self.in_features}, out={self.out_features}"


class Identity(Layer):
    def __init__(self, *args, **kwargs):
        super().__init__()

    def forward(self, x):
        return x


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p, self.axis, self.mode = p, axis, mode

    def forward(self, x):
        return F.dropout(x, p=self.p, axis=self.axis, training=self.training, mode=self.mode)


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p, self.data_format = p, data_format

    def forward(self, x):
        return F.dropout2d(x, p=self.p, training=self.training, data_format=self.data_format)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p, self.data_format = p, data_format

    def forward(self, x):
        return F.dropout3d(x, p=self.p, training=self.training, data_format=self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.alpha_dropout(x, p=self.p, training=self.training)


class Embedding(Layer):
    """Parity: paddle.nn.Embedding (python/paddle/nn/layer/common.py:1380)."""

    def __init__(self, num_embeddings, embedding_dim, padding_idx=None, sparse=False, weight_attr=None, name=None):
        super().__init__()
        self.num_embeddings, self.embedding_dim = num_embeddings, embedding_dim
        self.padding_idx = padding_idx
        self._sparse = bool(sparse)
        self.weight = self.create_parameter([num_embeddings, embedding_dim], attr=weight_attr, default_initializer=I.Normal(0.0, 1.0))

    def forward(self, x):
        out = F.embedding(x, self.weight, padding_idx=self.padding_idx)
        if self._sparse and not self.weight.stop_gradient:
            # SelectedRows contract: note which rows this batch touched so
            # SGD / Adam(lazy_mode) can update only those rows in eager mode
            # (framework/selected_rows.py). Only grad-producing forwards
            # count — rows from no_grad/eval lookups have zero grad and must
            # not be stepped. Inside a trace the grad is dense (XLA scatter).
            from ...framework.autograd import is_grad_enabled
            from ...framework.selected_rows import is_traced_value, record_rows
            from ...tensor._helpers import ensure_tensor

            ids = ensure_tensor(x)._value
            if is_grad_enabled() and not is_traced_value(ids):
                record_rows(self.weight, ids)
        return out


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis, self.stop_axis = start_axis, stop_axis

    def forward(self, x):
        from ...tensor.manipulation import flatten

        return flatten(x, self.start_axis, self.stop_axis)


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest", align_corners=False, data_format="NCHW", name=None):
        super().__init__()
        self.size, self.scale_factor, self.mode = size, scale_factor, mode
        self.align_corners, self.data_format = align_corners, data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, self.mode, self.align_corners, self.data_format)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW", name=None):
        super().__init__(size, scale_factor, "bilinear", True, data_format)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW", name=None):
        super().__init__(size, scale_factor, "nearest", False, data_format)


class Bilinear(Layer):
    def __init__(self, in1_features, in2_features, out_features, weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        bound = 1 / math.sqrt(in1_features)
        self.weight = self.create_parameter([out_features, in1_features, in2_features], attr=weight_attr, default_initializer=I.Uniform(-bound, bound))
        self.bias = None
        if bias_attr is not False:
            self.bias = self.create_parameter([out_features], attr=bias_attr, is_bias=True)

    def forward(self, x1, x2):
        return F.bilinear(x1, x2, self.weight, self.bias)


class _PadNd(Layer):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCHW"):
        super().__init__()
        self.padding, self.mode, self.value, self.data_format = padding, mode, value, data_format

    def forward(self, x):
        return F.pad(x, self.padding, self.mode, self.value, self.data_format)


class Pad1D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCL", name=None):
        super().__init__(padding, mode, value, data_format)


class Pad2D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCHW", name=None):
        super().__init__(padding, mode, value, data_format)


class Pad3D(_PadNd):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCDHW", name=None):
        super().__init__(padding, mode, value, data_format)


class ZeroPad2D(Pad2D):
    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__(padding, "constant", 0.0, data_format)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis, self.eps = axis, eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, axis=self.axis, eps=self.eps)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
        super().__init__()
        self.kernel_sizes, self.strides, self.paddings, self.dilations = kernel_sizes, strides, paddings, dilations

    def forward(self, x):
        return F.unfold(x, self.kernel_sizes, self.strides, self.paddings, self.dilations)
