from .activation import *  # noqa: F401,F403
from .base import Layer, Parameter  # noqa: F401
from .common import *  # noqa: F401,F403
from .container import LayerDict, LayerList, ParameterList, Sequential  # noqa: F401
from .conv import (  # noqa: F401
    Conv1D,
    Conv1DTranspose,
    Conv2D,
    Conv2DTranspose,
    Conv3D,
    Conv3DTranspose,
)
from .loss import *  # noqa: F401,F403
from .norm import (  # noqa: F401
    BatchNorm,
    BatchNorm1D,
    BatchNorm2D,
    BatchNorm3D,
    GroupNorm,
    InstanceNorm1D,
    InstanceNorm2D,
    InstanceNorm3D,
    LayerNorm,
    LocalResponseNorm,
    RMSNorm,
    SpectralNorm,
    SyncBatchNorm,
)
from .pooling import *  # noqa: F401,F403
from .rnn import GRU, GRUCell, LSTM, LSTMCell, RNN, SimpleRNN, SimpleRNNCell  # noqa: F401
from .transformer import (  # noqa: F401
    MultiHeadAttention,
    Transformer,
    TransformerDecoder,
    TransformerDecoderLayer,
    TransformerEncoder,
    TransformerEncoderLayer,
)
from .extension import (  # noqa: F401
    BeamSearchDecoder,
    BiRNN,
    ChannelShuffle,
    CTCLoss,
    dynamic_decode,
    Fold,
    HSigmoidLoss,
    MaxUnPool1D,
    MaxUnPool2D,
    MaxUnPool3D,
    PairwiseDistance,
    PixelShuffle,
    PixelUnshuffle,
    RNNCellBase,
    Softmax2D,
    ThresholdedReLU,
)
