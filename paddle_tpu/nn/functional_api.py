"""Functional bridge: run a Layer's forward as a pure function.

This is the dygraph→static seam. Parity: ``@paddle.jit.to_static`` +
``run_program`` op (reference: python/paddle/fluid/dygraph/dygraph_to_static/
program_translator.py:239, partial_program.py) — but TPU-first: no AST
transpiler; the layer's Python forward *is* the trace, parameters are bound
to traced values, the tape is disabled (grads come from jax.grad over this
pure function), and RNG is an explicit key.
"""
from __future__ import annotations

import contextlib

from ..framework import random as _random
from ..framework.autograd import no_grad
from ..framework.core import Tensor, _wrap_value, unwrap


def _wrap_tree(x):
    import jax

    if isinstance(x, Tensor):
        return x
    if isinstance(x, (list, tuple)):
        return type(x)(_wrap_tree(v) for v in x)
    if isinstance(x, dict):
        return {k: _wrap_tree(v) for k, v in x.items()}
    if isinstance(x, (jax.Array,)) or hasattr(x, "dtype"):
        return _wrap_value(x)
    return x


def unwrap_tree(x):
    if isinstance(x, Tensor):
        return x._value
    if isinstance(x, (list, tuple)):
        return type(x)(unwrap_tree(v) for v in x)
    if isinstance(x, dict):
        return {k: unwrap_tree(v) for k, v in x.items()}
    return x


def functional_call(layer, arrays, *args, training=False, rng=None, **kwargs):
    """Run ``layer(*args)`` with params/buffers replaced by ``arrays``.

    Pure w.r.t. ``arrays`` and ``args``; jit/grad-safe. ``rng`` (a PRNG key)
    feeds Dropout etc. via the rng scope.
    """
    modes = [(l, l.training) for l in layer.sublayers(include_self=True)]
    for l, _ in modes:
        l.training = training
    rng_ctx = _random.rng_scope(rng) if rng is not None else contextlib.nullcontext()
    try:
        with no_grad(), layer.bind(arrays), rng_ctx:
            out = layer(*_wrap_tree(list(args)), **kwargs)
    finally:
        for l, was in modes:
            l.training = was
    return unwrap_tree(out)
