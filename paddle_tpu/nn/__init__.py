"""paddle_tpu.nn (parity: python/paddle/nn)."""
from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from .clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue, clip_grad_norm_  # noqa: F401
from .functional_api import functional_call, unwrap_tree  # noqa: F401
from .layer import *  # noqa: F401,F403
from .layer import Layer, Parameter  # noqa: F401
from . import quant  # noqa: F401,E402 — paddle.nn.quant surface
