"""paddle.nn.quant parity (reference python/paddle/nn/quant/quant_layers.py):
the quantization layer surface re-exported from paddle_tpu.quantization —
fake-quant QAT wrappers and int8 inference layers, plus the functional
helpers the reference exposes here."""
import jax.numpy as jnp

from ...quantization import (  # noqa: F401
    ImperativeQuantAware,
    QATQuantizedConv2D,
    QATQuantizedLinear,
    QuantizedConv2D,
    QuantizedLinear,
    _qdq_ste,
    dequant,
    fake_quant,
    quant_abs_max,
)
from ...tensor._helpers import ensure_tensor, op
from ..layer.base import Layer


class FakeQuantAbsMax(Layer):
    """Standalone abs-max fake-quant layer (reference quant_layers.py
    FakeQuantAbsMax): quantize-dequantize the input by its own abs-max
    scale, straight-through in backward. Reference-compatible constructor
    (name/moving_rate/dtype accepted; abs-max needs no moving average)."""

    def __init__(self, name=None, moving_rate=0.9, quant_bits=8,
                 dtype="float32", quant_on_weight=False, reduce_type=None):
        super().__init__()
        if not 2 <= int(quant_bits) <= 8:
            raise ValueError("FakeQuantAbsMax supports quant_bits in [2, 8] "
                             f"(int8 QDQ grid), got {quant_bits}")
        self.quant_bits = int(quant_bits)

    def forward(self, x):
        bound = float(2 ** (self.quant_bits - 1) - 1)

        def fn(v):
            # _qdq_ste carries the straight-through vjp; its ±127 clip is a
            # no-op here because the dynamic abs-max scale already bounds
            # round(|v|/s) by `bound` <= 127
            s = jnp.maximum(jnp.abs(v).max(), 1e-8) / bound
            return _qdq_ste(v, s)

        return op(fn, ensure_tensor(x), _name="fake_quantize_abs_max")


class QuantizedConv2DTranspose(Layer):
    """Reference-compatible placeholder (quant_layers.py
    QuantizedConv2DTranspose). Int8 transposed conv is not implemented —
    QuantizedConv2D quantizes on the wrong channel axis for transposed
    weights, so aliasing it would be silently wrong."""

    def __init__(self, layer, weight_bits=8, activation_bits=8, *args, **kwargs):
        super().__init__()
        raise NotImplementedError(
            "QuantizedConv2DTranspose is not implemented in paddle_tpu: "
            "Conv2DTranspose weights are [in, out, kh, kw], so per-channel "
            "int8 scales need axis=1, which QuantizedConv2D does not do. "
            "Keep the layer in float, or quantize the surrounding layers.")
