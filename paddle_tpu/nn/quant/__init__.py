"""paddle.nn.quant parity (reference python/paddle/nn/quant/quant_layers.py):
the quantization layer surface re-exported from paddle_tpu.quantization —
fake-quant QAT wrappers and int8 inference layers, plus the functional
helpers the reference exposes here."""
from ...quantization import (  # noqa: F401
    ImperativeQuantAware,
    QATQuantizedConv2D,
    QATQuantizedLinear,
    QuantizedConv2D,
    QuantizedLinear,
    dequant,
    fake_quant,
    quant_abs_max,
)

# reference class-name aliases (quant_layers.py)
QuantizedConv2DTranspose = QuantizedConv2D
FakeQuantAbsMax = QATQuantizedLinear
