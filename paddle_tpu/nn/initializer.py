"""Weight initializers (parity: python/paddle/nn/initializer/*).

Initializers are callables ``(shape, dtype) -> jax.Array`` drawing from the
framework RNG (framework/random.py) so eager init is reproducible under
``paddle_tpu.seed``.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import random as _random
from ..framework.dtype import to_jax_dtype


class Initializer:
    def __call__(self, shape, dtype="float32"):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype="float32"):
        return jnp.full(tuple(shape), self.value, to_jax_dtype(dtype))


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype="float32"):
        key = _random.split_key()
        return self.mean + self.std * jax.random.normal(key, tuple(shape), to_jax_dtype(dtype))


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype="float32"):
        key = _random.split_key()
        return self.mean + self.std * jax.random.truncated_normal(key, -2.0, 2.0, tuple(shape), to_jax_dtype(dtype))


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype="float32"):
        key = _random.split_key()
        return jax.random.uniform(key, tuple(shape), to_jax_dtype(dtype), self.low, self.high)


def _fans(shape):
    shape = tuple(shape)
    if len(shape) < 1:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv kernels [out, in, *k] (paddle layout)
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype="float32"):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        key = _random.split_key()
        return std * jax.random.normal(key, tuple(shape), to_jax_dtype(dtype))


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype="float32"):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        key = _random.split_key()
        return jax.random.uniform(key, tuple(shape), to_jax_dtype(dtype), -limit, limit)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in, self.negative_slope, self.nonlinearity = fan_in, negative_slope, nonlinearity

    def __call__(self, shape, dtype="float32"):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope**2)) if self.nonlinearity in ("relu", "leaky_relu") else 1.0
        std = gain / math.sqrt(fi)
        key = _random.split_key()
        return std * jax.random.normal(key, tuple(shape), to_jax_dtype(dtype))


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in, self.negative_slope, self.nonlinearity = fan_in, negative_slope, nonlinearity

    def __call__(self, shape, dtype="float32"):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope**2)) if self.nonlinearity in ("relu", "leaky_relu") else 1.0
        limit = gain * math.sqrt(3.0 / fi)
        key = _random.split_key()
        return jax.random.uniform(key, tuple(shape), to_jax_dtype(dtype), -limit, limit)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype="float32"):
        from ..framework.core import unwrap

        arr = jnp.asarray(unwrap(self.value), to_jax_dtype(dtype))
        assert tuple(arr.shape) == tuple(shape), f"Assign shape {arr.shape} != {shape}"
        return arr


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype="float32"):
        key = _random.split_key()
        return self.gain * jax.nn.initializers.orthogonal()(key, tuple(shape), to_jax_dtype(dtype))


class Dirac(Initializer):
    def __init__(self, groups=1):
        self.groups = groups

    def __call__(self, shape, dtype="float32"):
        out = np.zeros(shape, np.float32)
        oc, ic = shape[0], shape[1]
        mins = min(oc // self.groups, ic)
        centers = [s // 2 for s in shape[2:]]
        for g in range(self.groups):
            for i in range(mins):
                out[(g * (oc // self.groups) + i, i, *centers)] = 1.0
        return jnp.asarray(out, to_jax_dtype(dtype))


class Bilinear(Initializer):
    """Bilinear-upsampling kernel init for transposed convs (reference
    nn/initializer/Bilinear): weight [c_out, c_in, kh, kw] filled with the
    separable triangle filter."""

    def __call__(self, shape, dtype="float32"):
        import numpy as np

        out = np.zeros(shape, np.float32)
        kh, kw = shape[-2], shape[-1]

        def tri(k):
            f = (k + 1) // 2
            c = (2 * f - 1 - f % 2) / (2.0 * f)
            return np.asarray([1 - abs(i / f - c) for i in range(k)])

        kern = np.outer(tri(kh), tri(kw))
        for i in range(shape[0]):
            out[i, min(i, shape[1] - 1)] = kern
        return jnp.asarray(out, to_jax_dtype(dtype))


_GLOBAL_INITIALIZER = {"weight": None, "bias": None}


def set_global_initializer(weight_init=None, bias_init=None):
    """Default initializers for subsequently created parameters (reference
    nn/initializer/set_global_initializer); pass None to reset."""
    _GLOBAL_INITIALIZER["weight"] = weight_init
    _GLOBAL_INITIALIZER["bias"] = bias_init


def _global_initializer(is_bias):
    return _GLOBAL_INITIALIZER["bias" if is_bias else "weight"]
