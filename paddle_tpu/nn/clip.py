"""Gradient clipping (parity: python/paddle/nn/clip.py — ClipGradByValue,
ClipGradByNorm, ClipGradByGlobalNorm).

Dual-form: each clip works eagerly on (param, grad Tensor) pairs and
functionally on a grads pytree (the jit train-step path).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


class ClipGradBase:
    def apply_tree(self, grads_tree):
        raise NotImplementedError

    def __call__(self, params_grads):
        """Eager form: list of (param, grad) Tensors -> same with clipped grads."""
        from .functional_api import unwrap_tree

        from ..framework.core import _wrap_value

        grads = {i: g._value for i, (_, g) in enumerate(params_grads) if g is not None}
        clipped = self.apply_tree(grads)
        out = []
        for i, (p, g) in enumerate(params_grads):
            if g is None:
                out.append((p, g))
            else:
                out.append((p, _wrap_value(clipped[i])))
        return out


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = max
        self.min = -max if min is None else min

    def apply_tree(self, grads_tree):
        return jax.tree_util.tree_map(lambda g: jnp.clip(g, self.min, self.max), grads_tree)


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = clip_norm

    def apply_tree(self, grads_tree):
        def clip_one(g):
            n = jnp.sqrt(jnp.sum(jnp.square(g.astype(jnp.float32))))
            scale = jnp.minimum(1.0, self.clip_norm / jnp.maximum(n, 1e-12))
            return (g.astype(jnp.float32) * scale).astype(g.dtype)

        return jax.tree_util.tree_map(clip_one, grads_tree)


class ClipGradByGlobalNorm(ClipGradBase):
    """Parity: python/paddle/nn/clip.py ClipGradByGlobalNorm. Under pjit the
    per-leaf square-sums over sharded grads compile to psums across the mesh,
    matching HybridParallelOptimizer's cross-group norm reduction
    (fleet/meta_optimizers/dygraph_optimizer/hybrid_parallel_optimizer.py:50)
    with zero extra code.

    NaN behavior (explicit, pinned by test): a non-finite global norm makes
    the clip scale non-finite, so every clipped gradient PROPAGATES as
    NaN — the clip never silently "fixes" a blown-up step by scaling it
    down. Downstream, the jit TrainStep guard (``guard=True``) detects the
    non-finite grads and skips the update bitwise."""

    def __init__(self, clip_norm=1.0, group_name="default_group"):
        self.clip_norm = clip_norm

    def apply_tree(self, grads_tree):
        leaves = jax.tree_util.tree_leaves(grads_tree)
        total = sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in leaves)
        gnorm = jnp.sqrt(total)
        scale = jnp.minimum(1.0, self.clip_norm / jnp.maximum(gnorm, 1e-12))
        return jax.tree_util.tree_map(lambda g: (g.astype(jnp.float32) * scale).astype(g.dtype), grads_tree)


def clip_grad_norm_(parameters, max_norm, norm_type=2.0, error_if_nonfinite=False):
    """torch-style utility over eager parameters with .grad set.

    ``error_if_nonfinite=True`` raises RuntimeError when the total norm is
    NaN/Inf (before touching any gradient); with the default False the
    non-finite norm flows through the scale like torch: a NaN norm makes
    every clipped gradient NaN, an Inf norm scales them to 0 — never a
    silent "fix". The downstream train guard / GradScaler is the layer
    expected to skip such a step.
    """
    params = [p for p in parameters if p.grad is not None]
    if not params:
        return None
    norm_type = float(norm_type)
    if norm_type == float("inf"):
        gnorm = jnp.max(jnp.stack(
            [jnp.max(jnp.abs(p.grad._value.astype(jnp.float32))) for p in params]))
    elif norm_type == 2.0:
        total = sum(jnp.sum(jnp.square(p.grad._value.astype(jnp.float32))) for p in params)
        gnorm = jnp.sqrt(total)
    else:
        total = sum(jnp.sum(jnp.abs(p.grad._value.astype(jnp.float32)) ** norm_type)
                    for p in params)
        gnorm = total ** (1.0 / norm_type)
    if error_if_nonfinite and not bool(jnp.isfinite(gnorm)):
        raise RuntimeError(
            f"The total norm of order {norm_type} for gradients from "
            "`parameters` is non-finite, so it cannot be clipped. To disable "
            "this error and scale the gradients by the non-finite norm "
            "anyway, set `error_if_nonfinite=False`")
    scale = jnp.minimum(1.0, max_norm / jnp.maximum(gnorm, 1e-12))
    for p in params:
        p.grad._value = (p.grad._value.astype(jnp.float32) * scale).astype(p.grad._value.dtype)
    from .functional_api import unwrap_tree  # noqa: F401

    from ..framework.core import _wrap_value

    return _wrap_value(gnorm)
