"""Convolution functionals (parity: python/paddle/nn/functional/conv.py).

Reference's conv kernels (paddle/phi/kernels/gpu/conv_kernel.cu via cuDNN)
map to ``jax.lax.conv_general_dilated`` — XLA tiles convs directly onto the
MXU; layout assignment is the compiler's job, so the NCHW paddle API is kept.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...tensor._helpers import ensure_tensor, op, unwrap


def _pair(v, n):
    if isinstance(v, (list, tuple)):
        return list(v)
    return [v] * n


def _conv_padding(padding, n):
    if isinstance(padding, str):
        return padding.upper()  # SAME / VALID
    p = _pair(padding, n)
    if len(p) == n:
        return [(int(x), int(x)) for x in p]
    # [before0, after0, before1, after1...]
    return [(int(p[2 * i]), int(p[2 * i + 1])) for i in range(n)]


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NCL", name=None):
    return _convnd(x, weight, bias, stride, padding, dilation, groups, 1, data_format)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NCHW", name=None):
    return _convnd(x, weight, bias, stride, padding, dilation, groups, 2, data_format)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NCDHW", name=None):
    return _convnd(x, weight, bias, stride, padding, dilation, groups, 3, data_format)


def _convnd(x, weight, bias, stride, padding, dilation, groups, n, data_format):
    strides = _pair(stride, n)
    dilations = _pair(dilation, n)
    pad = _conv_padding(padding, n)
    chan_first = data_format in ("NCL", "NCHW", "NCDHW")
    spatial = "DHW"[3 - n :] if n < 3 else "DHW"
    if n == 1:
        spatial = "W"
    elif n == 2:
        spatial = "HW"
    lhs_spec = ("NC" + spatial) if chan_first else ("N" + spatial + "C")
    dn = (lhs_spec, "OI" + spatial, lhs_spec)

    def fn(v, w, *rest):
        out = jax.lax.conv_general_dilated(
            v, w, window_strides=strides, padding=pad, rhs_dilation=dilations,
            dimension_numbers=dn, feature_group_count=groups,
        )
        if rest:
            b = rest[0]
            shape = [1] * out.ndim
            shape[1 if chan_first else -1] = b.shape[0]
            out = out + b.reshape(shape)
        return out

    args = [ensure_tensor(x), ensure_tensor(weight)]
    if bias is not None:
        args.append(ensure_tensor(bias))
    return op(fn, *args, _name=f"conv{n}d")


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0, dilation=1, groups=1, output_size=None, data_format="NCL", name=None):
    return _convnd_transpose(x, weight, bias, stride, padding, output_padding, dilation, groups, 1, data_format)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0, dilation=1, groups=1, output_size=None, data_format="NCHW", name=None):
    return _convnd_transpose(x, weight, bias, stride, padding, output_padding, dilation, groups, 2, data_format)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0, dilation=1, groups=1, output_size=None, data_format="NCDHW", name=None):
    return _convnd_transpose(x, weight, bias, stride, padding, output_padding, dilation, groups, 3, data_format)


def _convnd_transpose(x, weight, bias, stride, padding, output_padding, dilation, groups, n, data_format):
    strides = _pair(stride, n)
    dilations = _pair(dilation, n)
    pads = _pair(padding, n)
    opads = _pair(output_padding, n)
    chan_first = data_format in ("NCL", "NCHW", "NCDHW")
    spatial = {1: "W", 2: "HW", 3: "DHW"}[n]
    lhs_spec = ("NC" + spatial) if chan_first else ("N" + spatial + "C")
    # paddle weight layout for transpose conv: [in, out/groups, *k]
    dn = (lhs_spec, "IO" + spatial, lhs_spec)

    def fn(v, w, *rest):
        k = w.shape[2:]
        pad_cfg = [
            (k[i] - 1 - pads[i], k[i] - 1 - pads[i] + opads[i]) for i in range(n)
        ]
        out = jax.lax.conv_general_dilated(
            v, w, window_strides=[1] * n, padding=pad_cfg, lhs_dilation=strides,
            rhs_dilation=dilations, dimension_numbers=dn, feature_group_count=groups,
            # flip kernel for true transposed conv
        )
        if rest:
            b = rest[0]
            shape = [1] * out.ndim
            shape[1 if chan_first else -1] = b.shape[0]
            out = out + b.reshape(shape)
        return out

    def fn_flipped(v, w, *rest):
        w = jnp.flip(w, axis=tuple(range(2, 2 + n)))
        return fn(v, w, *rest)

    args = [ensure_tensor(x), ensure_tensor(weight)]
    if bias is not None:
        args.append(ensure_tensor(bias))
    return op(fn_flipped, *args, _name=f"conv{n}d_transpose")
