"""Functional tail ops closing the nn.functional API diff.

Parity anchors: python/paddle/nn/functional/{vision,extension,common}.py —
pixel_shuffle/unshuffle, channel_shuffle, affine_grid, grid_sample,
temporal_shift, fold, max_unpool*, diag_embed, gather_tree,
class_center_sample, sparse_attention, zeropad2d. All are pure jnp
compositions through the primitive chokepoint; XLA fuses them — the
reference needs a CUDA kernel per op (paddle/fluid/operators/
{pixel_shuffle_op.cu, grid_sampler_op.cu, temporal_shift_op.cu, ...}).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...tensor._helpers import ensure_tensor, op

__all__ = [
    "pixel_shuffle", "pixel_unshuffle", "channel_shuffle", "zeropad2d",
    "diag_embed", "temporal_shift", "affine_grid", "grid_sample", "fold",
    "max_unpool1d", "max_unpool2d", "max_unpool3d", "gather_tree",
    "class_center_sample", "sparse_attention",
]


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = int(upscale_factor)

    def fn(v):
        if data_format == "NHWC":
            v = jnp.transpose(v, (0, 3, 1, 2))
        n, c, h, w = v.shape
        v = v.reshape(n, c // (r * r), r, r, h, w)
        v = jnp.transpose(v, (0, 1, 4, 2, 5, 3)).reshape(n, c // (r * r), h * r, w * r)
        if data_format == "NHWC":
            v = jnp.transpose(v, (0, 2, 3, 1))
        return v

    return op(fn, ensure_tensor(x), _name="pixel_shuffle")


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    r = int(downscale_factor)

    def fn(v):
        if data_format == "NHWC":
            v = jnp.transpose(v, (0, 3, 1, 2))
        n, c, h, w = v.shape
        v = v.reshape(n, c, h // r, r, w // r, r)
        v = jnp.transpose(v, (0, 1, 3, 5, 2, 4)).reshape(n, c * r * r, h // r, w // r)
        if data_format == "NHWC":
            v = jnp.transpose(v, (0, 2, 3, 1))
        return v

    return op(fn, ensure_tensor(x), _name="pixel_unshuffle")


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    g = int(groups)

    def fn(v):
        if data_format == "NHWC":
            v = jnp.transpose(v, (0, 3, 1, 2))
        n, c, h, w = v.shape
        v = v.reshape(n, g, c // g, h, w)
        v = jnp.transpose(v, (0, 2, 1, 3, 4)).reshape(n, c, h, w)
        if data_format == "NHWC":
            v = jnp.transpose(v, (0, 2, 3, 1))
        return v

    return op(fn, ensure_tensor(x), _name="channel_shuffle")


def zeropad2d(x, padding, data_format="NCHW", name=None):
    l, r, t, b = (padding if isinstance(padding, (list, tuple)) else [padding] * 4)

    def fn(v):
        pads = [(0, 0), (0, 0), (t, b), (l, r)] if data_format == "NCHW" \
            else [(0, 0), (t, b), (l, r), (0, 0)]
        return jnp.pad(v, pads)

    return op(fn, ensure_tensor(x), _name="zeropad2d")


def diag_embed(x, offset=0, dim1=-2, dim2=-1, name=None):
    return op(lambda v: jnp.vectorize(lambda row: jnp.diag(row, k=offset),
                                      signature="(n)->(m,m)")(v)
              if (dim1, dim2) == (-2, -1) else
              jnp.moveaxis(jnp.vectorize(lambda row: jnp.diag(row, k=offset),
                                         signature="(n)->(m,m)")(v), (-2, -1), (dim1, dim2)),
              ensure_tensor(x), _name="diag_embed")


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW", name=None):
    """Shift a fraction of channels one step along the segment (time) axis
    (reference temporal_shift_op: TSM)."""

    def fn(v):
        if data_format == "NHWC":
            v = jnp.transpose(v, (0, 3, 1, 2))
        nt, c, h, w = v.shape
        n = nt // seg_num
        v = v.reshape(n, seg_num, c, h, w)
        c1 = int(c * shift_ratio)
        c2 = int(c * 2 * shift_ratio)
        back = jnp.concatenate([v[:, 1:, :c1], jnp.zeros_like(v[:, :1, :c1])], axis=1)
        fwd = jnp.concatenate([jnp.zeros_like(v[:, :1, c1:c2]), v[:, :-1, c1:c2]], axis=1)
        out = jnp.concatenate([back, fwd, v[:, :, c2:]], axis=2).reshape(nt, c, h, w)
        if data_format == "NHWC":
            out = jnp.transpose(out, (0, 2, 3, 1))
        return out

    return op(fn, ensure_tensor(x), _name="temporal_shift")


def affine_grid(theta, out_shape, align_corners=True, name=None):
    """[n, 2, 3] affine params -> [n, h, w, 2] sampling grid (reference
    affine_grid_op)."""
    n, _, h, w = [int(d) for d in out_shape]

    def lin(size):
        if align_corners:
            return jnp.linspace(-1.0, 1.0, size)
        step = 2.0 / size
        return jnp.linspace(-1.0 + step / 2, 1.0 - step / 2, size)

    def fn(th):
        ys, xs = jnp.meshgrid(lin(h), lin(w), indexing="ij")
        base = jnp.stack([xs, ys, jnp.ones_like(xs)], axis=-1)  # [h, w, 3]
        return jnp.einsum("hwk,nck->nhwc", base, th.astype(jnp.float32)).astype(th.dtype)

    return op(fn, ensure_tensor(theta), _name="affine_grid")


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros", align_corners=True, name=None):
    """Sample [n,c,h,w] at normalized grid [n,gh,gw,2] (reference
    grid_sampler_op). Modes: bilinear/nearest; padding: zeros/border."""
    if mode not in ("bilinear", "nearest"):
        raise ValueError(mode)
    if padding_mode not in ("zeros", "border"):
        raise NotImplementedError(f"padding_mode {padding_mode!r}")

    def fn(v, g):
        n, c, h, w = v.shape
        gx, gy = g[..., 0].astype(jnp.float32), g[..., 1].astype(jnp.float32)
        if align_corners:
            fx = (gx + 1) * (w - 1) / 2
            fy = (gy + 1) * (h - 1) / 2
        else:
            fx = ((gx + 1) * w - 1) / 2
            fy = ((gy + 1) * h - 1) / 2

        def gather(ix, iy):
            inb = (ix >= 0) & (ix < w) & (iy >= 0) & (iy < h)
            ixc = jnp.clip(ix, 0, w - 1)
            iyc = jnp.clip(iy, 0, h - 1)
            vals = v[jnp.arange(n)[:, None, None], :, iyc, ixc]  # [n, gh, gw, c]
            if padding_mode == "zeros":
                vals = jnp.where(inb[..., None], vals, 0)
            return vals

        if mode == "nearest":
            out = gather(jnp.round(fx).astype(jnp.int32), jnp.round(fy).astype(jnp.int32))
        else:
            x0 = jnp.floor(fx).astype(jnp.int32)
            y0 = jnp.floor(fy).astype(jnp.int32)
            wx = (fx - x0)[..., None]
            wy = (fy - y0)[..., None]
            out = (gather(x0, y0) * (1 - wx) * (1 - wy)
                   + gather(x0 + 1, y0) * wx * (1 - wy)
                   + gather(x0, y0 + 1) * (1 - wx) * wy
                   + gather(x0 + 1, y0 + 1) * wx * wy)
        return jnp.transpose(out.astype(v.dtype), (0, 3, 1, 2))

    return op(fn, ensure_tensor(x), ensure_tensor(grid), _name="grid_sample")


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """col2im: [n, c*kh*kw, L] patches -> [n, c, H, W] with overlap-add
    (reference fold_op / unfold inverse)."""
    from .pooling import _pair

    H, W = _pair(output_sizes, 2)
    kh, kw = _pair(kernel_sizes, 2)
    sh, sw = _pair(strides, 2)
    ph, pw = _pair(paddings, 2)
    dh, dw = _pair(dilations, 2)
    ho = (H + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
    wo = (W + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1

    def fn(v):
        n, ckk, L = v.shape
        c = ckk // (kh * kw)
        v = v.reshape(n, c, kh, kw, ho, wo)
        out = jnp.zeros((n, c, H + 2 * ph, W + 2 * pw), v.dtype)
        for i in range(kh):
            for j in range(kw):
                ys = i * dh
                xs = j * dw
                out = out.at[:, :, ys:ys + sh * ho:sh, xs:xs + sw * wo:sw].add(v[:, :, i, j])
        return out[:, :, ph:ph + H, pw:pw + W]

    return op(fn, ensure_tensor(x), _name="fold")


def _max_unpool(x, indices, n, kernel_size, stride, padding, output_size, data_format):
    """Scatter pooled values back to the positions recorded by
    return_mask=True max pooling (reference unpool_op)."""
    from .pooling import _pair

    ks = _pair(kernel_size, n)
    st = _pair(stride if stride is not None else kernel_size, n)
    pd = _pair(padding, n)

    def fn(v, idx):
        spatial_in = v.shape[2:]
        if output_size is not None:
            out_sp = [int(d) for d in output_size[-n:]]
        else:
            out_sp = [(spatial_in[i] - 1) * st[i] - 2 * pd[i] + ks[i] for i in range(n)]
        N, C = v.shape[:2]
        S = int(np.prod(out_sp))
        flat = jnp.zeros((N, C, S), v.dtype)
        vi = v.reshape(N, C, -1)
        ii = idx.reshape(N, C, -1).astype(jnp.int32)
        flat = flat.at[jnp.arange(N)[:, None, None], jnp.arange(C)[None, :, None], ii].set(vi)
        return flat.reshape((N, C) + tuple(out_sp))

    return op(fn, ensure_tensor(x), ensure_tensor(indices), _name="max_unpool")


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0, data_format="NCL", output_size=None, name=None):
    return _max_unpool(x, indices, 1, kernel_size, stride, padding, output_size, data_format)


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0, data_format="NCHW", output_size=None, name=None):
    return _max_unpool(x, indices, 2, kernel_size, stride, padding, output_size, data_format)


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0, data_format="NCDHW", output_size=None, name=None):
    return _max_unpool(x, indices, 3, kernel_size, stride, padding, output_size, data_format)


def gather_tree(ids, parents, name=None):
    """Back-trace beam-search parent pointers into full sequences
    (reference gather_tree_op): ids/parents [T, batch, beam]."""

    def fn(ids_, par):
        T = ids_.shape[0]

        def step(beams, t):
            # beams: the beam index occupied at time t; emit its token, then
            # hop to its parent for time t-1
            tok = jnp.take_along_axis(ids_[t], beams, axis=-1)
            prev = jnp.take_along_axis(par[t], beams, axis=-1)
            return prev, tok

        init = jnp.broadcast_to(jnp.arange(ids_.shape[2]), ids_.shape[1:])
        _, toks = jax.lax.scan(step, init, jnp.arange(T - 1, -1, -1))
        return toks[::-1]

    return op(fn, ensure_tensor(ids), ensure_tensor(parents), _name="gather_tree")


def class_center_sample(label, num_classes, num_samples, group=None):
    """Sample negative class centers plus all positives (reference
    class_center_sample_op, PartialFC). Returns (remapped_label,
    sampled_class_indices). Host-side sampling: the sampled set is data-
    dependent."""
    lab = np.asarray(ensure_tensor(label)._value).ravel()
    pos = np.unique(lab)
    num_samples = max(int(num_samples), len(pos))
    neg_pool = np.setdiff1d(np.arange(num_classes), pos)
    rng = np.random.default_rng()  # fresh entropy: negatives resample per call
    extra = rng.choice(neg_pool, size=min(num_samples - len(pos), len(neg_pool)), replace=False)
    sampled = np.sort(np.concatenate([pos, extra]))
    remap = {c: i for i, c in enumerate(sampled)}
    new_lab = np.asarray([remap[c] for c in lab], np.int64)
    from ...framework.core import _wrap_value

    return _wrap_value(jnp.asarray(new_lab)), _wrap_value(jnp.asarray(sampled))


def sparse_attention(query, key, value, sparse_csr_offset, sparse_csr_columns, name=None):
    """Block-sparse attention (reference sparse_attention_op). TPU-native
    form: materialize the CSR layout as an additive mask and let the fused
    attention path run it — on TPU the MXU prefers dense tiles with masking
    over gather-based sparsity at these block sizes."""
    q, k, v = ensure_tensor(query), ensure_tensor(key), ensure_tensor(value)
    off, cols = ensure_tensor(sparse_csr_offset), ensure_tensor(sparse_csr_columns)

    def fn(qq, kk, vv, o, c):
        b, h, s, d = qq.shape
        # CSR rows -> dense [s, s] connectivity (same for every batch/head
        # when offsets are 2-D [h, s+1]; take head 0 layout otherwise)
        o2 = o.reshape(-1, o.shape[-1])[0]
        c2 = c.reshape(-1)[: int(o2[-1])] if c.ndim > 1 else c
        counts = o2[1:] - o2[:-1]
        row_of = jnp.repeat(jnp.arange(s), counts, total_repeat_length=c2.shape[0])
        mask = jnp.zeros((s, s), bool).at[row_of, c2].set(True)
        logits = jnp.einsum("bhqd,bhkd->bhqk", qq.astype(jnp.float32), kk.astype(jnp.float32))
        logits = logits / jnp.sqrt(jnp.asarray(d, jnp.float32))
        logits = jnp.where(mask[None, None], logits, -1e30)
        p = jax.nn.softmax(logits, axis=-1)
        return jnp.einsum("bhqk,bhkd->bhqd", p, vv.astype(jnp.float32)).astype(qq.dtype)

    return op(fn, q, k, v, off, cols, _name="sparse_attention")
