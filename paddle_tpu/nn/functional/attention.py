"""Attention functionals.

Parity: the reference's fused attention CUDA ops
(paddle/fluid/operators/fused/fused_attention_op.cu,
fused_softmax_mask.cu.h) — on TPU the hot path is the Pallas
flash-attention kernel (paddle_tpu/ops/flash_attention.py); the jnp
path below is the reference implementation XLA fuses on its own.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework import random as _random
from ...framework.flags import flag
from ...tensor._helpers import ensure_tensor, op, unwrap


def scaled_dot_product_attention(query, key, value, attn_mask=None, dropout_p=0.0, is_causal=False, training=True, name=None):
    """q/k/v: [batch, seq, heads, head_dim] (paddle layout).

    Dispatches to the Pallas flash kernel on TPU when
    FLAGS_use_flash_attention is set and shapes are tile-friendly.
    """
    q, k, v = ensure_tensor(query), ensure_tensor(key), ensure_tensor(value)

    use_flash = flag("FLAGS_use_flash_attention") and dropout_p == 0.0 and attn_mask is None
    if use_flash:
        from ...ops.flash_attention import flash_attention_available, flash_attention

        if flash_attention_available(tuple(q.shape), tuple(k.shape)):
            return op(lambda qq, kk, vv: flash_attention(qq, kk, vv, causal=is_causal), q, k, v, _name="flash_attention")

    # masked / GQA envelope: additive [b|1, 1, s, s] masks (bool masks become
    # 0/-1e30) and h_kv | h grouped KV run through the flat-lane kernels when
    # FLAGS_flash_flat is on (reference fused_attention_op.cu attn_mask path)
    if flag("FLAGS_use_flash_attention") and dropout_p == 0.0 and attn_mask is not None:
        from ...ops import flash_attention_flat as _flat

        b, s, h, d = q.shape
        m = ensure_tensor(attn_mask)
        kv_ok = tuple(k.shape) == tuple(q.shape) or (
            k.shape[0] == b and k.shape[1] == s and h % k.shape[2] == 0 and k.shape[3] == d)
        if (_flat.enabled((b, s, 3, h, d), packed=False) and kv_ok
                and _flat.mask_supported(b, s, h, d, tuple(m.shape))):
            def fn(qq, kk, vv, mm):
                if mm.dtype == jnp.bool_:
                    mm = jnp.where(mm, 0.0, -1e30).astype(jnp.float32)
                return _flat.flash_flat_gqa(qq, kk, vv, causal=is_causal, mask=mm)

            return op(fn, q, k, v, m, _name="flash_attention")

    dropping = dropout_p > 0.0 and training
    aux = [ensure_tensor(attn_mask)] if attn_mask is not None else []
    if dropping:
        aux.append(_random.key_tensor())
        aux.append(_random.train_flag_tensor())
    has_mask = attn_mask is not None

    def fn(qq, kk, vv, *extra):
        mask = extra[0] if has_mask else None
        drop_key = extra[-2] if dropping else None
        train = extra[-1] if dropping else None
        return _sdpa_reference(qq, kk, vv, mask, is_causal,
                               dropout_p if training else 0.0, drop_key, train)

    return op(fn, q, k, v, *aux, _name="sdpa")


def _sdpa_reference(q, k, v, mask=None, causal=False, dropout_p=0.0, drop_key=None, train=None):
    # [B, S, H, D] -> [B, H, S, D]
    qh = jnp.swapaxes(q, 1, 2)
    kh = jnp.swapaxes(k, 1, 2)
    vh = jnp.swapaxes(v, 1, 2)
    scale = 1.0 / jnp.sqrt(jnp.asarray(q.shape[-1], jnp.float32))
    logits = jnp.einsum("bhqd,bhkd->bhqk", qh, kh).astype(jnp.float32) * scale
    if causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        cm = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        logits = jnp.where(cm, logits, -1e30)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            logits = jnp.where(mask, logits, -1e30)
        else:
            logits = logits + mask.astype(logits.dtype)
    probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    if dropout_p > 0.0 and drop_key is not None:
        keep = jax.random.bernoulli(drop_key, 1.0 - dropout_p, probs.shape)
        scale = 1.0 / (1.0 - dropout_p)
        if train is not None:  # captured program flipped to inference
            keep = keep | (train == 0)
            scale = jnp.where(train == 0, 1.0, scale)
        probs = jnp.where(keep, probs * scale, 0.0).astype(probs.dtype)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vh)
    return jnp.swapaxes(out, 1, 2)
