"""Attention functionals.

Parity: the reference's fused attention CUDA ops
(paddle/fluid/operators/fused/fused_attention_op.cu,
fused_softmax_mask.cu.h) — on TPU the hot path is the Pallas
flash-attention kernel (paddle_tpu/ops/flash_attention.py); the jnp
path below is the reference implementation XLA fuses on its own.

Kernel selection goes through :mod:`paddle_tpu.ops.registry`: two kernels
are registered here —

- ``sdpa``: the eager/staged scaled-dot-product entry point. Impls:
  ``flash`` (classic Pallas pair: no mask, no dropout), ``flash_flat_gqa``
  (flat-lane kernels: additive/bool masks + grouped KV), ``xla`` fallback.
- ``attention_core``: GPT's pure-array packed-qkv causal core. Impls:
  ``flash_packed`` (flat-lane packed, zero-relayout), ``flash`` (classic
  pair over slices), ``xla`` fallback.

The hand-rolled ``flag(...) and available(...)`` dance each call site used
to carry lives in the impls' availability predicates; selection is cached
per call signature with ``kernels.{sdpa,attention_core}.*`` counters.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework import random as _random
from ...framework.flags import flag
from ...ops import registry as _registry
from ...tensor._helpers import ensure_tensor, op, unwrap


def scaled_dot_product_attention(query, key, value, attn_mask=None, dropout_p=0.0, is_causal=False, training=True, name=None):
    """q/k/v: [batch, seq, heads, head_dim] (paddle layout).

    Dispatches through the ``sdpa`` kernel registry entry: the Pallas
    flash kernel on TPU when FLAGS_use_flash_attention is set and shapes
    are tile-friendly, the flat-lane masked/GQA kernels for supported
    masks, the jnp reference otherwise.
    """
    q, k, v = ensure_tensor(query), ensure_tensor(key), ensure_tensor(value)

    dropping = dropout_p > 0.0 and training
    p = dropout_p if training else 0.0
    aux = [ensure_tensor(attn_mask)] if attn_mask is not None else []
    if dropping:
        aux.append(_random.key_tensor())
        aux.append(_random.train_flag_tensor())
    has_mask = attn_mask is not None

    def fn(qq, kk, vv, *extra):
        mask = extra[0] if has_mask else None
        drop_key = extra[-2] if dropping else None
        train = extra[-1] if dropping else None
        return _registry.dispatch("sdpa", qq, kk, vv, mask, is_causal, p, drop_key, train)

    return op(fn, q, k, v, *aux, _name="sdpa")


def _sdpa_reference(q, k, v, mask=None, causal=False, dropout_p=0.0, drop_key=None, train=None):
    # [B, S, H, D] -> [B, H, S, D]
    qh = jnp.swapaxes(q, 1, 2)
    kh = jnp.swapaxes(k, 1, 2)
    vh = jnp.swapaxes(v, 1, 2)
    scale = 1.0 / jnp.sqrt(jnp.asarray(q.shape[-1], jnp.float32))
    logits = jnp.einsum("bhqd,bhkd->bhqk", qh, kh).astype(jnp.float32) * scale
    if causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        cm = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        logits = jnp.where(cm, logits, -1e30)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            logits = jnp.where(mask, logits, -1e30)
        else:
            logits = logits + mask.astype(logits.dtype)
    probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    if dropout_p > 0.0 and drop_key is not None:
        keep = jax.random.bernoulli(drop_key, 1.0 - dropout_p, probs.shape)
        scale = 1.0 / (1.0 - dropout_p)
        if train is not None:  # captured program flipped to inference
            keep = keep | (train == 0)
            scale = jnp.where(train == 0, 1.0, scale)
        probs = jnp.where(keep, probs * scale, 0.0).astype(probs.dtype)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vh)
    return jnp.swapaxes(out, 1, 2)


# -- kernel registrations ----------------------------------------------------


def _interpret_state():
    # interpret-mode toggles live outside the flag registry; fold them into
    # the selection-cache key so set_interpret() re-runs the predicates
    from ...ops import flash_attention as _fa
    from ...ops import flash_attention_flat as _flat

    return (_fa._INTERPRET, _flat._INTERPRET)


def _sdpa_flash_available(q, k, v, mask, causal, dropout_p, drop_key, train):
    from ...ops.flash_attention import flash_attention_available

    return (mask is None and dropout_p == 0.0 and flag("FLAGS_use_flash_attention")
            and flash_attention_available(tuple(q.shape), tuple(k.shape)))


def _sdpa_flash(q, k, v, mask, causal, dropout_p, drop_key, train):
    from ...ops.flash_attention import flash_attention

    return flash_attention(q, k, v, causal=causal)


def _sdpa_flat_available(q, k, v, mask, causal, dropout_p, drop_key, train):
    # masked / GQA envelope: additive [b|1, 1, s, s] masks (bool masks become
    # 0/-1e30) and h_kv | h grouped KV run through the flat-lane kernels when
    # FLAGS_flash_flat is on (reference fused_attention_op.cu attn_mask path)
    from ...ops import flash_attention_flat as _flat

    if mask is None or dropout_p != 0.0 or not flag("FLAGS_use_flash_attention"):
        return False
    b, s, h, d = q.shape
    kv_ok = tuple(k.shape) == tuple(q.shape) or (
        k.shape[0] == b and k.shape[1] == s and h % k.shape[2] == 0 and k.shape[3] == d)
    return (_flat.enabled((b, s, 3, h, d), packed=False) and kv_ok
            and _flat.mask_supported(b, s, h, d, tuple(mask.shape)))


def _sdpa_flat(q, k, v, mask, causal, dropout_p, drop_key, train):
    from ...ops import flash_attention_flat as _flat

    if mask.dtype == jnp.bool_:
        mask = jnp.where(mask, 0.0, -1e30).astype(jnp.float32)
    return _flat.flash_flat_gqa(q, k, v, causal=causal, mask=mask)


_registry.define_kernel(
    "sdpa", flags=("FLAGS_use_flash_attention", "FLAGS_flash_flat"),
    cache_key=_interpret_state)
_registry.register(
    "sdpa", "flash", _sdpa_flash, available=_sdpa_flash_available,
    doc="classic Pallas flash pair (self-attn, no mask/dropout, tile-friendly seq)")
_registry.register(
    "sdpa", "flash_flat_gqa", _sdpa_flat, available=_sdpa_flat_available,
    doc="flat-lane masked/GQA flash kernels (additive or bool [b|1,1,s,s] mask)")
_registry.register(
    "sdpa", "xla", _sdpa_reference, fallback=True,
    doc="jnp reference composite (any mask/dropout/shape)")


def _core_flat_available(qkv, dropout_p, drop_key):
    from ...ops import flash_attention_flat as _flat

    return (dropout_p == 0.0 and flag("FLAGS_use_flash_attention")
            and _flat.enabled(tuple(qkv.shape)))


def _core_flat(qkv, dropout_p, drop_key):
    from ...ops import flash_attention_flat as _flat

    return _flat.flash_packed(qkv, causal=True)


def _core_flash_available(qkv, dropout_p, drop_key):
    from ...ops.flash_attention import flash_attention_available

    b, s, _, h, d = qkv.shape
    return (dropout_p == 0.0 and flag("FLAGS_use_flash_attention")
            and flash_attention_available((b, s, h, d)))


def _core_flash(qkv, dropout_p, drop_key):
    from ...ops.flash_attention import _flash

    return _flash(qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2], True)


def _core_xla(qkv, dropout_p, drop_key):
    return _sdpa_reference(qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2], None, True,
                           dropout_p, drop_key)


_registry.define_kernel(
    "attention_core", flags=("FLAGS_use_flash_attention", "FLAGS_flash_flat"),
    cache_key=_interpret_state)
_registry.register(
    "attention_core", "flash_packed", _core_flat, available=_core_flat_available,
    doc="flat-lane packed-qkv kernels (zero-relayout reads via index maps)")
_registry.register(
    "attention_core", "flash", _core_flash, available=_core_flash_available,
    doc="classic Pallas flash pair over packed-qkv slices")
_registry.register(
    "attention_core", "xla", _core_xla, fallback=True,
    doc="jnp reference over packed-qkv slices (handles attention dropout)")
