"""Normalization functionals (parity: python/paddle/nn/functional/norm.py).

On TPU, batch-norm "sync" across data-parallel shards is free under GSPMD:
with the batch axis sharded, the mean/var reductions compile to psums over the
mesh — the reference's SyncBatchNorm C++ machinery
(paddle/fluid/operators/sync_batch_norm_op.cu) has no TPU analog needed.
"""
from __future__ import annotations

import jax.numpy as jnp

from ...tensor._helpers import Tensor, ensure_tensor, op, unwrap


def _assign_buffer(buf: Tensor, new: Tensor) -> None:
    """Write an op result into a stateful buffer (running stats).

    Eager: in-place value swap. Static capture: register a deferred write —
    the program's ops keep reading the pre-run value (reference static-BN
    dataflow) and the Executor commits the new value after the run.
    """
    from ...framework.static_trace import current_program, is_symbolic

    prog = current_program()
    if prog is not None and is_symbolic(new._value):
        prog.buffer_writes.append((buf, new._value))
    else:
        buf._value = new._value


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5, name=None):
    ns = normalized_shape if isinstance(normalized_shape, (list, tuple)) else [normalized_shape]
    axes = tuple(range(-len(ns), 0))

    if len(ns) == 1 and weight is not None and bias is not None:
        # hot path: last-axis LN with affine params uses the fused
        # closed-form-backward kernel (ops/layer_norm.py) — autodiff of the
        # mean/var chain compiles to several× the bandwidth bound on TPU
        from ...ops.layer_norm import layer_norm_fused

        return op(lambda v, w, b: layer_norm_fused(v, w, b, epsilon),
                  ensure_tensor(x), ensure_tensor(weight), ensure_tensor(bias),
                  _name="layer_norm")

    def fn(v, *rest):
        mean = jnp.mean(v, axis=axes, keepdims=True)
        var = jnp.var(v, axis=axes, keepdims=True)
        out = (v - mean) / jnp.sqrt(var + epsilon)
        it = iter(rest)
        if weight is not None:
            out = out * next(it)
        if bias is not None:
            out = out + next(it)
        return out

    args = [ensure_tensor(x)]
    if weight is not None:
        args.append(ensure_tensor(weight))
    if bias is not None:
        args.append(ensure_tensor(bias))
    return op(fn, *args, _name="layer_norm")


def batch_norm(x, running_mean, running_var, weight=None, bias=None, training=False, momentum=0.9, epsilon=1e-5, data_format="NCHW", use_global_stats=None, name=None):
    ch_axis = 1 if data_format.startswith("NC") else -1

    rm, rv = ensure_tensor(running_mean), ensure_tensor(running_var)
    x = ensure_tensor(x)
    reduce_axes = tuple(i for i in range(x.ndim) if i != (ch_axis % x.ndim))
    use_batch_stats = training and not use_global_stats

    if use_batch_stats:
        # compute batch stats (differentiable), update running stats in-place
        def stats_fn(v):
            m = jnp.mean(v, axis=reduce_axes)
            var = jnp.var(v, axis=reduce_axes)
            return m, var

        m_t, var_t = op(stats_fn, x, _name="bn_stats")
        # running-stat update is a side effect on buffer tensors (paddle
        # parity). Routed through op() so static capture records it; the
        # Executor writes the result back to the buffer after each run.
        def ema(old, new):
            return momentum * old + (1 - momentum) * new

        # pass the buffer Tensor itself (not a detached copy) so a recorded
        # program re-reads the CURRENT buffer value on every run
        ro_rm = rm if rm.stop_gradient else rm.detach()
        ro_rv = rv if rv.stop_gradient else rv.detach()
        new_rm = op(ema, ro_rm, m_t.detach(), _name="bn_update_mean")
        new_rv = op(ema, ro_rv, var_t.detach(), _name="bn_update_var")
        _assign_buffer(rm, new_rm)
        _assign_buffer(rv, new_rv)
        mean_in, var_in = m_t, var_t
    else:
        mean_in = rm if rm.stop_gradient else rm.detach()
        var_in = rv if rv.stop_gradient else rv.detach()

    def fn(v, m, var, *rest):
        shape = [1] * v.ndim
        shape[ch_axis] = v.shape[ch_axis]
        out = (v - m.reshape(shape)) / jnp.sqrt(var.reshape(shape) + epsilon)
        it = iter(rest)
        if weight is not None:
            out = out * next(it).reshape(shape)
        if bias is not None:
            out = out + next(it).reshape(shape)
        return out

    args = [x, mean_in, var_in]
    if weight is not None:
        args.append(ensure_tensor(weight))
    if bias is not None:
        args.append(ensure_tensor(bias))
    return op(fn, *args, _name="batch_norm")


def instance_norm(x, running_mean=None, running_var=None, weight=None, bias=None, use_input_stats=True, momentum=0.9, eps=1e-5, data_format="NCHW", name=None):
    x = ensure_tensor(x)
    ch_axis = 1 if data_format.startswith("NC") else -1
    spatial_axes = tuple(range(2, x.ndim)) if ch_axis == 1 else tuple(range(1, x.ndim - 1))

    def fn(v, *rest):
        m = jnp.mean(v, axis=spatial_axes, keepdims=True)
        var = jnp.var(v, axis=spatial_axes, keepdims=True)
        out = (v - m) / jnp.sqrt(var + eps)
        shape = [1] * v.ndim
        shape[ch_axis] = v.shape[ch_axis]
        it = iter(rest)
        if weight is not None:
            out = out * next(it).reshape(shape)
        if bias is not None:
            out = out + next(it).reshape(shape)
        return out

    args = [x]
    if weight is not None:
        args.append(ensure_tensor(weight))
    if bias is not None:
        args.append(ensure_tensor(bias))
    return op(fn, *args, _name="instance_norm")


def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None, data_format="NCHW", name=None):
    x = ensure_tensor(x)
    if not data_format.startswith("NC"):
        raise NotImplementedError("group_norm currently supports channel-first")

    def fn(v, *rest):
        n, c = v.shape[0], v.shape[1]
        g = num_groups
        vv = v.reshape(n, g, c // g, *v.shape[2:])
        axes = tuple(range(2, vv.ndim))
        m = jnp.mean(vv, axis=axes, keepdims=True)
        var = jnp.var(vv, axis=axes, keepdims=True)
        out = ((vv - m) / jnp.sqrt(var + epsilon)).reshape(v.shape)
        shape = [1, c] + [1] * (v.ndim - 2)
        it = iter(rest)
        if weight is not None:
            out = out * next(it).reshape(shape)
        if bias is not None:
            out = out + next(it).reshape(shape)
        return out

    args = [x]
    if weight is not None:
        args.append(ensure_tensor(weight))
    if bias is not None:
        args.append(ensure_tensor(bias))
    return op(fn, *args, _name="group_norm")


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW", name=None):
    x = ensure_tensor(x)

    def fn(v):
        sq = jnp.square(v)
        half = size // 2
        c = v.shape[1]
        pad_sq = jnp.pad(sq, [(0, 0), (half, size - half - 1)] + [(0, 0)] * (v.ndim - 2))
        acc = sum(pad_sq[:, i : i + c] for i in range(size))
        return v / jnp.power(k + alpha * acc / size, beta)

    return op(fn, x, _name="local_response_norm")


def rms_norm(x, weight=None, epsilon=1e-6, name=None):
    """RMSNorm (not in the reference snapshot; standard for modern LLMs)."""

    def fn(v, *rest):
        var = jnp.mean(jnp.square(v.astype(jnp.float32)), axis=-1, keepdims=True)
        out = (v.astype(jnp.float32) / jnp.sqrt(var + epsilon)).astype(v.dtype)
        if rest:
            out = out * rest[0]
        return out

    args = [ensure_tensor(x)]
    if weight is not None:
        args.append(ensure_tensor(weight))
    return op(fn, *args, _name="rms_norm")
