"""Loss functionals (parity: python/paddle/nn/functional/loss.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...tensor._helpers import Tensor, ensure_tensor, op, unwrap


def _reduce(v, reduction):
    """Reductions accumulate in f32: under AMP O2 the per-element losses
    arrive in bf16 and a bf16 mean over millions of terms loses ~5 digits;
    the cast fuses into the reduce so nothing extra materializes."""
    if v.dtype in (jnp.bfloat16, jnp.float16):
        v = v.astype(jnp.float32)
    if reduction == "mean":
        return jnp.mean(v)
    if reduction == "sum":
        return jnp.sum(v)
    return v


def _fused_softmax_ce(logits, lab, axis):
    """Per-token hard-label CE with a hand-rolled vjp.

    The naive form (log_softmax → take_along_axis) materializes an f32
    [..., V] log-prob tensor and scatters in backward — ~2.5GB of HBM
    traffic per step at GPT vocab sizes (profiled, see BASELINE.md). Here
    the forward keeps only row reductions (logsumexp) + a label gather, and
    the backward rebuilds softmax from the saved logits dtype (bf16 under
    AMP) with the one-hot expressed as an iota compare — no scatter, no f32
    [..., V] tensor anywhere. Parity: the fused
    softmax_with_cross_entropy CUDA kernel (operators/softmax_with_cross_entropy_op.cu).
    """
    ax = axis % logits.ndim
    labx = jnp.expand_dims(lab, ax)

    def _lse(lg):
        m = jnp.max(lg, axis=ax, keepdims=True)
        se = jnp.sum(jnp.exp(lg.astype(jnp.float32) - m.astype(jnp.float32)), axis=ax, keepdims=True)
        return jnp.log(se) + m.astype(jnp.float32)

    @jax.custom_vjp
    def ce(lg):
        lab_logit = jnp.take_along_axis(lg, labx, axis=ax).astype(jnp.float32)
        return (_lse(lg) - lab_logit).squeeze(ax)

    def fwd(lg):
        lse = _lse(lg)
        lab_logit = jnp.take_along_axis(lg, labx, axis=ax).astype(jnp.float32)
        return (lse - lab_logit).squeeze(ax), (lg, lse)

    def bwd(res, g):
        lg, lse = res
        gx = jnp.expand_dims(g, ax).astype(jnp.float32)
        p = jnp.exp(lg.astype(jnp.float32) - lse)
        iota = jax.lax.broadcasted_iota(labx.dtype, lg.shape, ax)
        dlg = (p - (iota == labx).astype(jnp.float32)) * gx
        return (dlg.astype(lg.dtype),)

    ce.defvjp(fwd, bwd)
    return ce(logits)


def cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean", soft_label=False, axis=-1, use_softmax=True, label_smoothing=0.0, name=None):
    aux = [ensure_tensor(weight)] if weight is not None else []

    def fn(logits, lbl, *ws):
        w = ws[0] if ws else None
        if not soft_label and use_softmax and label_smoothing == 0.0:
            lab = lbl
            if lab.ndim == logits.ndim and lab.shape[axis] == 1:
                lab = jnp.squeeze(lab, axis=axis)
            lab = lab.astype(jnp.int32)
            loss = _fused_softmax_ce(logits, lab, axis)
            valid = lab != ignore_index
            loss = jnp.where(valid, loss, 0.0)
            if w is not None:
                loss = loss * jnp.take(w, jnp.maximum(lab, 0))
            if reduction == "mean":
                denom = jnp.maximum(jnp.sum(valid.astype(loss.dtype)), 1.0) if w is None else jnp.sum(
                    jnp.where(valid, jnp.take(w, jnp.maximum(lab, 0)), 0.0)
                )
                return jnp.sum(loss) / denom
            return _reduce(loss, reduction)
        # non-fused fallback (soft labels / smoothing / pre-softmaxed input):
        # full f32 log-probs, matching the pre-AMP-change numerics
        if logits.dtype in (jnp.bfloat16, jnp.float16):
            logits = logits.astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=axis) if use_softmax else jnp.log(jnp.maximum(logits, 1e-30))
        if soft_label:
            loss = -jnp.sum(lbl * logp, axis=axis)
        else:
            lab = lbl
            if lab.ndim == logits.ndim and lab.shape[axis] == 1:
                lab = jnp.squeeze(lab, axis=axis)
            lab = lab.astype(jnp.int32)
            n = logits.shape[axis]
            onehot = jax.nn.one_hot(lab, n, dtype=logp.dtype, axis=axis)
            smoothed = onehot * (1.0 - label_smoothing) + label_smoothing / n
            loss = -jnp.sum(smoothed * logp, axis=axis)
            valid = lab != ignore_index
            loss = jnp.where(valid, loss, 0.0)
            if w is not None:
                loss = loss * jnp.take(w, jnp.maximum(lab, 0))
            if reduction == "mean":
                denom = jnp.maximum(jnp.sum(valid.astype(loss.dtype)), 1.0) if w is None else jnp.sum(
                    jnp.where(valid, jnp.take(w, jnp.maximum(lab, 0)), 0.0)
                )
                return jnp.sum(loss) / denom
        return _reduce(loss, reduction)

    return op(fn, ensure_tensor(input), ensure_tensor(label), *aux, _name="cross_entropy")


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100, axis=-1, return_softmax=False):
    out = cross_entropy(logits, label, soft_label=soft_label, ignore_index=ignore_index, reduction="none", axis=axis)
    if return_softmax:
        from .activation import softmax as _softmax

        return out, _softmax(logits, axis=axis)
    return out


def _f32(x):
    """Upcast low-precision inputs for loss math (fuses into the consumer)."""
    return x.astype(jnp.float32) if x.dtype in (jnp.bfloat16, jnp.float16) else x


def mse_loss(input, label, reduction="mean", name=None):
    return op(lambda a, b: _reduce(jnp.square(_f32(a) - _f32(b)), reduction), ensure_tensor(input), ensure_tensor(label), _name="mse_loss")


def l1_loss(input, label, reduction="mean", name=None):
    return op(lambda a, b: _reduce(jnp.abs(_f32(a) - _f32(b)), reduction), ensure_tensor(input), ensure_tensor(label), _name="l1_loss")


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def fn(a, b):
        d = _f32(a) - _f32(b)
        absd = jnp.abs(d)
        loss = jnp.where(absd < delta, 0.5 * d * d / delta, absd - 0.5 * delta)
        return _reduce(loss, reduction)

    return op(fn, ensure_tensor(input), ensure_tensor(label), _name="smooth_l1_loss")


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean", name=None):
    aux = [ensure_tensor(weight)] if weight is not None else []

    def fn(logp, lbl, *ws):
        lbl = lbl.astype(jnp.int32)
        w = ws[0] if ws else None
        # class axis is 1 for ndim>=2 ([N,C] or [N,C,d1,...]); gather the
        # label's log-prob along it.
        loss = -jnp.take_along_axis(logp, jnp.expand_dims(lbl, 1), axis=1).squeeze(1)
        valid = lbl != ignore_index
        loss = jnp.where(valid, loss, 0.0)
        if w is not None:
            loss = loss * jnp.take(w, jnp.maximum(lbl, 0))
        if reduction == "mean":
            denom = jnp.maximum(jnp.sum(valid), 1) if w is None else jnp.sum(jnp.where(valid, jnp.take(w, jnp.maximum(lbl, 0)), 0.0))
            return jnp.sum(loss) / denom
        return _reduce(loss, reduction)

    return op(fn, ensure_tensor(input), ensure_tensor(label), *aux, _name="nll_loss")


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    aux = [ensure_tensor(weight)] if weight is not None else []

    def fn(p, y, *ws):
        p2 = jnp.clip(_f32(p), 1e-12, 1.0 - 1e-7)
        loss = -(y * jnp.log(p2) + (1.0 - y) * jnp.log(1.0 - p2))
        if ws:
            loss = loss * ws[0]
        return _reduce(loss, reduction)

    return op(fn, ensure_tensor(input), ensure_tensor(label), *aux, _name="bce")


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean", pos_weight=None, name=None):
    aux = [ensure_tensor(m) for m in (pos_weight, weight) if m is not None]
    has_pw, has_w = pos_weight is not None, weight is not None

    def fn(z, y, *extra):
        z = _f32(z)
        if has_pw:
            pw = extra[0]
            loss = (1 - y) * z + (1 + (pw - 1) * y) * (jnp.logaddexp(0.0, -jnp.abs(z)) + jnp.maximum(-z, 0))
        else:
            loss = jnp.maximum(z, 0) - z * y + jnp.logaddexp(0.0, -jnp.abs(z))
        if has_w:
            loss = loss * extra[-1]
        return _reduce(loss, reduction)

    return op(fn, ensure_tensor(logit), ensure_tensor(label), *aux, _name="bce_with_logits")


def kl_div(input, label, reduction="mean", name=None):
    def fn(logp, y):
        loss = y * (jnp.log(jnp.maximum(y, 1e-30)) - logp)
        if reduction == "batchmean":
            return jnp.sum(loss) / logp.shape[0]
        return _reduce(loss, reduction)

    return op(fn, ensure_tensor(input), ensure_tensor(label), _name="kl_div")


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):
    return op(
        lambda a, b, y: _reduce(jnp.maximum(0.0, -y * (a - b) + margin), reduction),
        ensure_tensor(input),
        ensure_tensor(other),
        ensure_tensor(label),
        _name="margin_ranking_loss",
    )


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    return op(
        lambda a, y: _reduce(jnp.where(y == 1.0, a, jnp.maximum(0.0, margin - a)), reduction),
        ensure_tensor(input),
        ensure_tensor(label),
        _name="hinge_embedding_loss",
    )


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean", name=None):
    def fn(a, b, y):
        cos = jnp.sum(a * b, axis=-1) / jnp.maximum(jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12)
        loss = jnp.where(y == 1, 1.0 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce(loss, reduction)

    return op(fn, ensure_tensor(input1), ensure_tensor(input2), ensure_tensor(label), _name="cosine_embedding_loss")


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0, epsilon=1e-6, swap=False, reduction="mean", name=None):
    def fn(a, pos, neg):
        dp = jnp.linalg.norm(a - pos + epsilon, ord=p, axis=-1)
        dn = jnp.linalg.norm(a - neg + epsilon, ord=p, axis=-1)
        if swap:
            dn2 = jnp.linalg.norm(pos - neg + epsilon, ord=p, axis=-1)
            dn = jnp.minimum(dn, dn2)
        return _reduce(jnp.maximum(dp - dn + margin, 0.0), reduction)

    return op(fn, ensure_tensor(input), ensure_tensor(positive), ensure_tensor(negative), _name="triplet_margin_loss")


def log_loss(input, label, epsilon=1e-4, name=None):
    return op(
        lambda p, y: -y * jnp.log(p + epsilon) - (1.0 - y) * jnp.log(1.0 - p + epsilon),
        ensure_tensor(input),
        ensure_tensor(label),
        _name="log_loss",
    )


def square_error_cost(input, label):
    return op(lambda a, b: jnp.square(a - b), ensure_tensor(input), ensure_tensor(label), _name="square_error_cost")


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0, reduction="sum", name=None):
    def fn(z, y):
        p = jax.nn.sigmoid(z)
        ce = jnp.maximum(z, 0) - z * y + jnp.logaddexp(0.0, -jnp.abs(z))
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        loss = a_t * jnp.power(1 - p_t, gamma) * ce
        if normalizer is not None:
            loss = loss / unwrap(normalizer)
        return _reduce(loss, reduction)

    return op(fn, ensure_tensor(logit), ensure_tensor(label), _name="sigmoid_focal_loss")
