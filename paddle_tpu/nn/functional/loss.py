"""Loss functionals (parity: python/paddle/nn/functional/loss.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...tensor._helpers import Tensor, ensure_tensor, op, unwrap


def _reduce(v, reduction):
    """Reductions accumulate in f32: under AMP O2 the per-element losses
    arrive in bf16 and a bf16 mean over millions of terms loses ~5 digits;
    the cast fuses into the reduce so nothing extra materializes."""
    if v.dtype in (jnp.bfloat16, jnp.float16):
        v = v.astype(jnp.float32)
    if reduction == "mean":
        return jnp.mean(v)
    if reduction == "sum":
        return jnp.sum(v)
    return v


def _fused_softmax_ce(logits, lab, axis):
    """Per-token hard-label CE with a hand-rolled vjp.

    The naive form (log_softmax → take_along_axis) materializes an f32
    [..., V] log-prob tensor and scatters in backward — ~2.5GB of HBM
    traffic per step at GPT vocab sizes (profiled, see BASELINE.md). Here
    the forward keeps only row reductions (logsumexp) + a label gather, and
    the backward rebuilds softmax from the saved logits dtype (bf16 under
    AMP) with the one-hot expressed as an iota compare — no scatter, no f32
    [..., V] tensor anywhere. Parity: the fused
    softmax_with_cross_entropy CUDA kernel (operators/softmax_with_cross_entropy_op.cu).
    """
    ax = axis % logits.ndim
    labx = jnp.expand_dims(lab, ax)

    def _lse(lg):
        m = jnp.max(lg, axis=ax, keepdims=True)
        se = jnp.sum(jnp.exp(lg.astype(jnp.float32) - m.astype(jnp.float32)), axis=ax, keepdims=True)
        return jnp.log(se) + m.astype(jnp.float32)

    @jax.custom_vjp
    def ce(lg):
        lab_logit = jnp.take_along_axis(lg, labx, axis=ax).astype(jnp.float32)
        return (_lse(lg) - lab_logit).squeeze(ax)

    def fwd(lg):
        lse = _lse(lg)
        lab_logit = jnp.take_along_axis(lg, labx, axis=ax).astype(jnp.float32)
        return (lse - lab_logit).squeeze(ax), (lg, lse)

    def bwd(res, g):
        lg, lse = res
        gx = jnp.expand_dims(g, ax).astype(jnp.float32)
        p = jnp.exp(lg.astype(jnp.float32) - lse)
        iota = jax.lax.broadcasted_iota(labx.dtype, lg.shape, ax)
        dlg = (p - (iota == labx).astype(jnp.float32)) * gx
        return (dlg.astype(lg.dtype),)

    ce.defvjp(fwd, bwd)
    return ce(logits)


def cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean", soft_label=False, axis=-1, use_softmax=True, label_smoothing=0.0, name=None):
    aux = [ensure_tensor(weight)] if weight is not None else []

    def fn(logits, lbl, *ws):
        w = ws[0] if ws else None
        if not soft_label and use_softmax and label_smoothing == 0.0:
            lab = lbl
            if lab.ndim == logits.ndim and lab.shape[axis] == 1:
                lab = jnp.squeeze(lab, axis=axis)
            lab = lab.astype(jnp.int32)
            loss = _fused_softmax_ce(logits, lab, axis)
            valid = lab != ignore_index
            loss = jnp.where(valid, loss, 0.0)
            if w is not None:
                loss = loss * jnp.take(w, jnp.maximum(lab, 0))
            if reduction == "mean":
                denom = jnp.maximum(jnp.sum(valid.astype(loss.dtype)), 1.0) if w is None else jnp.sum(
                    jnp.where(valid, jnp.take(w, jnp.maximum(lab, 0)), 0.0)
                )
                return jnp.sum(loss) / denom
            return _reduce(loss, reduction)
        # non-fused fallback (soft labels / smoothing / pre-softmaxed input):
        # full f32 log-probs, matching the pre-AMP-change numerics
        if logits.dtype in (jnp.bfloat16, jnp.float16):
            logits = logits.astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=axis) if use_softmax else jnp.log(jnp.maximum(logits, 1e-30))
        if soft_label:
            loss = -jnp.sum(lbl * logp, axis=axis)
        else:
            lab = lbl
            if lab.ndim == logits.ndim and lab.shape[axis] == 1:
                lab = jnp.squeeze(lab, axis=axis)
            lab = lab.astype(jnp.int32)
            n = logits.shape[axis]
            onehot = jax.nn.one_hot(lab, n, dtype=logp.dtype, axis=axis)
            smoothed = onehot * (1.0 - label_smoothing) + label_smoothing / n
            loss = -jnp.sum(smoothed * logp, axis=axis)
            valid = lab != ignore_index
            loss = jnp.where(valid, loss, 0.0)
            if w is not None:
                loss = loss * jnp.take(w, jnp.maximum(lab, 0))
            if reduction == "mean":
                denom = jnp.maximum(jnp.sum(valid.astype(loss.dtype)), 1.0) if w is None else jnp.sum(
                    jnp.where(valid, jnp.take(w, jnp.maximum(lab, 0)), 0.0)
                )
                return jnp.sum(loss) / denom
        return _reduce(loss, reduction)

    return op(fn, ensure_tensor(input), ensure_tensor(label), *aux, _name="cross_entropy")


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100, axis=-1, return_softmax=False):
    out = cross_entropy(logits, label, soft_label=soft_label, ignore_index=ignore_index, reduction="none", axis=axis)
    if return_softmax:
        from .activation import softmax as _softmax

        return out, _softmax(logits, axis=axis)
    return out


def _f32(x):
    """Upcast low-precision inputs for loss math (fuses into the consumer)."""
    return x.astype(jnp.float32) if x.dtype in (jnp.bfloat16, jnp.float16) else x


def mse_loss(input, label, reduction="mean", name=None):
    return op(lambda a, b: _reduce(jnp.square(_f32(a) - _f32(b)), reduction), ensure_tensor(input), ensure_tensor(label), _name="mse_loss")


def l1_loss(input, label, reduction="mean", name=None):
    return op(lambda a, b: _reduce(jnp.abs(_f32(a) - _f32(b)), reduction), ensure_tensor(input), ensure_tensor(label), _name="l1_loss")


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def fn(a, b):
        d = _f32(a) - _f32(b)
        absd = jnp.abs(d)
        loss = jnp.where(absd < delta, 0.5 * d * d / delta, absd - 0.5 * delta)
        return _reduce(loss, reduction)

    return op(fn, ensure_tensor(input), ensure_tensor(label), _name="smooth_l1_loss")


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean", name=None):
    aux = [ensure_tensor(weight)] if weight is not None else []

    def fn(logp, lbl, *ws):
        lbl = lbl.astype(jnp.int32)
        w = ws[0] if ws else None
        # class axis is 1 for ndim>=2 ([N,C] or [N,C,d1,...]); gather the
        # label's log-prob along it.
        loss = -jnp.take_along_axis(logp, jnp.expand_dims(lbl, 1), axis=1).squeeze(1)
        valid = lbl != ignore_index
        loss = jnp.where(valid, loss, 0.0)
        if w is not None:
            loss = loss * jnp.take(w, jnp.maximum(lbl, 0))
        if reduction == "mean":
            denom = jnp.maximum(jnp.sum(valid), 1) if w is None else jnp.sum(jnp.where(valid, jnp.take(w, jnp.maximum(lbl, 0)), 0.0))
            return jnp.sum(loss) / denom
        return _reduce(loss, reduction)

    return op(fn, ensure_tensor(input), ensure_tensor(label), *aux, _name="nll_loss")


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    aux = [ensure_tensor(weight)] if weight is not None else []

    def fn(p, y, *ws):
        p2 = jnp.clip(_f32(p), 1e-12, 1.0 - 1e-7)
        loss = -(y * jnp.log(p2) + (1.0 - y) * jnp.log(1.0 - p2))
        if ws:
            loss = loss * ws[0]
        return _reduce(loss, reduction)

    return op(fn, ensure_tensor(input), ensure_tensor(label), *aux, _name="bce")


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean", pos_weight=None, name=None):
    aux = [ensure_tensor(m) for m in (pos_weight, weight) if m is not None]
    has_pw, has_w = pos_weight is not None, weight is not None

    def fn(z, y, *extra):
        z = _f32(z)
        if has_pw:
            pw = extra[0]
            loss = (1 - y) * z + (1 + (pw - 1) * y) * (jnp.logaddexp(0.0, -jnp.abs(z)) + jnp.maximum(-z, 0))
        else:
            loss = jnp.maximum(z, 0) - z * y + jnp.logaddexp(0.0, -jnp.abs(z))
        if has_w:
            loss = loss * extra[-1]
        return _reduce(loss, reduction)

    return op(fn, ensure_tensor(logit), ensure_tensor(label), *aux, _name="bce_with_logits")


def kl_div(input, label, reduction="mean", name=None):
    def fn(logp, y):
        loss = y * (jnp.log(jnp.maximum(y, 1e-30)) - logp)
        if reduction == "batchmean":
            return jnp.sum(loss) / logp.shape[0]
        return _reduce(loss, reduction)

    return op(fn, ensure_tensor(input), ensure_tensor(label), _name="kl_div")


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):
    return op(
        lambda a, b, y: _reduce(jnp.maximum(0.0, -y * (a - b) + margin), reduction),
        ensure_tensor(input),
        ensure_tensor(other),
        ensure_tensor(label),
        _name="margin_ranking_loss",
    )


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    return op(
        lambda a, y: _reduce(jnp.where(y == 1.0, a, jnp.maximum(0.0, margin - a)), reduction),
        ensure_tensor(input),
        ensure_tensor(label),
        _name="hinge_embedding_loss",
    )


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean", name=None):
    def fn(a, b, y):
        cos = jnp.sum(a * b, axis=-1) / jnp.maximum(jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12)
        loss = jnp.where(y == 1, 1.0 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce(loss, reduction)

    return op(fn, ensure_tensor(input1), ensure_tensor(input2), ensure_tensor(label), _name="cosine_embedding_loss")


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0, epsilon=1e-6, swap=False, reduction="mean", name=None):
    def fn(a, pos, neg):
        dp = jnp.linalg.norm(a - pos + epsilon, ord=p, axis=-1)
        dn = jnp.linalg.norm(a - neg + epsilon, ord=p, axis=-1)
        if swap:
            dn2 = jnp.linalg.norm(pos - neg + epsilon, ord=p, axis=-1)
            dn = jnp.minimum(dn, dn2)
        return _reduce(jnp.maximum(dp - dn + margin, 0.0), reduction)

    return op(fn, ensure_tensor(input), ensure_tensor(positive), ensure_tensor(negative), _name="triplet_margin_loss")


def log_loss(input, label, epsilon=1e-4, name=None):
    return op(
        lambda p, y: -y * jnp.log(p + epsilon) - (1.0 - y) * jnp.log(1.0 - p + epsilon),
        ensure_tensor(input),
        ensure_tensor(label),
        _name="log_loss",
    )


def square_error_cost(input, label):
    return op(lambda a, b: jnp.square(a - b), ensure_tensor(input), ensure_tensor(label), _name="square_error_cost")


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0, reduction="sum", name=None):
    def fn(z, y):
        p = jax.nn.sigmoid(z)
        ce = jnp.maximum(z, 0) - z * y + jnp.logaddexp(0.0, -jnp.abs(z))
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        loss = a_t * jnp.power(1 - p_t, gamma) * ce
        if normalizer is not None:
            loss = loss / unwrap(normalizer)
        return _reduce(loss, reduction)

    return op(fn, ensure_tensor(logit), ensure_tensor(label), _name="sigmoid_focal_loss")


# -- round-4 loss tail ------------------------------------------------------


def dice_loss(input, label, epsilon=1e-5, name=None):
    """1 - 2|X∩Y|/(|X|+|Y|) over the last axis (reference
    nn/functional/loss.py dice_loss)."""
    x, y = ensure_tensor(input), ensure_tensor(label)

    def fn(p, t):
        t1 = jax.nn.one_hot(t.squeeze(-1), p.shape[-1], dtype=p.dtype)
        red = tuple(range(1, p.ndim))
        inter = jnp.sum(p * t1, axis=red)
        union = jnp.sum(p, axis=red) + jnp.sum(t1, axis=red)
        return jnp.mean(1.0 - (2.0 * inter + epsilon) / (union + epsilon))

    return op(fn, x, y, _name="dice_loss")


def npair_loss(anchor, positive, labels, l2_reg=0.002, name=None):
    """N-pair loss (reference npair_loss): cross entropy over
    anchor·positiveᵀ similarities + L2 on embeddings."""
    a, p, lab = ensure_tensor(anchor), ensure_tensor(positive), ensure_tensor(labels)

    def fn(av, pv, lv):
        lv = lv.reshape(-1, 1)
        tgt = (lv == lv.T).astype(jnp.float32)
        tgt = tgt / jnp.sum(tgt, axis=1, keepdims=True)
        logits = av.astype(jnp.float32) @ pv.astype(jnp.float32).T
        ce = -jnp.mean(jnp.sum(tgt * jax.nn.log_softmax(logits, axis=1), axis=1))
        reg = l2_reg * 0.25 * (jnp.mean(jnp.sum(av.astype(jnp.float32) ** 2, 1))
                               + jnp.mean(jnp.sum(pv.astype(jnp.float32) ** 2, 1)))
        return ce + reg

    return op(fn, a, p, lab, _name="npair_loss")


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0, reduction="mean", name=None):
    """CTC forward algorithm in log space via lax.scan (reference
    warpctc_op / nn.functional.ctc_loss). log_probs: [T, B, C] logits
    (softmax applied internally, reference contract), labels [B, L]."""
    lp, lab = ensure_tensor(log_probs), ensure_tensor(labels)
    il, ll = ensure_tensor(input_lengths), ensure_tensor(label_lengths)

    def fn(logits, lv, ilv, llv):
        T, B, C = logits.shape
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        L = lv.shape[1]
        S = 2 * L + 1
        # extended label sequence: blank l1 blank l2 ... blank
        ext = jnp.full((B, S), blank, jnp.int32)
        ext = ext.at[:, 1::2].set(lv.astype(jnp.int32))
        neg = jnp.float32(-1e30)
        alpha0 = jnp.full((B, S), neg)
        alpha0 = alpha0.at[:, 0].set(logp[0, jnp.arange(B), blank])
        alpha0 = alpha0.at[:, 1].set(jnp.where(llv > 0, logp[0, jnp.arange(B), ext[:, 1]], neg))

        allow_skip = jnp.concatenate(
            [jnp.zeros((B, 2), bool),
             (ext[:, 2:] != blank) & (ext[:, 2:] != ext[:, :-2])], axis=1)

        def step(alpha, t):
            a1 = jnp.concatenate([jnp.full((B, 1), neg), alpha[:, :-1]], axis=1)
            a2 = jnp.concatenate([jnp.full((B, 2), neg), alpha[:, :-2]], axis=1)
            a2 = jnp.where(allow_skip, a2, neg)
            merged = jnp.logaddexp(jnp.logaddexp(alpha, a1), a2)
            emit = jnp.take_along_axis(logp[t], ext, axis=1)
            new = merged + emit
            return jnp.where((t < ilv)[:, None], new, alpha), None

        alpha, _ = jax.lax.scan(step, alpha0, jnp.arange(1, T))
        sidx = 2 * llv.astype(jnp.int32)
        last_blank = jnp.take_along_axis(alpha, sidx[:, None], axis=1)[:, 0]
        last_label = jnp.take_along_axis(alpha, jnp.maximum(sidx - 1, 0)[:, None], axis=1)[:, 0]
        ll_ = jnp.logaddexp(last_blank, jnp.where(llv > 0, last_label, neg))
        loss = -ll_
        if reduction == "mean":
            return jnp.mean(loss / jnp.maximum(llv.astype(jnp.float32), 1.0))
        if reduction == "sum":
            return jnp.sum(loss)
        return loss

    return op(fn, lp, lab, il, ll, _name="ctc_loss")


def hsigmoid_loss(input, label, num_classes, weight, bias=None, path_table=None, path_code=None, is_sparse=False, name=None):
    """Hierarchical sigmoid with the default complete binary tree
    (reference hsigmoid_loss / hierarchical_sigmoid_op). weight:
    [num_classes-1, feature]."""
    if path_table is not None or path_code is not None:
        raise NotImplementedError("custom trees not supported; use the default tree")
    x, lab, w = ensure_tensor(input), ensure_tensor(label), ensure_tensor(weight)
    args = [x, lab, w] + ([ensure_tensor(bias)] if bias is not None else [])
    # default tree depth
    import math as _m

    depth = max(1, int(_m.ceil(_m.log2(max(num_classes, 2)))))

    def fn(xv, lv, wv, *rest):
        bv = rest[0] if rest else None
        B = xv.shape[0]
        code = lv.reshape(-1).astype(jnp.int32) + num_classes  # leaf node id in implicit heap
        loss = jnp.zeros((B,), jnp.float32)
        for _ in range(depth):
            parent = code // 2
            is_right = (code % 2).astype(jnp.float32)
            valid = parent >= 1
            nw = wv[jnp.clip(parent - 1, 0, wv.shape[0] - 1)]
            logit = jnp.sum(xv.astype(jnp.float32) * nw.astype(jnp.float32), axis=1)
            if bv is not None:
                logit = logit + bv.reshape(-1)[jnp.clip(parent - 1, 0, wv.shape[0] - 1)].astype(jnp.float32)
            # right child => sigmoid(logit), left => 1 - sigmoid
            ll_ = jax.nn.log_sigmoid(jnp.where(is_right > 0, logit, -logit))
            loss = loss - jnp.where(valid, ll_, 0.0)
            code = parent
        return jnp.mean(loss)

    return op(fn, *args, _name="hsigmoid_loss")


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5, margin3=0.0, scale=64.0, group=None, return_softmax=False, reduction="mean", name=None):
    """ArcFace-family margin softmax (reference margin_cross_entropy_op):
    cos(m1·θ + m2) - m3 on the target logit, then scaled CE."""
    if group is not None:
        raise NotImplementedError(
            "margin_cross_entropy over a model-parallel group: use the "
            "vocab/class-sharded ParallelCrossEntropy path (distributed "
            "mp_layers) — per-shard-only softmax would be silently wrong")
    lg, lab = ensure_tensor(logits), ensure_tensor(label)

    def fn(lv, yv):
        y = yv.reshape(-1).astype(jnp.int32)
        cos = jnp.clip(lv.astype(jnp.float32), -1.0, 1.0)
        theta = jnp.arccos(cos)
        tgt = jnp.cos(margin1 * theta + margin2) - margin3
        onehot = jax.nn.one_hot(y, lv.shape[-1], dtype=jnp.float32)
        out = jnp.where(onehot > 0, tgt, cos) * scale
        logp = jax.nn.log_softmax(out, axis=-1)
        loss = -jnp.take_along_axis(logp, y[:, None], axis=1)[:, 0]
        if reduction == "mean":
            loss = jnp.mean(loss)
        elif reduction == "sum":
            loss = jnp.sum(loss)
        if return_softmax:
            return loss, jnp.exp(logp)
        return loss

    return op(fn, lg, lab, _name="margin_cross_entropy")
