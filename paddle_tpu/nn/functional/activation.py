"""Activation functionals (parity: python/paddle/nn/functional/activation.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...tensor._helpers import ensure_tensor, op, unwrap


def relu(x, name=None):
    return op(jax.nn.relu, ensure_tensor(x), _name="relu")


def relu6(x, name=None):
    return op(jax.nn.relu6, ensure_tensor(x), _name="relu6")


def elu(x, alpha=1.0, name=None):
    return op(lambda v: jax.nn.elu(v, alpha=alpha), ensure_tensor(x), _name="elu")


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return op(lambda v: scale * jnp.where(v > 0, v, alpha * jnp.expm1(v)), ensure_tensor(x), _name="selu")


def gelu(x, approximate=False, name=None):
    return op(lambda v: jax.nn.gelu(v, approximate=approximate), ensure_tensor(x), _name="gelu")


def sigmoid(x, name=None):
    return op(jax.nn.sigmoid, ensure_tensor(x), _name="sigmoid")


def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return op(lambda v: jnp.clip(slope * v + offset, 0.0, 1.0), ensure_tensor(x), _name="hardsigmoid")


def hardswish(x, name=None):
    return op(lambda v: v * jnp.clip(v + 3.0, 0.0, 6.0) / 6.0, ensure_tensor(x), _name="hardswish")


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return op(lambda v: jnp.clip(v, min, max), ensure_tensor(x), _name="hardtanh")


def hardshrink(x, threshold=0.5, name=None):
    return op(lambda v: jnp.where(jnp.abs(v) > threshold, v, 0.0), ensure_tensor(x), _name="hardshrink")


def softshrink(x, threshold=0.5, name=None):
    return op(
        lambda v: jnp.where(v > threshold, v - threshold, jnp.where(v < -threshold, v + threshold, 0.0)),
        ensure_tensor(x),
        _name="softshrink",
    )


def tanhshrink(x, name=None):
    return op(lambda v: v - jnp.tanh(v), ensure_tensor(x), _name="tanhshrink")


def leaky_relu(x, negative_slope=0.01, name=None):
    return op(lambda v: jax.nn.leaky_relu(v, negative_slope), ensure_tensor(x), _name="leaky_relu")


def prelu(x, weight, data_format="NCHW", name=None):
    def fn(v, w):
        if w.size == 1:
            return jnp.where(v > 0, v, w.reshape(()) * v)
        shape = [1] * v.ndim
        ch_axis = 1 if data_format == "NCHW" else v.ndim - 1
        shape[ch_axis] = w.size
        return jnp.where(v > 0, v, w.reshape(shape) * v)

    return op(fn, ensure_tensor(x), ensure_tensor(weight), _name="prelu")


def rrelu(x, lower=0.125, upper=0.3333333, training=True, name=None):
    from ...framework import random as _random

    x = ensure_tensor(x)
    if training:
        key = _random.split_key()
        slope = jax.random.uniform(key, tuple(x.shape), minval=lower, maxval=upper)
    else:
        slope = (lower + upper) / 2.0
    return op(lambda v: jnp.where(v >= 0, v, slope * v), x, _name="rrelu")


def swish(x, name=None):
    return op(jax.nn.silu, ensure_tensor(x), _name="swish")


silu = swish


def mish(x, name=None):
    return op(lambda v: v * jnp.tanh(jax.nn.softplus(v)), ensure_tensor(x), _name="mish")


def softplus(x, beta=1, threshold=20, name=None):
    return op(lambda v: jnp.where(beta * v > threshold, v, jax.nn.softplus(beta * v) / beta), ensure_tensor(x), _name="softplus")


def softsign(x, name=None):
    return op(jax.nn.soft_sign, ensure_tensor(x), _name="softsign")


def tanh(x, name=None):
    return op(jnp.tanh, ensure_tensor(x), _name="tanh")


def softmax(x, axis=-1, dtype=None, name=None):
    return op(lambda v: jax.nn.softmax(v, axis=axis), ensure_tensor(x), _name="softmax")


def log_softmax(x, axis=-1, dtype=None, name=None):
    return op(lambda v: jax.nn.log_softmax(v, axis=axis), ensure_tensor(x), _name="log_softmax")


def log_sigmoid(x, name=None):
    return op(jax.nn.log_sigmoid, ensure_tensor(x), _name="log_sigmoid")


def maxout(x, groups, axis=1, name=None):
    def fn(v):
        shape = list(v.shape)
        c = shape[axis]
        new_shape = shape[:axis] + [c // groups, groups] + shape[axis + 1 :]
        return jnp.max(v.reshape(new_shape), axis=axis + 1)

    return op(fn, ensure_tensor(x), _name="maxout")


def glu(x, axis=-1, name=None):
    return op(lambda v: jax.nn.glu(v, axis=axis), ensure_tensor(x), _name="glu")


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from ...framework import random as _random

    x = ensure_tensor(x)
    key = _random.split_key()
    g = jax.random.gumbel(key, tuple(x.shape))

    def fn(v):
        y = jax.nn.softmax((v + g) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis, keepdims=True)
            y_hard = jnp.zeros_like(y)
            y_hard = jnp.put_along_axis(y_hard, idx, 1.0, axis=axis, inplace=False)
            y = jax.lax.stop_gradient(y_hard - y) + y
        return y

    return op(fn, x, _name="gumbel_softmax")


def celu(x, alpha=1.0, name=None):
    """max(0,x) + min(0, a*(exp(x/a)-1)) (reference nn/functional/activation.py celu)."""
    return op(lambda v: jnp.maximum(v, 0) + jnp.minimum(
        alpha * (jnp.exp(v / alpha) - 1.0), 0).astype(v.dtype), ensure_tensor(x), _name="celu")


def thresholded_relu(x, threshold=1.0, name=None):
    return op(lambda v: jnp.where(v > threshold, v, 0).astype(v.dtype),
              ensure_tensor(x), _name="thresholded_relu")


def relu_(x, name=None):
    from ...tensor.manipulation import _inplace

    return _inplace("relu_", ensure_tensor(x), relu)


def elu_(x, alpha=1.0, name=None):
    from ...tensor.manipulation import _inplace

    return _inplace("elu_", ensure_tensor(x), lambda v: elu(v, alpha))


def softmax_(x, axis=-1, dtype=None, name=None):
    from ...tensor.manipulation import _inplace

    return _inplace("softmax_", ensure_tensor(x), lambda v: softmax(v, axis, dtype))


def tanh_(x, name=None):
    from ...tensor.math import tanh_ as _t

    return _t(x)
