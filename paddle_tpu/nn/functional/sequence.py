"""Sequence ops: the TPU-native LoDTensor replacement.

Parity anchors: python/paddle/nn/functional/extension.py sequence_mask and
the fluid sequence ops (python/paddle/fluid/layers/sequence_lod.py:
sequence_pad, sequence_unpad, sequence_pool, sequence_softmax; C++ kernels
under paddle/fluid/operators/sequence_ops/).

The reference carries variable-length batches as LoDTensor (flat values +
level-of-detail offsets) and every sequence op walks the LoD. On TPU, XLA
wants static shapes, so the native representation is (padded dense
[batch, maxlen, ...], lengths [batch]) — the exact pair sequence_pad
produces. Every op here takes/returns that pair; masks are computed from
lengths with iota-compare, which XLA fuses into the consumer.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ...tensor._helpers import ensure_tensor, op

__all__ = ["sequence_mask", "sequence_pad", "sequence_unpad", "sequence_pool",
           "sequence_softmax", "sequence_expand"]


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    """[b] lengths -> [b, maxlen] 0/1 mask (paddle.nn.functional.sequence_mask).
    The mask's extent is a SHAPE, so ``maxlen`` (or, when None, max(x)) must
    be concrete — pass an int under tracing."""
    from ...framework.dtype import to_jax_dtype

    x = ensure_tensor(x)
    jdt = to_jax_dtype(dtype)
    if maxlen is None:
        maxlen = int(np.asarray(x._value).max())
    elif not isinstance(maxlen, int):
        maxlen = int(np.asarray(ensure_tensor(maxlen)._value))

    def fn(lens):
        return (jnp.arange(maxlen)[None, :] < lens[..., None]).astype(jdt)

    return op(fn, x, _name="sequence_mask")


def sequence_pad(sequences, pad_value=0.0, maxlen=None, name=None):
    """List of [len_i, ...] tensors -> (padded [b, maxlen, ...], lengths [b]).

    Reference sequence_pad consumes a LoDTensor; the list-of-tensors form is
    its eager equivalent (the LoD is exactly the per-item lengths). Host-side
    by design — padding happens at data-ingest, like the DataLoader collate.
    """
    seqs = [np.asarray(ensure_tensor(s)._value) for s in sequences]
    if not seqs:
        raise ValueError("sequence_pad needs at least one sequence")
    lengths = np.asarray([s.shape[0] for s in seqs], np.int64)
    m = int(maxlen) if maxlen is not None else int(lengths.max())
    if maxlen is not None and int(lengths.max()) > m:
        raise ValueError(f"maxlen={m} < longest sequence {int(lengths.max())}")
    tail = seqs[0].shape[1:]
    out = np.full((len(seqs), m) + tail, pad_value, seqs[0].dtype)
    for i, s in enumerate(seqs):
        out[i, : s.shape[0]] = s
    from ...framework.core import _wrap_value

    return _wrap_value(jnp.asarray(out)), _wrap_value(jnp.asarray(lengths))


def sequence_unpad(x, length, name=None):
    """(padded [b, maxlen, ...], lengths [b]) -> list of [len_i, ...] tensors
    (reference sequence_unpad returns the LoDTensor; a list is its eager
    form). Output shapes are data-dependent so the LENGTHS are read on the
    host, but each slice stays a tape op — gradients flow back into the
    padded input (zeros in the padding region)."""
    x = ensure_tensor(x)
    lens = np.asarray(ensure_tensor(length)._value, np.int64)
    return [op(lambda v, _i=int(i), _l=int(l): v[_i, :_l], x, _name="sequence_unpad")
            for i, l in enumerate(lens)]


def sequence_pool(x, lengths, pool_type="average", name=None):
    """Masked pooling over the time axis of (padded [b, t, ...], lengths):
    sum / average / sqrt / max / first / last (reference sequence_pool)."""
    pool_type = pool_type.lower()
    if pool_type not in ("sum", "average", "sqrt", "max", "first", "last"):
        raise ValueError(f"unknown pool_type {pool_type!r}")
    x, lens = ensure_tensor(x), ensure_tensor(lengths)

    def fn(v, ln):
        t = v.shape[1]
        mask = jnp.arange(t)[None, :] < ln[:, None]
        mexp = mask.reshape(mask.shape + (1,) * (v.ndim - 2))
        if pool_type in ("sum", "average", "sqrt"):
            s = jnp.sum(jnp.where(mexp, v, 0), axis=1)
            if pool_type == "sum":
                return s
            den = jnp.maximum(ln, 1).astype(v.dtype)
            den = den.reshape((-1,) + (1,) * (v.ndim - 2))
            return s / (jnp.sqrt(den) if pool_type == "sqrt" else den)
        if pool_type == "max":
            neg = jnp.asarray(jnp.finfo(v.dtype).min if jnp.issubdtype(v.dtype, jnp.floating)
                              else jnp.iinfo(v.dtype).min, v.dtype)
            return jnp.max(jnp.where(mexp, v, neg), axis=1)
        if pool_type == "first":
            return v[:, 0]
        idx = jnp.maximum(ln - 1, 0)
        return jnp.take_along_axis(v, idx.reshape((-1, 1) + (1,) * (v.ndim - 2)), axis=1)[:, 0]

    return op(fn, x, lens, _name=f"sequence_pool_{pool_type}")


def sequence_softmax(x, lengths, name=None):
    """Softmax over valid positions of the time axis; padded slots get 0
    (reference sequence_softmax over each sequence's LoD span)."""
    x, lens = ensure_tensor(x), ensure_tensor(lengths)

    def fn(v, ln):
        t = v.shape[1]
        mask = (jnp.arange(t)[None, :] < ln[:, None])
        mask = mask.reshape(mask.shape + (1,) * (v.ndim - 2))
        z = jnp.where(mask, v, -jnp.inf)
        z = z - jnp.max(z, axis=1, keepdims=True)
        e = jnp.where(mask, jnp.exp(z), 0)
        return e / jnp.maximum(jnp.sum(e, axis=1, keepdims=True), 1e-30)

    return op(fn, x, lens, _name="sequence_softmax")


def sequence_expand(x, lengths, name=None):
    """Repeat row i of x lengths[i] times along a new flat axis (the common
    reference sequence_expand use: broadcast per-sequence features onto
    per-token positions). Host-side sizes (data-dependent output shape)."""
    x = ensure_tensor(x)
    lens = np.asarray(ensure_tensor(lengths)._value, np.int64)
    # sizes are host-side (data-dependent output shape) but the repeat is a
    # tape op so gradients sum back over each row's repeats
    return op(lambda v: jnp.repeat(v, jnp.asarray(lens), axis=0,
                                   total_repeat_length=int(lens.sum())),
              x, _name="sequence_expand")
