"""Pooling functionals (parity: python/paddle/nn/functional/pooling.py).

reduce_window is the XLA-native pooling primitive (reference's
paddle/phi/kernels/gpu/pool_kernel.cu equivalent).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...tensor._helpers import ensure_tensor, op, unwrap


def _pair(v, n):
    return list(v) if isinstance(v, (list, tuple)) else [v] * n


def _pool(x, kernel, stride, padding, n, init, reduce_fn, avg=False, ceil_mode=False, exclusive=True, count_include_pad=False):
    ks = _pair(kernel, n)
    st = _pair(stride if stride is not None else kernel, n)
    pd = _pair(padding, n)

    def fn(v):
        window = [1, 1] + ks
        strides = [1, 1] + st
        pads = [(0, 0), (0, 0)] + [(p, p) for p in pd]
        if avg:
            summed = jax.lax.reduce_window(v, 0.0, jax.lax.add, window, strides, pads)
            if exclusive and not count_include_pad and any(pd):
                ones = jnp.ones_like(v)
                counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window, strides, pads)
                return summed / counts
            return summed / np.prod(ks)
        return jax.lax.reduce_window(v, init, reduce_fn, window, strides, pads)

    return op(fn, ensure_tensor(x), _name="pool")


def _max_pool_with_mask(x, kernel, stride, padding, n):
    """Pooled values + flat spatial argmax indices (reference max_pool
    mask semantics, consumed by max_unpool*). Windows are gathered as
    patches; the mask records each window max's flat index into the
    (unpadded) input spatial plane."""
    ks = _pair(kernel, n)
    st = _pair(stride if stride is not None else kernel, n)
    pd = _pair(padding, n)

    def fn(v):
        spatial = v.shape[2:]
        # patches pad with 0; shift values positive so padding can never win
        shift = jnp.min(v) - 1
        pt = jax.lax.conv_general_dilated_patches(
            v - shift, filter_shape=ks, window_strides=st, padding=[(p, p) for p in pd])
        N, C = v.shape[0], v.shape[1]
        out_sp = pt.shape[2:]
        pt = pt.reshape(N, C, int(np.prod(ks)), *out_sp)
        local = jnp.argmax(pt, axis=2)
        pooled = jnp.max(pt, axis=2) + shift
        # local window offset -> global flat index
        grids = jnp.meshgrid(*[jnp.arange(s) for s in out_sp], indexing="ij")
        loc = local
        flat = jnp.zeros_like(local)
        for i in range(n):
            kprod = int(np.prod(ks[i + 1:]))
            off_i = loc // kprod  # offset within window along dim i
            loc = loc % kprod
            gi = grids[i] * st[i] - pd[i] + off_i
            flat = flat * spatial[i] + gi
        return pooled, flat.astype(jnp.int32)

    return op(fn, ensure_tensor(x), _name="max_pool_mask")


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, name=None):
    if return_mask:
        return _max_pool_with_mask(x, kernel_size, stride, padding, 1)
    return _pool(x, kernel_size, stride, padding, 1, -jnp.inf, jax.lax.max)


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, data_format="NCHW", name=None):
    if return_mask:
        return _max_pool_with_mask(x, kernel_size, stride, padding, 2)
    return _pool(x, kernel_size, stride, padding, 2, -jnp.inf, jax.lax.max)


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, data_format="NCDHW", name=None):
    if return_mask:
        return _max_pool_with_mask(x, kernel_size, stride, padding, 3)
    return _pool(x, kernel_size, stride, padding, 3, -jnp.inf, jax.lax.max)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True, ceil_mode=False, name=None):
    return _pool(x, kernel_size, stride, padding, 1, 0.0, jax.lax.add, avg=True, exclusive=exclusive)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True, divisor_override=None, data_format="NCHW", name=None):
    return _pool(x, kernel_size, stride, padding, 2, 0.0, jax.lax.add, avg=True, exclusive=exclusive)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True, divisor_override=None, data_format="NCDHW", name=None):
    return _pool(x, kernel_size, stride, padding, 3, 0.0, jax.lax.add, avg=True, exclusive=exclusive)


def _adaptive_pool(x, output_size, n, avg=True):
    out_sp = _pair(output_size, n)

    def fn(v):
        in_sp = v.shape[2:]
        out = v
        # decompose into per-dim variable-window pooling using mean over splits
        for d in range(n):
            osz = out_sp[d] if out_sp[d] is not None else in_sp[d]
            isz = out.shape[2 + d]
            # windows: start[i] = floor(i*isz/osz), end[i] = ceil((i+1)*isz/osz)
            starts = [int(np.floor(i * isz / osz)) for i in range(osz)]
            ends = [int(np.ceil((i + 1) * isz / osz)) for i in range(osz)]
            slices = []
            for s, e in zip(starts, ends):
                seg = jax.lax.slice_in_dim(out, s, e, axis=2 + d)
                red = jnp.mean(seg, axis=2 + d, keepdims=True) if avg else jnp.max(seg, axis=2 + d, keepdims=True)
                slices.append(red)
            out = jnp.concatenate(slices, axis=2 + d)
        return out

    return op(fn, ensure_tensor(x), _name="adaptive_pool")


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive_pool(x, output_size, 1, avg=True)


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive_pool(x, output_size, 2, avg=True)


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_pool(x, output_size, 3, avg=True)


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 1, avg=False)


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 2, avg=False)


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 3, avg=False)
