"""Common functionals: linear/dropout/embedding/pad/... (parity:
python/paddle/nn/functional/common.py, input.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework import random as _random
from ...tensor._helpers import Tensor, ensure_tensor, op, to_jax_dtype, unwrap, _wrap_value


def linear(x, weight, bias=None, name=None):
    """y = x @ W + b. Weight layout [in, out] (paddle convention,
    python/paddle/nn/functional/common.py:1584)."""
    if bias is None:
        return op(lambda v, w: v @ w, ensure_tensor(x), ensure_tensor(weight), _name="linear")
    return op(lambda v, w, b: v @ w + b, ensure_tensor(x), ensure_tensor(weight), ensure_tensor(bias), _name="linear")


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None):
    x = ensure_tensor(x)
    if not training or p == 0.0:
        return x if mode == "upscale_in_train" else op(lambda v: v * (1.0 - p), x, _name="dropout_eval")
    axes = None if axis is None else (axis if isinstance(axis, (list, tuple)) else [axis])

    def fn(v, key, train):
        shape = tuple(v.shape) if axes is None else tuple(
            s if i in axes else 1 for i, s in enumerate(v.shape))
        # train==0 (a captured program flipped to inference) keeps everything
        keep = jax.random.bernoulli(key, 1.0 - p, shape) | (train == 0)
        if mode == "upscale_in_train":
            scale = jnp.where(train == 0, 1.0, 1.0 / (1.0 - p)).astype(v.dtype)
            return jnp.where(keep, v * scale, 0.0).astype(v.dtype)
        out = jnp.where(keep, v, 0.0).astype(v.dtype)
        return jnp.where(train == 0, (v * (1.0 - p)).astype(v.dtype), out)

    return op(fn, x, _random.key_tensor(), _random.train_flag_tensor(), _name="dropout")


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    axis = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p=p, axis=axis, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    axis = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p=p, axis=axis, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    x = ensure_tensor(x)
    if not training or p == 0.0:
        return x
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale

    def fn(v, key, train):
        keep = jax.random.bernoulli(key, 1.0 - p, tuple(v.shape))
        a = (1.0 / (scale * ((1.0 - p) * (1.0 + p * alpha_p**2)) ** 0.5))
        b = -a * alpha_p * p
        out = a * jnp.where(keep, v, alpha_p) + b
        return jnp.where(train == 0, v, out.astype(v.dtype))

    return op(fn, x, _random.key_tensor(), _random.train_flag_tensor(), _name="alpha_dropout")


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    """Row lookup with explicit out-of-range semantics.

    XLA's gather clamps bad indices quietly (an id >= vocab silently read
    the LAST row). Here the contract is explicit: in eager mode an
    out-of-range id raises a structured ``ValueError`` naming the id and
    its position; in traced code (where no host check can run) the lookup
    returns the ZERO row for out-of-range ids — deterministic, and a bad-id
    bug surfaces as missing signal instead of another row's gradient.
    ``padding_idx`` rows emit zeros and receive no gradient."""

    def fn(w, idx):
        from ...framework.selected_rows import is_traced_value

        v = w.shape[0]
        bad = (idx < 0) | (idx >= v)
        if not (is_traced_value(idx) or is_traced_value(w)):
            if bool(jnp.any(bad)):
                flat_bad = jnp.argmax(bad.reshape(-1))
                pos = int(flat_bad)
                offender = int(jnp.asarray(idx).reshape(-1)[pos])
                raise ValueError(
                    f"embedding(): id {offender} at flat position {pos} is "
                    f"out of range [0, {v}) for a {v}-row table")
        out = jnp.take(w, jnp.clip(idx, 0, v - 1), axis=0)
        out = jnp.where(bad[..., None], 0.0, out).astype(w.dtype)
        if padding_idx is not None:
            mask = (idx == padding_idx)[..., None]
            out = jnp.where(mask, 0.0, out)
        return out

    return op(fn, ensure_tensor(weight), ensure_tensor(x), _name="embedding")


def one_hot(x, num_classes, name=None):
    return op(lambda idx: jax.nn.one_hot(idx, num_classes, dtype=to_jax_dtype("float32")),
              ensure_tensor(x), _name="one_hot")


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    aux = [ensure_tensor(prior_dist)] if prior_dist is not None else []

    def fn(v, *pd):
        k = v.shape[-1]
        if pd:
            return (1.0 - epsilon) * v + epsilon * pd[0]
        return (1.0 - epsilon) * v + epsilon / k

    return op(fn, ensure_tensor(label), *aux, _name="label_smooth")


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    x = ensure_tensor(x)
    nd = x.ndim
    pad = [int(unwrap(p)) for p in pad]

    def fn(v):
        if len(pad) == 2 * nd:
            cfg = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
        else:
            # paddle semantics: pairs apply to spatial dims starting from the
            # LAST dim: [left, right, top, bottom, front, back] for NCHW means
            # (left,right)->W, (top,bottom)->H (nn/functional/common.py pad).
            n_spatial = len(pad) // 2
            cfg = [(0, 0)] * nd
            if data_format.startswith("NC"):
                dims = list(range(nd - 1, nd - 1 - n_spatial, -1))
            else:
                dims = list(range(nd - 2, nd - 2 - n_spatial, -1))
            for i, d in enumerate(dims):
                cfg[d] = (pad[2 * i], pad[2 * i + 1])
        jmode = {"constant": "constant", "reflect": "reflect", "replicate": "edge", "circular": "wrap"}[mode]
        if jmode == "constant":
            return jnp.pad(v, cfg, mode="constant", constant_values=value)
        return jnp.pad(v, cfg, mode=jmode)

    return op(fn, x, _name="pad")


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    return op(
        lambda v: v / jnp.maximum(jnp.linalg.norm(v, ord=p, axis=axis, keepdims=True), epsilon),
        ensure_tensor(x),
        _name="normalize",
    )


def cosine_similarity(x1, x2, axis=1, eps=1e-8, name=None):
    def fn(a, b):
        dot = jnp.sum(a * b, axis=axis)
        na = jnp.linalg.norm(a, axis=axis)
        nb = jnp.linalg.norm(b, axis=axis)
        return dot / jnp.maximum(na * nb, eps)

    return op(fn, ensure_tensor(x1), ensure_tensor(x2), _name="cosine_similarity")


def bilinear(x1, x2, weight, bias=None, name=None):
    def fn(a, b, w, *rest):
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        if rest:
            out = out + rest[0]
        return out

    args = [ensure_tensor(x1), ensure_tensor(x2), ensure_tensor(weight)]
    if bias is not None:
        args.append(ensure_tensor(bias))
    return op(fn, *args, _name="bilinear")


def interpolate(x, size=None, scale_factor=None, mode="nearest", align_corners=False, data_format="NCHW", name=None):
    x = ensure_tensor(x)

    def fn(v):
        if data_format == "NCHW":
            spatial = v.shape[2:]
        else:
            spatial = v.shape[1:-1]
        if size is not None:
            out_sp = [int(unwrap(s)) for s in (size if isinstance(size, (list, tuple)) else [size] * len(spatial))]
        else:
            sf = scale_factor if isinstance(scale_factor, (list, tuple)) else [scale_factor] * len(spatial)
            out_sp = [int(s * f) for s, f in zip(spatial, sf)]
        jmode = {"nearest": "nearest", "bilinear": "linear", "linear": "linear", "trilinear": "linear", "bicubic": "cubic", "area": "linear"}[mode]
        if data_format == "NCHW":
            out_shape = (*v.shape[:2], *out_sp)
        else:
            out_shape = (v.shape[0], *out_sp, v.shape[-1])
        return jax.image.resize(v, out_shape, method=jmode)

    return op(fn, x, _name="interpolate")


def upsample(x, size=None, scale_factor=None, mode="nearest", align_corners=False, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, data_format)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    x = ensure_tensor(x)
    ks = kernel_sizes if isinstance(kernel_sizes, (list, tuple)) else [kernel_sizes] * 2
    st = strides if isinstance(strides, (list, tuple)) else [strides] * 2
    pd = paddings if isinstance(paddings, (list, tuple)) else [paddings] * 2
    dl = dilations if isinstance(dilations, (list, tuple)) else [dilations] * 2

    def fn(v):
        n, c, h, w = v.shape
        v2 = jnp.pad(v, [(0, 0), (0, 0), (pd[0], pd[0]), (pd[1], pd[1])])
        patches = jax.lax.conv_general_dilated_patches(
            v2, filter_shape=ks, window_strides=st, padding="VALID", rhs_dilation=dl,
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
        )
        # -> [N, C*kh*kw, L]
        return patches.reshape(n, c * ks[0] * ks[1], -1)

    return op(fn, x, _name="unfold")
