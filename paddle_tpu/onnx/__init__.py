"""paddle.onnx parity: ``paddle.onnx.export``.

Parity: python/paddle/onnx/export.py (which delegates to the external
paddle2onnx converter over a traced program). Here the exporter walks this
repo's own static-trace IR (framework/static_trace.py Program) and emits a
standard ONNX ModelProto. The protobuf wire encoding is written directly
(onnx is not installed in this environment; the format is stable and small
— varint/length-delimited fields only), so the artifact is loadable by any
onnx runtime outside.
"""
from .export import export  # noqa: F401

__all__ = ["export"]
