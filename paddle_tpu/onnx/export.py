"""ONNX export over the static-trace IR.

The op allowlist maps this repo's primitive names (the `_name` labels the
tensor API records into Program ops) onto ONNX ops. Anything outside the
allowlist raises with the offending op named — same contract as the
reference's unsupported-op errors in paddle2onnx.
"""
from __future__ import annotations

import struct
from typing import Dict, List, Tuple

import numpy as np

# ---------------------------------------------------------------- protobuf
# Minimal writer for the proto3 wire format: varint (type 0) and
# length-delimited (type 2) fields are all ONNX needs.


def _varint(n: int) -> bytes:
    out = bytearray()
    n &= (1 << 64) - 1
    while True:
        b = n & 0x7F
        n >>= 7
        out.append(b | (0x80 if n else 0))
        if not n:
            return bytes(out)


def _tag(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def _field_varint(field: int, value: int) -> bytes:
    return _tag(field, 0) + _varint(value)


def _field_bytes(field: int, data: bytes) -> bytes:
    return _tag(field, 2) + _varint(len(data)) + data


def _field_str(field: int, s: str) -> bytes:
    return _field_bytes(field, s.encode())


# ONNX TensorProto.DataType
_DTYPE = {"float32": 1, "uint8": 2, "int8": 3, "int16": 5, "int32": 6,
          "int64": 7, "bool": 9, "float16": 10, "float64": 11, "bfloat16": 16}


def _tensor_proto(name: str, arr: np.ndarray) -> bytes:
    arr = np.ascontiguousarray(arr)
    out = b""
    for d in arr.shape:
        out += _field_varint(1, int(d))          # dims
    out += _field_varint(2, _DTYPE[str(arr.dtype)])  # data_type
    out += _field_str(8, name)                   # name
    out += _field_bytes(9, arr.tobytes())        # raw_data
    return out


def _value_info(name: str, shape, dtype: str) -> bytes:
    dims = b""
    for d in shape:
        if d is None or d < 0:
            dims += _field_bytes(1, _field_str(2, "batch"))   # dim_param
        else:
            dims += _field_bytes(1, _field_varint(1, int(d)))  # dim_value
    tensor_type = _field_varint(1, _DTYPE[dtype]) + _field_bytes(2, dims)
    type_proto = _field_bytes(1, tensor_type)
    return _field_str(1, name) + _field_bytes(2, type_proto)


def _attr_ints(name: str, values) -> bytes:
    out = _field_str(1, name)
    for v in values:
        out += _field_varint(8, int(v) & ((1 << 64) - 1))
    out += _field_varint(20, 7)  # AttributeType.INTS
    return out


def _attr_int(name: str, v: int) -> bytes:
    return _field_str(1, name) + _field_varint(3, int(v)) + _field_varint(20, 2)


def _attr_float(name: str, v: float) -> bytes:
    return (_field_str(1, name) + _tag(2, 5) + struct.pack("<f", float(v))
            + _field_varint(20, 1))


def _node(op_type: str, inputs: List[str], outputs: List[str], name: str = "", attrs: List[bytes] = ()) -> bytes:
    out = b""
    for i in inputs:
        out += _field_str(1, i)
    for o in outputs:
        out += _field_str(2, o)
    if name:
        out += _field_str(3, name)
    out += _field_str(4, op_type)
    for a in attrs:
        out += _field_bytes(5, a)
    return out


# ------------------------------------------------------------------ lowering

_ELEMENTWISE = {
    "add": "Add", "subtract": "Sub", "multiply": "Mul", "divide": "Div",
    "maximum": "Max", "minimum": "Min", "pow": "Pow",
}
_UNARY = {
    "relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh", "exp": "Exp",
    "log": "Log", "sqrt": "Sqrt", "abs": "Abs", "neg": "Neg", "erf": "Erf",
    "gelu": "Gelu",
}


def _lower_op(op, in_names: List[str], out_names: List[str], reg):
    """One Program op -> list of NodeProto bytes."""
    n = op.name
    if n in ("linear",):
        # x @ W + b -> Gemm (W is [in, out]; Gemm computes A·B + C directly)
        return [_node("Gemm", in_names, out_names, reg.fresh("gemm"))]
    if n in ("matmul", "mm", "bmm"):
        return [_node("MatMul", in_names[:2], out_names, reg.fresh("matmul"))]
    if n in _ELEMENTWISE:
        return [_node(_ELEMENTWISE[n], in_names[:2], out_names, reg.fresh(n))]
    if n in _UNARY:
        return [_node(_UNARY[n], in_names[:1], out_names, reg.fresh(n))]
    if n == "softmax":
        axis = op.kwargs.get("axis", -1)
        return [_node("Softmax", in_names[:1], out_names, reg.fresh(n), [_attr_int("axis", axis)])]
    if n in ("reshape", "flatten"):
        shape = [int(d) for d in op.outputs[0].shape]
        shape_name = reg.add_const(np.asarray([-1] + shape[1:], np.int64))
        return [_node("Reshape", [in_names[0], shape_name], out_names, reg.fresh(n))]
    if n == "conv2d":
        stride = op.kwargs.get("stride", 1)
        padding = op.kwargs.get("padding", 0)
        stride = [stride] * 2 if isinstance(stride, int) else list(stride)
        padding = [padding] * 4 if isinstance(padding, int) else list(padding) * 2
        attrs = [_attr_ints("strides", stride), _attr_ints("pads", padding)]
        return [_node("Conv", in_names, out_names, reg.fresh(n), attrs)]
    if n in ("dropout", "identity"):
        return [_node("Identity", in_names[:1], out_names, reg.fresh(n))]
    raise NotImplementedError(
        f"paddle.onnx.export: op {n!r} has no ONNX lowering yet "
        "(allowlist: linear/matmul/elementwise/activations/softmax/reshape/"
        "conv2d) — export via paddle.jit.save (StableHLO) instead")


class _Reg:
    def __init__(self):
        self.counter = 0
        self.extra_inits: List[bytes] = []

    def fresh(self, hint):
        self.counter += 1
        return f"{hint}_{self.counter}"

    def add_const(self, arr):
        name = self.fresh("const")
        self.extra_inits.append(_tensor_proto(name, arr))
        return name


def export(layer, path, input_spec=None, opset_version=13, **configs):
    """Trace ``layer`` with ``input_spec`` through the static recorder and
    write ``<path>.onnx``. Returns the file path."""
    from .. import static as static_mod
    from ..framework.core import Tensor
    from ..framework.static_trace import Program, pop_program, push_program
    from ..static import InputSpec

    if input_spec is None:
        raise ValueError("paddle.onnx.export requires input_spec")

    prog = Program()
    feeds = []
    push_program(prog)
    try:
        for i, spec in enumerate(input_spec):
            name = getattr(spec, "name", None) or f"x{i}"
            shape = [(-1 if (d is None or d < 0) else int(d)) for d in spec.shape]
            feeds.append(static_mod.data(name, shape, str(np.dtype(spec.dtype))))
        was_training = layer.training
        layer.eval()
        try:
            out = layer(*feeds)
        finally:
            if was_training:
                layer.train()
    finally:
        pop_program()
    outs = list(out) if isinstance(out, (tuple, list)) else [out]

    reg = _Reg()
    sym_names: Dict[int, str] = {}
    inits: List[bytes] = []
    init_seen = set()
    nodes: List[bytes] = []

    def name_of(kind, ref):
        if kind == "sym":
            return sym_names.setdefault(id(ref), ref.name)
        if kind == "tensor":
            nm = ref.name or f"param_{id(ref)}"
            if nm not in init_seen:
                init_seen.add(nm)
                inits.append(_tensor_proto(nm, np.asarray(ref._value)))
            return nm
        # const scalar: becomes an initializer
        return reg.add_const(np.asarray(ref))

    for op in prog.ops:
        in_names = [name_of(k, r) for k, r in op.inputs]
        out_names = [sym_names.setdefault(id(o), o.name) for o in op.outputs]
        nodes.extend(_lower_op(op, in_names, out_names, reg))

    graph = b""
    for nd in nodes:
        graph += _field_bytes(1, nd)
    graph += _field_str(2, "paddle_tpu_graph")
    for ini in inits + reg.extra_inits:
        graph += _field_bytes(5, ini)
    for f, spec in zip(feeds, input_spec):
        shape = [(None if (d is None or (isinstance(d, int) and d < 0)) else int(d)) for d in spec.shape]
        graph += _field_bytes(11, _value_info(f._value.name, shape, str(np.dtype(spec.dtype))))
    for o in outs:
        sv = o._value
        graph += _field_bytes(12, _value_info(sv.name, [int(d) if d >= 0 else None for d in sv.shape], str(sv.dtype)))

    model = _field_varint(1, 8)  # ir_version 8
    model += _field_str(2, "paddle_tpu")
    model += _field_bytes(7, graph)
    model += _field_bytes(8, _field_str(1, "") + _field_varint(2, int(opset_version)))

    out_path = str(path) + (".onnx" if not str(path).endswith(".onnx") else "")
    with open(out_path, "wb") as f:
        f.write(model)
    return out_path
