"""paddle.regularizer parity (reference python/paddle/regularizer.py):
L1Decay / L2Decay objects consumed as ``weight_decay=`` by optimizers or as
per-param ``ParamAttr.regularizer``. On the compiled path the decay folds
into the fused update like any weight_decay scalar."""
from __future__ import annotations

__all__ = ["L1Decay", "L2Decay"]


class L2Decay:
    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)
        self._regularization_coeff = self.coeff  # reference attr name

    def __float__(self):
        return self.coeff

    def __repr__(self):
        return f"L2Decay({self.coeff})"


class L1Decay:
    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)
        self._regularization_coeff = self.coeff

    def __float__(self):
        return self.coeff

    def __repr__(self):
        return f"L1Decay({self.coeff})"
