"""Functional higher-order autograd: vjp/jvp/jacobian/hessian.

Paddle parity: python/paddle/autograd/functional.py (vjp, jvp, Jacobian,
Hessian). TPU-first design: these are direct delegations to jax.vjp /
jax.jvp / jax.jacrev — no hand-built double-backward graphs. Functions take
and return eager Tensors; inside the transform the same Tensor ops trace
through jax.numpy.
"""
from __future__ import annotations

from typing import Callable, Sequence

import jax
import jax.numpy as jnp

from ..framework import no_grad
from ..framework.core import Tensor, _wrap_value, unwrap


def _as_list(x):
    return list(x) if isinstance(x, (tuple, list)) else [x]


def _tensorize(fn: Callable):
    """Lift a Tensor->Tensor function to arrays->arrays for jax transforms."""

    def array_fn(*arrs):
        ts = [_wrap_value(a) for a in arrs]
        with no_grad():
            out = fn(*ts)
        outs = _as_list(out)
        vals = tuple(unwrap(o) for o in outs)
        return vals if isinstance(out, (tuple, list)) else vals[0]

    return array_fn


def vjp(func: Callable, xs, v=None):
    """Vector-Jacobian product: returns (func(xs), vjp(v)).

    Parity with ``paddle.autograd.vjp`` — returns the forward outputs and the
    gradients of ``sum(out * v)`` w.r.t. ``xs``.
    """
    xs_l = _as_list(xs)
    arrs = [unwrap(x) for x in xs_l]
    out, pullback = jax.vjp(_tensorize(func), *arrs)
    multi_out = isinstance(out, tuple)
    if v is None:
        cot = jax.tree_util.tree_map(jnp.ones_like, out)
    else:
        v_l = _as_list(v)
        cot = tuple(unwrap(t) for t in v_l) if multi_out else unwrap(v_l[0])
    grads = pullback(cot)
    outs = tuple(_wrap_value(o) for o in out) if multi_out else _wrap_value(out)
    gs = [_wrap_value(g) for g in grads]
    return outs, (gs if len(gs) > 1 else gs[0])


def jvp(func: Callable, xs, v=None):
    """Jacobian-vector product: returns (func(xs), J @ v)."""
    xs_l = _as_list(xs)
    arrs = [unwrap(x) for x in xs_l]
    if v is None:
        tangents = tuple(jnp.ones_like(a) for a in arrs)
    else:
        tangents = tuple(unwrap(t) for t in _as_list(v))
    out, jvp_out = jax.jvp(_tensorize(func), tuple(arrs), tangents)
    wrap = lambda o: tuple(_wrap_value(x) for x in o) if isinstance(o, tuple) else _wrap_value(o)
    return wrap(out), wrap(jvp_out)


def jacobian(func: Callable, xs, create_graph: bool = False, allow_unused: bool = False):
    """Flattened 2-D Jacobian of ``func`` at ``xs``.

    For a single input/output: Tensor of shape ``(out.size, in.size)``.
    Multiple inputs/outputs: nested tuples J[i][j] over (output i, input j),
    matching the reference's ``Jacobian`` indexing.
    """
    xs_l = _as_list(xs)
    arrs = [unwrap(x) for x in xs_l]
    jac = jax.jacrev(_tensorize(func), argnums=tuple(range(len(arrs))))(*arrs)
    out_probe = jax.eval_shape(_tensorize(func), *arrs)  # structure only, no FLOPs
    multi_out = isinstance(out_probe, tuple)
    outs = list(out_probe) if multi_out else [out_probe]
    # jac layout: per-output (if multi) tuple over inputs of arrays with shape
    # out_shape + in_shape; flatten each block to 2-D.
    per_out = list(jac) if multi_out else [jac]

    def flatten_block(block, o, a):
        return _wrap_value(jnp.reshape(block, (int(o.size) or 1, int(a.size) or 1)))

    rows = []
    for o, jrow in zip(outs, per_out):
        blocks = [flatten_block(b, o, a) for b, a in zip(jrow, arrs)]
        rows.append(tuple(blocks) if len(blocks) > 1 else blocks[0])
    if multi_out:
        return tuple(rows)
    return rows[0]


def hessian(func: Callable, xs, create_graph: bool = False, allow_unused: bool = False):
    """Flattened Hessian of a scalar-valued ``func``: shape (in.size, in.size)."""
    xs_l = _as_list(xs)
    arrs = [unwrap(x) for x in xs_l]

    def scalar_fn(*a):
        out = _tensorize(func)(*a)
        if isinstance(out, tuple):
            raise ValueError("hessian requires a scalar-output function")
        return jnp.sum(out)

    h = jax.hessian(scalar_fn, argnums=tuple(range(len(arrs))))(*arrs)
    per_i = list(h) if len(arrs) > 1 else [(h,)] if not isinstance(h, tuple) else [h]
    if len(arrs) == 1:
        block = h[0][0] if isinstance(h, tuple) else h
        n = int(arrs[0].size) or 1
        return _wrap_value(jnp.reshape(block, (n, n)))
    result = []
    for i, hrow in enumerate(per_i):
        row = []
        for j, block in enumerate(hrow):
            ni, nj = int(arrs[i].size) or 1, int(arrs[j].size) or 1
            row.append(_wrap_value(jnp.reshape(block, (ni, nj))))
        result.append(tuple(row))
    return tuple(result)
