"""User-facing autograd: PyLayer custom functions + functional transforms.

Paddle parity: ``paddle.autograd.PyLayer`` (reference:
python/paddle/autograd/py_layer.py) and the functional jacobian/hessian API
(python/paddle/autograd/functional.py). TPU-first design: PyLayer's custom
backward is just another vjp closure on the eager tape; the functional API
delegates to jax.jacrev/jacfwd/jvp/vjp instead of building double-backward
graphs by hand.
"""
from __future__ import annotations

from ..framework import backward  # noqa: F401 — paddle.autograd.backward
from .py_layer import PyLayer, PyLayerContext  # noqa: F401
from .functional import hessian, jacobian, jvp, vjp  # noqa: F401

__all__ = ["backward", "PyLayer", "PyLayerContext", "hessian", "jacobian", "jvp", "vjp"]
