"""PyLayer: user-defined forward/backward pairs on the eager tape.

Paddle parity: ``paddle.autograd.PyLayer`` (reference:
python/paddle/autograd/py_layer.py — CPyLayer.apply / PyLayerContext
save_for_backward). TPU-first design: ``apply`` runs the user's forward with
the tape paused, then records ONE TapeNode whose vjp closure invokes the
user's ``backward``. The custom backward composes with ``jax.grad`` too: ops
built from :func:`paddle_tpu.framework.core.primitive` inside ``backward``
run eagerly, which is exactly the reference's semantics (backward of a
PyLayer is not itself differentiable unless written so).
"""
from __future__ import annotations

from typing import Any, List

from ..framework import no_grad
from ..framework.autograd import TapeNode, is_grad_enabled
from ..framework.core import Tensor, _is_float_array, _wrap_value


class PyLayerContext:
    """Context handed to forward/backward; carries saved tensors + user attrs.

    Parity: PyLayerContext (py_layer.py): ``save_for_backward`` /
    ``saved_tensor``; arbitrary attributes may be stashed on the ctx.
    """

    def __init__(self):
        self._saved: List[Tensor] = []
        self._materialize_grads = True

    def save_for_backward(self, *tensors):
        self._saved = list(tensors)

    def saved_tensor(self):
        return list(self._saved)

    def mark_not_inplace(self, *args):  # reference API; no-op (we never alias)
        pass

    def mark_non_differentiable(self, *args):
        self._non_diff = [id(a) for a in args]

    def set_materialize_grads(self, value: bool):
        self._materialize_grads = bool(value)


class PyLayerMeta(type):
    pass


class PyLayer(metaclass=PyLayerMeta):
    """Base for custom autograd functions.

    Usage parity with the reference::

        class Cube(PyLayer):
            @staticmethod
            def forward(ctx, x):
                ctx.save_for_backward(x)
                return x * x * x
            @staticmethod
            def backward(ctx, dy):
                (x,) = ctx.saved_tensor()
                return 3 * x * x * dy

        y = Cube.apply(x)
    """

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError("PyLayer subclasses must implement forward")

    @staticmethod
    def backward(ctx, *args):
        raise NotImplementedError("PyLayer subclasses must implement backward")

    @classmethod
    def apply(cls, *args, **kwargs):
        # Traced execution (inside jit.TrainStep / to_static / jax.grad over
        # raw arrays): the eager tape is absent, and letting jax.grad
        # differentiate *through* forward would silently ignore the user's
        # backward. Bridge to jax.custom_vjp instead so the custom backward
        # is honored in compiled graphs (reference parity: PyLayer grads are
        # part of the program, custom_operator.cc grad-op registration).
        import jax as _jax

        if any(isinstance(a, Tensor) and isinstance(a._value, _jax.core.Tracer)
               for a in list(args) + list(kwargs.values())):
            return cls._apply_traced(*args, **kwargs)
        ctx = PyLayerContext()
        # inputs that participate in grad flow: positional first, then kwargs
        # in insertion order (reference packs kwarg tensors into the graph too)
        all_inputs = list(args) + list(kwargs.values())
        diff_positions = [
            i
            for i, a in enumerate(all_inputs)
            if isinstance(a, Tensor) and not a.stop_gradient and _is_float_array(a._value)
        ] if is_grad_enabled() else []
        diff_inputs = [all_inputs[i] for i in diff_positions]
        tensor_positions = [i for i, a in enumerate(all_inputs) if isinstance(a, Tensor)]
        tensor_inputs = [all_inputs[i] for i in tensor_positions]

        with no_grad():
            out = cls.forward(ctx, *args, **kwargs)

        multi = isinstance(out, (tuple, list))
        outs = tuple(out) if multi else (out,)
        for o in outs:
            if not isinstance(o, Tensor):
                raise TypeError(f"PyLayer.forward must return Tensor(s), got {type(o)}")

        if not diff_inputs:
            return out

        non_diff = set(getattr(ctx, "_non_diff", ()))
        out_shapes = [(tuple(o._value.shape), o._value.dtype) for o in outs]

        def vjp_fn(cots):
            import jax.numpy as jnp

            cot_list = list(cots) if isinstance(cots, (tuple, list)) else [cots]
            if not ctx._materialize_grads:
                grad_out = [
                    None if c is None else _wrap_value(c if hasattr(c, "dtype") and _is_float_array(c) else jnp.asarray(c))
                    for c in cot_list
                ]
            else:
                grad_out = [
                    _wrap_value(
                        jnp.zeros(s, d) if c is None else (c if hasattr(c, "dtype") and _is_float_array(c) else jnp.asarray(c))
                    )
                    for c, (s, d) in zip(cot_list, out_shapes)
                ]
            with no_grad():
                gin = cls.backward(ctx, *grad_out)
            gin = list(gin) if isinstance(gin, (tuple, list)) else [gin]
            # reference semantics: backward returns one grad per *tensor* input,
            # positionally — the same tensor passed twice gets two distinct
            # partials, which the engine then accumulates.
            if len(gin) == len(tensor_inputs):
                pos_to_gin = dict(zip(tensor_positions, gin))
            elif len(gin) == len(diff_inputs):
                pos_to_gin = dict(zip(diff_positions, gin))
            else:
                raise ValueError(
                    f"PyLayer.backward returned {len(gin)} grads for "
                    f"{len(tensor_inputs)} tensor inputs"
                )
            result = []
            for p in diff_positions:
                t = all_inputs[p]
                g = pos_to_gin.get(p)
                if g is None:
                    result.append(jnp.zeros(t._value.shape, t._value.dtype))
                else:
                    result.append(g._value if isinstance(g, Tensor) else jnp.asarray(g))
            return tuple(result)

        vjp_fn._no_materialize_cots = True  # engine passes None for unused outputs
        node = TapeNode(vjp_fn, diff_inputs, len(outs), out_shapes, name=cls.__name__)
        wrapped = tuple(
            _wrap_value(
                o._value,
                stop_gradient=not _is_float_array(o._value) or id(o) in non_diff,
                node=node if _is_float_array(o._value) and id(o) not in non_diff else None,
                out_idx=i,
            )
            for i, o in enumerate(outs)
        )
        return wrapped if multi else wrapped[0]

    @classmethod
    def _apply_traced(cls, *args, **kwargs):
        """custom_vjp form used when inputs carry jax tracers. ctx python
        attributes set in forward are smuggled to backward via a closure cell
        (they are trace-time constants); saved tensors ride the residuals so
        they re-bind to the backward trace. Tensor kwargs are routed through
        the custom_vjp alongside positional tensors (the eager path includes
        them in grad flow too)."""
        import jax as _jax
        import jax.numpy as jnp

        tensor_idx = [i for i, a in enumerate(args) if isinstance(a, Tensor)]
        kw_keys = [k for k, v in kwargs.items() if isinstance(v, Tensor)]
        xs = tuple(args[i]._value for i in tensor_idx) + tuple(kwargs[k]._value for k in kw_keys)
        cell = {}

        def rebuild(vals):
            rebuilt = list(args)
            for i, v in zip(tensor_idx, vals):
                rebuilt[i] = _wrap_value(v, stop_gradient=args[i].stop_gradient)
            kw = dict(kwargs)
            for k, v in zip(kw_keys, vals[len(tensor_idx):]):
                kw[k] = _wrap_value(v, stop_gradient=kwargs[k].stop_gradient)
            return rebuilt, kw

        def run_forward(vals):
            ctx = PyLayerContext()
            pos, kw = rebuild(vals)
            with no_grad():
                out = cls.forward(ctx, *pos, **kw)
            multi = isinstance(out, (tuple, list))
            outs = tuple(out) if multi else (out,)
            return ctx, tuple(o._value for o in outs), multi

        @_jax.custom_vjp
        def f(*vals):
            _, out_vals, multi = run_forward(vals)
            return out_vals if multi else out_vals[0]

        def f_fwd(*vals):
            ctx, out_vals, multi = run_forward(vals)
            cell["ctx"], cell["multi"] = ctx, multi
            saved = tuple(t._value for t in ctx._saved)
            return (out_vals if multi else out_vals[0]), (vals, saved)

        def f_bwd(res, g):
            import numpy as np

            vals, saved = res
            ctx = cell["ctx"]
            ctx._saved = [_wrap_value(s) for s in saved]
            gs = tuple(g) if isinstance(g, (tuple, list)) else (g,)
            with no_grad():
                gin = cls.backward(ctx, *[_wrap_value(x) for x in gs])
            gin = list(gin) if isinstance(gin, (tuple, list)) else [gin]
            if len(gin) != len(vals):
                raise ValueError(
                    f"{cls.__name__}.backward returned {len(gin)} grads for "
                    f"{len(vals)} tensor inputs (traced PyLayer needs one per "
                    "tensor input)")

            def cot(t, v):
                if not _is_float_array(v):  # integer/bool primal: float0
                    return np.zeros(v.shape, _jax.dtypes.float0)
                if t is None:
                    return jnp.zeros(v.shape, v.dtype)
                return (t._value if isinstance(t, Tensor) else jnp.asarray(t)).astype(v.dtype).reshape(v.shape)

            return tuple(cot(t, v) for t, v in zip(gin, vals))

        f.defvjp(f_fwd, f_bwd)
        out = f(*xs)
        if cell.get("multi", isinstance(out, (tuple, list))):
            return tuple(_wrap_value(o) for o in out)
        return _wrap_value(out)
