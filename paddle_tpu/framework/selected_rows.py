"""SelectedRows: row-sparse tensor semantics for embedding gradients.

Parity anchor: paddle/phi/core/selected_rows.h (rows + value block of a
[height, ...] tensor) and the row-sparse optimizer kernels
(paddle/fluid/operators/optimizers/adam_op.h lazy_mode,
phi/kernels/selected_rows/). TPU-first framing: inside compiled steps the
gradient is a dense array (XLA scatter-add is native and fuses), so
SelectedRows here is (a) the API-parity container with merge/to_dense, and
(b) the EAGER optimizer contract: `Embedding(sparse=True)` records the rows
touched each forward, and SGD / Adam(lazy_mode=True) update only those rows
— O(batch-rows) optimizer cost instead of O(vocab), which is where large
embedding tables actually hurt.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def is_traced_value(v) -> bool:
    from .static_trace import is_symbolic

    if is_symbolic(v):
        return True
    try:
        import jax.core

        return isinstance(v, jax.core.Tracer)
    except Exception:
        return False


class SelectedRows:
    """Rows + values view of a [height, ...] tensor: row ``rows[i]`` holds
    ``values[i]``; unlisted rows are zero. Duplicate rows are allowed and
    sum (reference MergeAdd semantics)."""

    def __init__(self, rows, values, height: int):
        self._rows = jnp.asarray(rows, jnp.int32)
        self._values = jnp.asarray(values)
        if self._rows.ndim != 1 or self._values.shape[0] != self._rows.shape[0]:
            raise ValueError(f"rows {self._rows.shape} vs values {self._values.shape}")
        self._height = int(height)

    @property
    def rows(self):
        return self._rows

    @property
    def values(self):
        return self._values

    @property
    def height(self):
        return self._height

    def merge_add(self) -> "SelectedRows":
        """Coalesce duplicate rows by summation (reference
        phi/kernels/funcs/selected_rows_functor.h MergeAdd)."""
        uniq, inv = jnp.unique(self._rows, return_inverse=True)
        summed = jnp.zeros((uniq.shape[0],) + self._values.shape[1:], self._values.dtype)
        summed = summed.at[inv].add(self._values)
        return SelectedRows(uniq, summed, self._height)

    def to_dense(self):
        dense = jnp.zeros((self._height,) + self._values.shape[1:], self._values.dtype)
        return dense.at[self._rows].add(self._values)

    @staticmethod
    def from_dense(dense, rows, height=None) -> "SelectedRows":
        rows = jnp.asarray(rows, jnp.int32)
        return SelectedRows(rows, jnp.asarray(dense)[rows],
                            dense.shape[0] if height is None else height)

    def __repr__(self):
        return f"SelectedRows(height={self._height}, nnz_rows={int(self._rows.shape[0])}, dim={self._values.shape[1:]})"


def record_rows(param, ids) -> None:
    """Note embedding rows touched this forward on the weight parameter;
    consumed (and cleared) by the next eager optimizer step."""
    ids = np.unique(np.asarray(ids).ravel())
    param.__dict__.setdefault("_sparse_rows_pending", []).append(ids)


def take_pending_rows(param):
    pend = param.__dict__.get("_sparse_rows_pending")
    if not pend:
        return None
    rows = np.unique(np.concatenate(pend))
    pend.clear()
    return rows
