"""Global flag registry.

Paddle parity: ``PADDLE_DEFINE_EXPORTED_*`` gflags exposed to Python via
``paddle.set_flags``/``get_flags`` (reference: paddle/fluid/platform/flags.cc,
paddle/fluid/pybind/global_value_getter_setter.cc). Flags are overridable from
the environment (``FLAGS_*``) just like the reference.
"""
from __future__ import annotations

import os
from typing import Any, Dict

_REGISTRY: Dict[str, Any] = {}
# side-effecting flags: callback fired when the value is defined (import) or
# changed via set_flags — e.g. FLAGS_compile_cache_dir pushing jax.config
_ON_SET: Dict[str, Any] = {}


def on_flag_set(name: str, callback):
    """Register ``callback(value)`` to run now (with the current value) and
    on every subsequent ``set_flags`` of ``name``."""
    _ON_SET[name] = callback
    callback(_REGISTRY[name])


def define_flag(name: str, default, help_str: str = ""):
    env = os.environ.get(name)
    value = default
    if env is not None:
        if isinstance(default, bool):
            value = env.lower() in ("1", "true", "yes", "on")
        elif isinstance(default, int):
            value = int(env)
        elif isinstance(default, float):
            value = float(env)
        else:
            value = env
    _REGISTRY[name] = value
    return value


def set_flags(flags: Dict[str, Any]):
    for k, v in flags.items():
        if k not in _REGISTRY:
            raise KeyError(f"unknown flag {k!r}")
        _REGISTRY[k] = v
        if k in _ON_SET:
            _ON_SET[k](v)


def get_flags(flags):
    if isinstance(flags, str):
        flags = [flags]
    return {k: _REGISTRY[k] for k in flags}


def flag(name: str):
    return _REGISTRY[name]


# Core flags (subset of reference platform/flags.cc relevant on TPU).
define_flag("FLAGS_check_nan_inf", False, "check outputs for nan/inf after each eager op")
define_flag("FLAGS_benchmark", False, "synchronize after each op for timing")
define_flag("FLAGS_use_flash_attention", True, "use the Pallas flash-attention kernel when on TPU")
define_flag("FLAGS_flash_flat", False, "use the flat-lane (zero-relayout) flash kernels for packed qkv attention. Microbench verdict (bench.py flash_micro phase, CPU interpret, fwd+bwd [1,256,2,64]): flat ~1.7x classic under the interpreter (one fused packed pallas_call vs the classic pair's separate fwd/bwd launches); interpreter timings don't transfer to TPU, so stays opt-in pending the on-chip A/B (BASELINE.md: fwd verified correct+compiling in the r4 tunnel window, step A/B never ran)")
define_flag("FLAGS_kernel_overrides", "", "force kernel-registry implementations per kernel, e.g. 'moe=dense,sdpa=xla' (see paddle_tpu.ops.registry); forced impls bypass availability predicates; unknown impl names raise at dispatch")
define_flag("FLAGS_eager_delete_tensor_gb", 0.0, "compat no-op: XLA/PJRT manages buffers")
define_flag("FLAGS_allocator_strategy", "auto_growth", "compat no-op: PJRT BFC allocator is used")
define_flag("FLAGS_remat_policy", "none", "default rematerialization policy for jit steps")
define_flag("FLAGS_static_check", False, "run the paddle_tpu.analysis passes over each Program before its first compile in Executor.run; warnings are reported via the warnings module, error-severity diagnostics raise ProgramAnalysisError")
define_flag("FLAGS_executor_donate", False, "Executor.run donates parameter and optimizer-state buffers to the compiled program on training runs (flat param memory; stale outside handles raise StaleHandleError)")
define_flag("FLAGS_shard_check", False, "run the paddle_tpu.analysis.spmd PTA2xx passes over every lowered program once per new specialization (Executor.run, jit.TrainStep, inference.DecodeEngine, auto_parallel.Engine.prepare): implicit all-gathers, spec-mismatch reshards and decode-loop collectives warn with bytes-moved estimates, an HBM-budget overrun (FLAGS_hbm_budget_mb) raises ProgramAnalysisError before dispatch")
define_flag("FLAGS_hbm_budget_mb", 0.0, "per-device memory budget in MiB for the PTA204 pre-flight: a lowered program whose XLA memory_analysis estimate exceeds this raises under FLAGS_shard_check before the first dispatch (0 = unlimited)")
define_flag("FLAGS_compile_cache_dir", "", "persistent XLA compilation cache directory (jax_compilation_cache_dir): repeated runs of the same program skip recompiles. Env spelling: FLAGS_compile_cache_dir=/path (JAX's own JAX_COMPILATION_CACHE_DIR works too, but only this flag is visible to get_flags/set_flags)")


def _apply_compile_cache_dir(path):
    if not path:
        return
    import jax

    jax.config.update("jax_compilation_cache_dir", str(path))
    # cache every hit: the default 1s floor would skip exactly the small
    # specializations an Executor compiles dozens of
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)


on_flag_set("FLAGS_compile_cache_dir", _apply_compile_cache_dir)

# Dispatch-hygiene runtime sanitizer (paddle_tpu/analysis/sanitizer.py).
define_flag("FLAGS_sanitize", False, "runtime dispatch sanitizer: jax.transfer_guard('disallow') scoped around every hot-path dispatch (TrainStep, Executor.run, DecodeEngine — implicit device<->host transfers raise with the offending op named), a recompile-churn sentinel at every _dispatch site (> FLAGS_sanitize_max_recompiles signatures per logical callsite => RecompileChurnError naming the diffing aval), donated-state poisoning (reusing a donated TrainStep/DecodeEngine state leaf raises a structured StaleStateError instead of an XLA deleted-buffer crash), and a host-ledger growth sentinel on the serving-fleet tick")
define_flag("FLAGS_sanitize_max_recompiles", 8, "recompile-churn threshold: one logical dispatch callsite compiling more than this many distinct signatures trips the sentinel (warn by default, raise under FLAGS_sanitize_strict)")
define_flag("FLAGS_sanitize_strict", False, "escalate warn-only sanitizer findings (recompile churn, ledger growth) to raises; transfer-guard and stale-state violations always raise")

# Observability spine (paddle_tpu/observability/).
define_flag("FLAGS_monitor", True, "always-on runtime telemetry: step/compile/checkpoint run-log events, timeline spans and span histograms (spans become no-ops when off)")
define_flag("FLAGS_run_log_dir", "", "directory for the structured run log (JSONL, one run-<pid>.jsonl per process); empty keeps events only in the in-memory ring")
define_flag("FLAGS_run_log_max_mb", 64.0, "size-based run-log rotation: when run-<pid>.jsonl exceeds this many MiB it is renamed to run-<pid>.1.jsonl (replacing any prior rotation) and a fresh file is opened; 0 disables rotation (unbounded growth)")
define_flag("FLAGS_run_log_keep", 16, "keep-last-k GC of stale run logs: when a process opens its run log it deletes dead pids' run-*.jsonl files under FLAGS_run_log_dir beyond the newest k (by mtime); 0 disables the GC")
define_flag("FLAGS_trace", True, "distributed tracing plane (observability/trace.py): deterministic per-request/per-run trace ids propagated through ServingFleet submit->route->prefill->decode->requeue->delivery and run_resilient per-step/per-incident spans, emitted as 'span' run-log events; off allocates no ids and emits no span events (the bench's tracing-off arm)")
define_flag("FLAGS_metrics_port", 0, "live metrics export (observability/exporter.py): serve /metrics (Prometheus text), /healthz and /snapshot (JSON) on this localhost port from a stdlib HTTP server started by ServingFleet and run_resilient workers; 0 (default) disables the server")
define_flag("FLAGS_flightrec_events", 256, "crash flight recorder (observability/flightrec.py): dump the last N run-log ring events plus a metrics snapshot to flightrec-<pid>.json on replica death, DivergenceFault, PTA204/205 analysis errors and unhandled dispatch exceptions; 0 disables the recorder")
define_flag("FLAGS_slo", False, "judgment layer (observability/slo.py + regress.py): auto-install the default SLO spec set on the first serving/training tick and evaluate it on the FLAGS_slo_eval_every_s cadence — error budgets, multi-window burn-rate alerts ('alert' run-log events, /alerts, degraded /healthz while a page fires) and the perf-regression sentinel; off keeps every tick-loop hook a single flag check (explicit slo.install() still works)")
define_flag("FLAGS_slo_eval_every_s", 5.0, "SLOMonitor evaluation cadence in seconds: tick-loop hooks (scheduler/fleet/procfleet step, TrainStep.run_steps) evaluate the registered spec set at most this often; evaluation is host-side reads of the lock-free metrics registries — never a device sync")
define_flag("FLAGS_slo_fast_window_s", 300.0, "fast burn-rate window (seconds) for SLO alerting: the page-severity window — a burn rate >= the spec's page_burn sustained over this window pages. ~5 minutes in production; tests and the bench alerting arm shrink it to sub-second")
define_flag("FLAGS_slo_slow_window_s", 3600.0, "slow burn-rate window (seconds) for SLO alerting: the warn-severity window and the second gate of the page condition for ratio SLOs (classic multi-window burn-rate alerting). ~1 hour in production")

# Fault-tolerance runtime (distributed/resilience.py).
define_flag("FLAGS_collective_timeout_s", 0.0, "watchdog: report a cross-process collective still pending after this many seconds (0 = off)")
define_flag("FLAGS_store_retry_jitter", True, "full jitter on the resilience.retry/RetryingStore exponential backoff: attempt i sleeps uniform(0, min(max_delay, base_delay*2**i)) instead of the deterministic cap, so N replicas retrying a dead store spread out instead of thundering-herding. The jitter stream is seeded via framework.random (paddle.seed + PADDLE_TRAINER_ID), so chaos tests replay bitwise; off restores the pre-jitter deterministic sleeps")

# Training-health guard (jit.TrainStep guard / paddle_tpu.stability).
define_flag("FLAGS_train_guard", False, "fuse an all-finite check over loss+grads into every jit.TrainStep program and skip the param/opt/rng update in-graph when it trips (state stays bitwise at its pre-step value); read at TrainStep construction")
define_flag("FLAGS_dataloader_max_bad_batches", 0, "DataLoader: skip up to this many batches whose sample/collate raised (per iteration) instead of killing the iterator; 0 keeps the raise-through behavior")

# Deterministic fault injection (testing/chaos.py). All hooks are no-ops
# unless FLAGS_chaos is on; each knob below selects one failure mode.
define_flag("FLAGS_chaos", False, "master switch for deterministic fault injection")
define_flag("FLAGS_chaos_crash_point", "", "named crash point to fire (e.g. 'checkpoint_save', 'train_step')")
define_flag("FLAGS_chaos_crash_at_step", -1, "step index at which the crash point fires (-1: first hit)")
define_flag("FLAGS_chaos_corrupt_ckpt", False, "flip bytes in the next published checkpoint (on-disk corruption)")
define_flag("FLAGS_chaos_store_drop_ops", "", "comma list of store ops to fail, each 'op' or 'op:key-prefix'")
define_flag("FLAGS_chaos_store_drop_count", -1, "fail only the first N matching store ops, then heal (-1: always)")
define_flag("FLAGS_chaos_store_delay_s", 0.0, "sleep this long before every store op")
define_flag("FLAGS_chaos_freeze_heartbeat", "", "comma list of elastic node ids whose heartbeat stops refreshing")
define_flag("FLAGS_chaos_nan_at_step", -1, "inject non-finite gradients in-graph at this TrainStep step index (fires exactly once; read at TrainStep construction; -1 = off)")
define_flag("FLAGS_chaos_nan_steps", 1, "number of consecutive steps the NaN-gradient injection fires for (default 1)")
define_flag("FLAGS_chaos_replica_kill_at", "", "kill a serving-fleet engine replica mid-stream: 'R:K' kills replica R after its K-th decode tick (fires exactly once per replica per process). Drives the fleet kill/requeue tests")
define_flag("FLAGS_chaos_replica_slow_ms", "", "inject per-tick latency into serving-fleet replicas: 'MS' slows every replica, 'R:MS' only replica R, by MS milliseconds per scheduler tick (a straggler/overloaded host; long enough and the fleet's heartbeat tracking declares it dead)")
define_flag("FLAGS_chaos_replica_sigkill_at", "", "SIGKILL a cross-process serving replica mid-stream: 'R:K' makes the ProcServingFleet parent send SIGKILL to replica R's subprocess after harvesting its K-th tick message (fires exactly once per replica per process). The real-process form of FLAGS_chaos_replica_kill_at — no Python exception, the child just dies")
define_flag("FLAGS_chaos_replica_hang_ms", "", "wedge a cross-process serving replica without exiting: 'MS' (every replica) or 'R:MS' (one) makes the child stop publishing heartbeats for MS milliseconds after its first served tick while the process stays alive (a zombie the parent's stale-beat sweep must catch). Fires exactly once per replica per process")
define_flag("FLAGS_chaos_socket_drop_at", "", "kill the fast-path RPC socket mid-stream: 'R:K' (replica R) or 'K' (any) makes a SocketChannel writer kill its connection right before its K-th socket send (fires exactly once per replica per process). The channel must degrade to the store transport with no chunk lost or duplicated — the socket-fallback chaos pin")
define_flag("FLAGS_chaos_ingress_disconnect_at", -1, "drop the HTTP client connection mid-stream: the ingress force-closes a streaming response socket after writing N chunks (fires exactly once per process; -1 = off). Drives the client-disconnect -> mid-decode cancel() test without a real flaky client")
define_flag("FLAGS_chaos_net_delay_ms", 0.0, "sleep this many milliseconds before every fast-path socket frame send (both directions, both ends) — deterministic WAN latency for the transport-lag backpressure and TTFT-under-latency tests")
