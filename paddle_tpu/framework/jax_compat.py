"""Compatibility shims for the range of jax versions this repo runs on.

The codebase targets the current jax API surface (``jax.shard_map``,
``jax.export`` as eager attributes); older-but-supported releases (e.g.
0.4.3x) ship the same functionality under ``jax.experimental`` or as a
lazily-imported submodule. Importing this module (done once from
``paddle_tpu.framework``) binds the modern names so every call site can use
them unconditionally.
"""
from __future__ import annotations

import jax

if not hasattr(jax, "shard_map"):
    try:
        import functools
        import inspect

        from jax.experimental.shard_map import shard_map as _shard_map

        if "check_vma" in inspect.signature(_shard_map).parameters:
            jax.shard_map = _shard_map
        else:
            # pre-graduation shard_map spells today's kwargs differently:
            # check_vma was check_rep, and axis_names (axes the body is
            # manual over) was auto (the complement: axes left automatic)
            @functools.wraps(_shard_map)
            def shard_map(*args, **kwargs):
                if "check_vma" in kwargs:
                    kwargs["check_rep"] = kwargs.pop("check_vma")
                if "axis_names" in kwargs:
                    manual = set(kwargs.pop("axis_names"))
                    mesh = kwargs.get("mesh", args[1] if len(args) > 1 else None)
                    if mesh is not None:
                        # a size-1 axis is semantically identical manual or
                        # auto; keeping it manual dodges the partial-auto
                        # paths old shard_map never implemented
                        auto = frozenset(n for n in mesh.axis_names
                                         if n not in manual
                                         and mesh.shape[n] > 1)
                        if auto:
                            kwargs["auto"] = auto
                        else:
                            kwargs.setdefault("check_rep", False)
                return _shard_map(*args, **kwargs)

            jax.shard_map = shard_map
    except ImportError:  # pragma: no cover - very old jax; call sites raise
        pass

if not hasattr(jax.lax, "pcast"):
    # modern varying-manual-axes annotation; on old jax there is no vma
    # tracking (our shard_map shim disables check_rep when axes would need
    # it), so the annotation is an identity
    def _pcast(x, axis_name, *, to=None):
        return x

    jax.lax.pcast = _pcast

# `jax.export` is a real submodule but only resolvable as an attribute once
# imported; on versions where even that is absent, fall back to
# jax.experimental.export (same API, pre-graduation home).
try:
    import jax.export  # noqa: F401
except ImportError:  # pragma: no cover
    try:
        from jax.experimental import export as _export

        jax.export = _export
    except ImportError:
        pass


def compiled_cost_analysis(compiled) -> dict:
    """``Compiled.cost_analysis()`` normalized across jax versions/backends:
    older jax returns one dict per device (take the first), some backends
    return None or raise — both become ``{}`` so CPU-only CI sees the same
    call succeed."""
    try:
        cost = compiled.cost_analysis()
    except Exception:
        return {}
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    return dict(cost) if cost else {}


def compiled_memory_analysis(compiled):
    """``Compiled.memory_analysis()`` or None when the backend does not
    implement it (fields are read with getattr by callers)."""
    try:
        return compiled.memory_analysis()
    except Exception:
        return None
