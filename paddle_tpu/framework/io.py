"""paddle.save/load parity (reference: python/paddle/framework/io.py:574,791).

State dicts are pickled with tensors converted to numpy (protocol 4 for >4GB
chunking parity). Sharded/distributed checkpoints live in
paddle_tpu.distributed.checkpoint (orbax-backed).
"""
from __future__ import annotations

import os
import pickle
from collections import OrderedDict

import numpy as np

from .core import Tensor, _wrap_value


def _to_saveable(obj):
    if isinstance(obj, Tensor):
        return ("__tensor__", np.asarray(obj._value), obj.stop_gradient)
    if isinstance(obj, dict):
        return {k: _to_saveable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_saveable(v) for v in obj)
    return obj


def _from_saved(obj, return_numpy=False):
    import jax.numpy as jnp

    if isinstance(obj, tuple) and len(obj) == 3 and obj[0] == "__tensor__":
        if return_numpy:
            return obj[1]
        t = _wrap_value(jnp.asarray(obj[1]))
        t.stop_gradient = obj[2]
        return t
    if isinstance(obj, dict):
        return {k: _from_saved(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)) and not (len(obj) == 3 and obj and obj[0] == "__tensor__"):
        return type(obj)(_from_saved(v, return_numpy) for v in obj)
    return obj


def save(obj, path, protocol=4):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_to_saveable(obj), f, protocol=protocol)


def load(path, return_numpy=False, **config):
    with open(path, "rb") as f:
        obj = pickle.load(f)
    return _from_saved(obj, return_numpy=return_numpy)
