"""Eager autograd engine.

Paddle parity: the eager autograd graph of ``GradNodeBase`` +
``egr::Backward`` (reference: paddle/fluid/eager/grad_node_info.h:161,
paddle/fluid/eager/backward.cc:825). TPU-first design: instead of per-op
hand-written grad kernels, every recorded primitive stores the ``jax.vjp``
closure of its forward function — XLA differentiates the op, the tape only
does graph bookkeeping. Under ``jax.jit`` the tape is bypassed entirely
(grads come from ``jax.grad`` over the functional step), so the tape is the
debug/eager path, exactly like dygraph vs static in the reference.
"""
from __future__ import annotations

import contextlib
import threading
from typing import List, Optional, Sequence


class _GradMode(threading.local):
    enabled = True


_MODE = _GradMode()


def is_grad_enabled() -> bool:
    return _MODE.enabled


def set_grad_enabled(mode: bool):
    _MODE.enabled = bool(mode)


class no_grad(contextlib.ContextDecorator):
    """Context manager / decorator disabling tape recording (paddle.no_grad)."""

    def __enter__(self):
        self._prev = _MODE.enabled
        _MODE.enabled = False
        return self

    def __exit__(self, *exc):
        _MODE.enabled = self._prev
        return False


class enable_grad(contextlib.ContextDecorator):
    def __enter__(self):
        self._prev = _MODE.enabled
        _MODE.enabled = True
        return self

    def __exit__(self, *exc):
        _MODE.enabled = self._prev
        return False


class TapeNode:
    """One recorded primitive: vjp closure + references to the input tensors.

    Mirrors ``GradNodeBase`` (grad_node_info.h:161): ``in_tensors`` are the
    forward inputs that require grad (the node's "grad outs"), ``n_out`` the
    number of forward outputs (the node's "grad ins").
    """

    __slots__ = ("vjp_fn", "in_tensors", "n_out", "out_shapes", "name", "out_refs", "__weakref__")

    def __init__(self, vjp_fn, in_tensors: Sequence, n_out: int, out_shapes, name: str = ""):
        self.vjp_fn = vjp_fn
        self.in_tensors = list(in_tensors)
        self.n_out = n_out
        self.out_shapes = out_shapes  # [(shape, dtype)] per forward output
        self.name = name
        self.out_refs = None  # weakrefs to output tensors (for grad hooks)

    def release(self):
        self.vjp_fn = None
        self.in_tensors = []


def backward(tensors, grad_tensors=None, retain_graph=False):
    """Run reverse accumulation from ``tensors``.

    Engine parity with ``egr::Backward`` (backward.cc:825): build the
    reachable node graph, count in-degrees (pending fan-in), process ready
    nodes from a queue, accumulate fan-in cotangents, write ``.grad`` on leaf
    tensors (``GradNodeAccumulation`` parity, accumulation_node.h:23).
    """
    import jax.numpy as jnp

    from .core import Tensor, _wrap_value

    if not isinstance(tensors, (list, tuple)):
        tensors = [tensors]
    roots: List[Tensor] = list(tensors)
    if grad_tensors is None:
        grad_tensors = [None] * len(roots)
    elif not isinstance(grad_tensors, (list, tuple)):
        grad_tensors = [grad_tensors]

    # cotangent store: id(node) -> [list of per-output cotangents]
    node_cots = {}
    # discover reachable graph & in-degree (number of dependant downstream nodes)
    indeg = {}
    nodes = {}

    def discover(node):
        if id(node) in nodes:
            return
        nodes[id(node)] = node
        indeg.setdefault(id(node), 0)
        for t in node.in_tensors:
            prod = t._node
            if prod is not None:
                indeg[id(prod)] = indeg.get(id(prod), 0) + 1
                discover(prod)

    for root in roots:
        if root._node is not None:
            discover(root._node)

    # leaf tensors with hooks touched this pass: hooks run once on the final
    # accumulated grad (GradNodeAccumulation hook parity)
    hooked_leaves = []
    hooked_ids = set()

    def note_hooked_leaf(t):
        if getattr(t, "_hooks", None) and id(t) not in hooked_ids:
            hooked_ids.add(id(t))
            hooked_leaves.append(t)

    # seed cotangents
    for root, g in zip(roots, grad_tensors):
        if g is None:
            gval = jnp.ones_like(root._value)
        else:
            gval = g._value if isinstance(g, Tensor) else jnp.asarray(g)
        node = root._node
        if node is None:
            if not root.stop_gradient:
                _accum_grad(root, gval)
                note_hooked_leaf(root)
            continue
        if node.vjp_fn is None:
            raise RuntimeError(
                "Trying to backward through a graph that has already been freed. "
                "Pass retain_graph=True to backward() if you need to backward twice."
            )
        cots = node_cots.setdefault(id(node), [None] * node.n_out)
        idx = root._out_idx
        cots[idx] = gval if cots[idx] is None else cots[idx] + gval

    # ready queue = nodes with indeg 0 (no unprocessed consumers)
    queue = [n for nid, n in nodes.items() if indeg[nid] == 0]
    processed = []
    while queue:
        node = queue.pop()
        processed.append(node)
        cots = node_cots.pop(id(node), None)
        if cots is not None and node.vjp_fn is None:
            raise RuntimeError(
                "Trying to backward through a graph that has already been freed. "
                "Pass retain_graph=True to backward() if you need to backward twice."
            )
        if cots is not None and node.vjp_fn is not None:
            # fire output-tensor hooks on the fully-accumulated grad-in slots
            # (GradNodeBase::ApplyGradientHooks parity: once per node run)
            if node.out_refs is not None:
                for idx, c in enumerate(cots):
                    if c is None:
                        continue
                    t = node.out_refs[idx]() if node.out_refs[idx] is not None else None
                    if t is not None and getattr(t, "_hooks", None):
                        cots[idx] = _apply_hooks(t, c)
            in_cots = _call_vjp(node, cots)
            for t, c in zip(node.in_tensors, in_cots):
                prod = t._node
                if prod is None:
                    if not t.stop_gradient:
                        _accum_grad(t, c)
                        note_hooked_leaf(t)
                else:
                    pcots = node_cots.setdefault(id(prod), [None] * prod.n_out)
                    idx = t._out_idx
                    pcots[idx] = c if pcots[idx] is None else pcots[idx] + c
        # release consumer edges regardless of whether this node carried grads
        for t in node.in_tensors:
            prod = t._node
            if prod is not None:
                indeg[id(prod)] -= 1
                if indeg[id(prod)] == 0:
                    queue.append(prod)

    # leaf hooks: once, on the final accumulated grad
    for t in hooked_leaves:
        if t.grad is not None:
            t.grad._value = _apply_hooks(t, t.grad._value)

    if not retain_graph:
        for node in processed:
            node.release()

    # end-of-backward callbacks (reference: Reducer finalize_backward /
    # queued callbacks in backward.cc) — e.g. DataParallel's bucketed
    # all-reduce flush runs here, after every leaf grad has accumulated
    for cb in list(_POST_BACKWARD_CALLBACKS):
        cb()


_POST_BACKWARD_CALLBACKS: List = []


def register_post_backward_callback(fn):
    """Register ``fn()`` to run at the end of every ``backward()`` pass.
    Returns a handle with ``.remove()``."""

    class _Handle:
        def remove(self):
            try:
                _POST_BACKWARD_CALLBACKS.remove(fn)
            except ValueError:
                pass

    _POST_BACKWARD_CALLBACKS.append(fn)
    return _Handle()


def _call_vjp(node, cots):
    import jax
    import jax.numpy as jnp
    import numpy as np

    # A vjp_fn may opt out of zero-materialization (PyLayer
    # set_materialize_grads(False) parity): missing cotangents stay None.
    if getattr(node.vjp_fn, "_no_materialize_cots", False):
        out = node.vjp_fn(tuple(cots) if node.n_out > 1 else cots[0])
        return out
    # Replace missing cotangents (outputs unused downstream) with zeros of the
    # shape/dtype recorded at trace time. Integer/bool outputs take float0
    # cotangents per JAX convention.
    full = []
    for c, (shape, dtype) in zip(cots, node.out_shapes):
        if c is not None:
            full.append(c)
        elif jnp.issubdtype(dtype, jnp.floating) or jnp.issubdtype(dtype, jnp.complexfloating):
            full.append(jnp.zeros(shape, dtype))
        else:
            full.append(np.zeros(shape, jax.dtypes.float0))
    out = node.vjp_fn(tuple(full) if node.n_out > 1 else full[0])
    return out


def _apply_hooks(tensor, value):
    """Run the tensor's registered grad hooks (register_hook) on a freshly
    computed cotangent; a hook returning non-None replaces it."""
    hooks = getattr(tensor, "_hooks", None)
    if not hooks:
        return value
    from .core import Tensor, _wrap_value

    for hook in list(hooks):
        out = hook(_wrap_value(value))
        if out is not None:
            value = out._value if isinstance(out, Tensor) else out
    return value


def _accum_grad(tensor, value):
    from .core import Tensor

    if tensor.grad is None:
        g = Tensor.__new__(Tensor)
        g._init(value, stop_gradient=True)
        tensor.grad = g
    else:
        tensor.grad._value = tensor.grad._value + value
