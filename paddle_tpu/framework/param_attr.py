"""ParamAttr + device Place classes.

Parity anchors: python/paddle/fluid/param_attr.py (ParamAttr) and the
pybind Place types (paddle/phi/common/place.h). On TPU, Places are identity
markers — placement is the mesh/sharding's job — but the constructors exist
so reference code (`paddle.CPUPlace()`, `place=...` kwargs) runs unchanged.
"""
from __future__ import annotations

from typing import Any, Optional


class ParamAttr:
    """Parameter attribute bundle (reference fluid/param_attr.py:31):
    name, initializer, learning_rate (per-param LR scale), regularizer,
    trainable. Consumed by Layer.create_parameter."""

    def __init__(self, name: Optional[str] = None, initializer: Any = None,
                 learning_rate: float = 1.0, regularizer: Any = None,
                 trainable: bool = True, do_model_average: bool = True,
                 need_clip: bool = True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.do_model_average = do_model_average
        self.need_clip = need_clip


class _Place:
    _kind = "unknown"

    def __init__(self, device_id: int = 0):
        self.device_id = int(device_id)

    def __repr__(self):
        return f"{type(self).__name__}({self.device_id})"

    def __eq__(self, other):
        return type(self) is type(other) and self.device_id == other.device_id

    def __hash__(self):
        return hash((type(self).__name__, self.device_id))


class CPUPlace(_Place):
    _kind = "cpu"

    def __init__(self):
        super().__init__(0)

    def __repr__(self):
        return "CPUPlace"


class TPUPlace(_Place):
    _kind = "tpu"


class CUDAPlace(_Place):
    """Accepted for source compatibility; on this framework it denotes 'the
    accelerator' (the TPU chip) — there is no CUDA."""

    _kind = "tpu"


class CUDAPinnedPlace(_Place):
    _kind = "cpu"

    def __init__(self):
        super().__init__(0)


class NPUPlace(_Place):
    _kind = "tpu"
