from . import jax_compat  # noqa: F401  (must run before anything touches jax.*)
from .autograd import backward, enable_grad, is_grad_enabled, no_grad, set_grad_enabled
from .core import Tensor, get_device, is_compiled_with_tpu, primitive, set_device, unwrap
from .dtype import convert_dtype, get_default_dtype, set_default_dtype, to_jax_dtype
from .flags import define_flag, flag, get_flags, set_flags
from .random import get_rng_state, host_generator, rng_scope, seed, set_rng_state, split_key
from .selected_rows import SelectedRows
from .string_tensor import FasterTokenizer, StringTensor
