"""RNG state management.

Paddle parity: ``paddle.seed`` + per-op stateful RNG (reference:
python/paddle/framework/random.py, curand states in
paddle/fluid/platform/device_context.h). TPU-first design: JAX threaded PRNG
keys. Eager mode keeps a host-side counter folded into a root key; traced
(jit) code must use :class:`rng_scope` so the key is an explicit traced value
— never host state — keeping compiled steps pure and reproducible.
"""
from __future__ import annotations

import contextlib
import threading

import jax


class _RngState(threading.local):
    """The root key is created lazily: materializing it at import would
    initialize the XLA backend, which must not happen before a possible
    ``init_parallel_env``/``jax.distributed.initialize`` (multi-host)."""

    def __init__(self):
        self._root_key = None
        self.counter = 0
        self.seed_value = 0  # last paddle.seed value (host-side derivations)
        # stack of (key, [counter]) installed by rng_scope for traced code
        self.scopes = []

    @property
    def root_key(self):
        if self._root_key is None:
            self._root_key = jax.random.key(0)
        return self._root_key

    @root_key.setter
    def root_key(self, value):
        self._root_key = value


_STATE = _RngState()


def seed(value: int):
    """Reset the global RNG root key (paddle.seed parity)."""
    _STATE.root_key = jax.random.key(int(value))
    _STATE.counter = 0
    _STATE.seed_value = int(value)
    return value


def host_generator(tag: str = ""):
    """A ``numpy.random.Generator`` derived deterministically from the global
    seed (``paddle.seed``) and ``tag`` — host-side randomness (e.g. retry
    backoff jitter) that never touches the device PRNG, never initializes the
    XLA backend, and replays bitwise under chaos tests: same seed + same tag
    ⇒ same stream. Distinct tags (and distinct seeds) give independent
    streams, so N processes that fold their rank into ``tag`` de-correlate
    while each still replays deterministically."""
    import zlib

    import numpy as np

    base = zlib.crc32(f"{_STATE.seed_value}/{tag}".encode())
    return np.random.default_rng(base)


def get_rng_state():
    return (_STATE.root_key, _STATE.counter)


def set_rng_state(state):
    _STATE.root_key, _STATE.counter = state


def split_key():
    """Return a fresh PRNG key.

    Inside an :class:`rng_scope` (i.e. under jit tracing), keys derive from the
    scope's traced key; otherwise from the host-side eager state.
    """
    if _STATE.scopes:
        key, counter = _STATE.scopes[-1]
        counter[0] += 1
        return jax.random.fold_in(key, counter[0])
    _STATE.counter += 1
    return jax.random.fold_in(_STATE.root_key, _STATE.counter)


def key_tensor():
    """A fresh PRNG key as a Tensor, usable as a positional primitive input.

    Eager / rng_scope: wraps :func:`split_key`'s concrete key. Static capture:
    records a key-derivation op fed by the reserved ``__rng_key__`` scalar the
    Executor bumps every run — so dropout masks differ across runs, matching
    the reference's stateful curand semantics without host state in the graph.
    """
    from .core import _wrap_value
    from .static_trace import current_program, record_op

    prog = current_program()
    if prog is None:
        return _wrap_value(split_key())
    base = prog.feeds.get("__rng_key__")
    if base is None:
        base = prog.add_feed("__rng_key__", (), jax.numpy.uint32)
    salt = prog.version  # one distinct stream per recorded key op

    def derive(seed):
        return jax.random.fold_in(jax.random.key(seed), salt)

    return record_op(derive, [_wrap_value(base)], {}, "rng_key")


def train_flag_tensor():
    """Scalar 0/1 "is training" Tensor threaded into recorded rng ops.

    Static capture records it as the reserved ``__train_flag__`` feed so a
    captured program can be flipped to inference post-hoc — the analog of the
    reference's ``Program.clone(for_test=True)`` rewriting ops' ``is_test``
    attr (python/paddle/fluid/framework.py Program.clone). Eager code never
    reads it (Python ``training`` flags branch before recording).
    """
    from .core import _wrap_value
    from .static_trace import current_program

    prog = current_program()
    if prog is None:
        return _wrap_value(jax.numpy.uint32(1), stop_gradient=True)
    flag = prog.feeds.get("__train_flag__")
    if flag is None:
        flag = prog.add_feed("__train_flag__", (), jax.numpy.uint32)
    return _wrap_value(flag, stop_gradient=True)


@contextlib.contextmanager
def rng_scope(key):
    """Install ``key`` as the RNG source for code executed in this scope.

    Used by the functional/jit path to thread an explicit key through
    stateful-looking layers (Dropout etc.).
    """
    _STATE.scopes.append((key, [0]))
    try:
        yield
    finally:
        _STATE.scopes.pop()


def in_rng_scope() -> bool:
    return bool(_STATE.scopes)
