"""Tensor and eager op dispatch.

Paddle parity: the eager ``Tensor`` (reference:
paddle/fluid/pybind/eager_method.cc, python/paddle/fluid/dygraph/
varbase_patch_methods.py) and the dygraph tracer
(paddle/fluid/imperative/tracer.cc:175). TPU-first design: a Tensor is a thin
mutable handle over an immutable ``jax.Array`` living in HBM via PJRT; the
"tracer" is :func:`primitive`, which executes the forward with jax.numpy and
records the op's ``jax.vjp`` closure on the autograd tape. There is no op
registry, kernel factory, or device dispatch — XLA is the kernel library.
"""
from __future__ import annotations

import threading
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from . import autograd, static_trace
from .autograd import TapeNode, is_grad_enabled, no_grad
from .dtype import convert_dtype, get_default_dtype, to_jax_dtype


class _DeviceState(threading.local):
    device = None  # None = JAX default


_DEVICE = _DeviceState()


def set_device(device: str):
    """paddle.set_device parity. Accepts 'tpu', 'cpu', 'tpu:0' etc."""
    name = device.split(":")[0]
    if name in ("tpu", "gpu"):  # gpu accepted as an alias for accelerator
        name = None  # default platform (TPU when present)
    _DEVICE.device = name
    return device


def get_device() -> str:
    plat = jax.default_backend() if _DEVICE.device is None else _DEVICE.device
    return f"{plat}:0"


def is_compiled_with_tpu() -> bool:
    return any(d.platform != "cpu" for d in jax.devices())


class Tensor:
    """Eager tensor: mutable handle over a jax.Array.

    ``stop_gradient`` defaults True like paddle's ``Tensor`` created from
    data; parameters flip it to False. ``_node``/``_out_idx`` link into the
    autograd tape (None for leaves).
    """

    __slots__ = ("_value", "stop_gradient", "grad", "_node", "_out_idx", "name", "persistable", "trainable", "__weakref__", "__dict__")

    def __init__(self, data=None, dtype=None, place=None, stop_gradient=True):
        if data is None:
            data = jnp.zeros((), to_jax_dtype(dtype or get_default_dtype()))
        value = _to_array(data, dtype)
        self._init(value, stop_gradient=stop_gradient)

    def _init(self, value, stop_gradient=True, name=None):
        self._value = value
        self.stop_gradient = stop_gradient
        self.grad = None
        self._node = None
        self._out_idx = 0
        self.name = name or ""
        self.persistable = False
        self.trainable = not stop_gradient

    # -- metadata ---------------------------------------------------------
    @property
    def shape(self):
        return list(self._value.shape)

    @property
    def ndim(self):
        return self._value.ndim

    @property
    def size(self):
        return int(np.prod(self._value.shape)) if self._value.shape else 1

    @property
    def dtype(self):
        return convert_dtype(self._value.dtype)

    @property
    def place(self):
        devs = getattr(self._value, "devices", None)
        if callable(devs):
            try:
                return str(next(iter(devs())))
            except Exception:
                return "cpu"
        return "cpu"

    @property
    def is_leaf(self):
        return self._node is None

    # -- conversion -------------------------------------------------------
    def numpy(self):
        if static_trace.is_symbolic(self._value):
            raise RuntimeError(
                f"Variable {self.name or self._value.name!r} is symbolic (static "
                "graph mode): fetch it through Executor.run(fetch_list=[...]) "
                "instead of reading its value at build time")
        return np.asarray(self._value)

    def item(self):
        return self._value.item()

    def tolist(self):
        return np.asarray(self._value).tolist()

    def astype(self, dtype):
        from ..tensor.manipulation import cast

        return cast(self, dtype)

    def clone(self):
        from ..tensor.creation import clone

        return clone(self)

    def detach(self):
        t = Tensor.__new__(Tensor)
        t._init(self._value, stop_gradient=True, name=self.name)
        return t

    def numel(self):
        return self.size

    # -- autograd ---------------------------------------------------------
    def backward(self, grad_tensor=None, retain_graph=False):
        autograd.backward([self], [grad_tensor], retain_graph=retain_graph)

    def clear_grad(self):
        self.grad = None

    clear_gradient = clear_grad

    def register_hook(self, hook):
        """Register ``hook(grad) -> grad | None`` fired when this tensor's
        gradient is computed during backward (parity:
        varbase_patch_methods.py:202 / the reducer's accumulation hooks).
        The hook may return a new Tensor to replace the gradient. Returns a
        removable handle."""
        if self.stop_gradient:
            raise RuntimeError("cannot register a hook on a tensor with stop_gradient=True")
        hooks = self.__dict__.setdefault("_hooks", [])
        hooks.append(hook)

        class _Handle:
            def remove(_self):
                if hook in hooks:
                    hooks.remove(hook)

        return _Handle()

    # -- mutation (leaf-only, used by optimizers / load) ------------------
    def set_value(self, value):
        value = _to_array(value, self.dtype)
        if tuple(value.shape) != tuple(self._value.shape):
            raise ValueError(f"set_value shape mismatch: {value.shape} vs {self._value.shape}")
        self._value = value

    def copy_(self, other):
        self.set_value(other._value if isinstance(other, Tensor) else other)
        return self

    def _apply_update(self, new_value):
        """In-place parameter update (optimizer fast path, no checks)."""
        self._value = new_value

    def __repr__(self):
        sg = self.stop_gradient
        return f"Tensor(shape={self.shape}, dtype={self.dtype}, stop_gradient={sg},\n       {np.asarray(self._value)!r})"

    def __len__(self):
        if not self._value.shape:
            raise TypeError("len() of a 0-d tensor")
        return self._value.shape[0]

    def __bool__(self):
        return bool(self._value)

    def __int__(self):
        return int(self._value)

    def __float__(self):
        return float(self._value)

    def __format__(self, spec):
        return format(self.item() if self._value.ndim == 0 else np.asarray(self._value), spec)

    def __hash__(self):
        return id(self)

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    # arithmetic dunders are patched in paddle_tpu.tensor (monkey_patch_tensor)

    # jax pytree-friendly: expose the raw array
    def __jax_array__(self):
        return self._value


def _to_array(data, dtype=None):
    jdt = to_jax_dtype(dtype) if dtype is not None else None
    if isinstance(data, Tensor):
        arr = data._value
        return arr.astype(jdt) if jdt is not None and arr.dtype != jdt else arr
    if isinstance(data, (jnp.ndarray, jax.Array)):
        return data.astype(jdt) if jdt is not None and data.dtype != jdt else data
    arr = np.asarray(data)
    if jdt is None:
        # paddle semantics: python floats -> default dtype; ints -> int64
        if arr.dtype == np.float64 and not isinstance(data, np.ndarray):
            jdt = to_jax_dtype(get_default_dtype())
        elif arr.dtype == np.int64 and not isinstance(data, np.ndarray):
            jdt = to_jax_dtype("int64")
    return jnp.asarray(arr, dtype=jdt)


def _wrap_value(value, stop_gradient=True, node=None, out_idx=0):
    t = Tensor.__new__(Tensor)
    t._init(value, stop_gradient=stop_gradient)
    t._node = node
    t._out_idx = out_idx
    return t


def unwrap(x):
    """Tensor -> jax.Array; passthrough otherwise."""
    return x._value if isinstance(x, Tensor) else x


_FLOAT_KINDS = ("f", "V")  # V covers bfloat16 numpy view

# per-op wall-time stats collected when FLAGS_benchmark is on
_BENCH_STATS: dict = {}


def benchmark_stats():
    """{op_name: {"count": n, "total_s": t}} accumulated while
    FLAGS_benchmark is set (reference: the per-op timing the benchmark flag
    enables in the executor, profiler.cc)."""
    return dict(_BENCH_STATS)


def reset_benchmark_stats():
    _BENCH_STATS.clear()


def _check_outputs(name, outs):
    """FLAGS_check_nan_inf hook (reference nan_inf_utils_detail.cc:316 runs
    after every op); host-syncs each eager output and raises on nan/inf."""
    for v in outs:
        if isinstance(v, jax.core.Tracer) or not _is_float_array(v):
            continue
        arr = np.asarray(v)
        if not np.isfinite(arr.astype(np.float32)).all():
            raise FloatingPointError(
                f"Operator {name or 'op'} output contains Inf/Nan "
                f"(shape {arr.shape}, dtype {v.dtype}) — FLAGS_check_nan_inf is set")

# installed by paddle_tpu.amp at import (avoids a circular import); called as
# _amp_hook(op_name, vals) -> vals when an auto_cast scope is active
_amp_hook = None


def _is_float_array(v) -> bool:
    if not hasattr(v, "dtype"):
        return False
    try:
        dt = np.dtype(v.dtype)
    except TypeError:  # extended dtypes (PRNG key arrays) are never float
        return False
    return dt.kind == "f" or v.dtype == jnp.bfloat16


def primitive(fn: Callable, *args, _name: str = "", **kwargs):
    """Execute ``fn(*arrays, **kwargs)`` and record it on the tape.

    ``fn`` must be a pure function of its positional array arguments
    (keyword args are static). Tensor positional args that require grad are
    differentiated through via ``jax.vjp``; everything else is closed over.
    Returns Tensor or tuple of Tensors mirroring fn's output.
    """
    if static_trace.current_program() is not None:
        # static-graph capture (program_guard/enable_static): record the call
        # instead of executing — shapes via jax.eval_shape, execution deferred
        # to Executor.run where the whole program compiles as one jit
        return static_trace.record_op(fn, args, kwargs, _name)

    vals = [unwrap(a) for a in args]
    if _amp_hook is not None:
        vals = _amp_hook(_name, vals)
    diff_idx = []
    if is_grad_enabled():
        for i, a in enumerate(args):
            if isinstance(a, Tensor) and not a.stop_gradient and _is_float_array(a._value):
                diff_idx.append(i)

    from .flags import _REGISTRY as _FLAGS

    check = _FLAGS.get("FLAGS_check_nan_inf", False)
    bench = _FLAGS.get("FLAGS_benchmark", False)

    if not diff_idx:
        if bench:
            import time as _time

            t0 = _time.perf_counter()
            out = fn(*vals, **kwargs)
            jax.block_until_ready(out)
            st = _BENCH_STATS.setdefault(_name or getattr(fn, "__name__", "op"), {"count": 0, "total_s": 0.0})
            st["count"] += 1
            st["total_s"] += _time.perf_counter() - t0
        else:
            out = fn(*vals, **kwargs)
        if check:
            _check_outputs(_name, out if isinstance(out, (tuple, list)) else (out,))
        if isinstance(out, (tuple, list)):
            return tuple(_wrap_value(v) for v in out)
        return _wrap_value(out)

    def closed(*diff_vals):
        v = list(vals)
        for i, dv in zip(diff_idx, diff_vals):
            v[i] = dv
        return fn(*v, **kwargs)

    if bench:
        import time as _time

        t0 = _time.perf_counter()
        out, vjp_fn = jax.vjp(closed, *[vals[i] for i in diff_idx])
        jax.block_until_ready(out)
        st = _BENCH_STATS.setdefault(_name or getattr(fn, "__name__", "op"), {"count": 0, "total_s": 0.0})
        st["count"] += 1
        st["total_s"] += _time.perf_counter() - t0
    else:
        out, vjp_fn = jax.vjp(closed, *[vals[i] for i in diff_idx])
    multi = isinstance(out, (tuple, list))
    outs = tuple(out) if multi else (out,)
    if check:
        _check_outputs(_name, outs)
    # only float outputs participate in grad flow; but vjp structure covers all
    out_shapes = [(o.shape, o.dtype) for o in outs]
    node = TapeNode(vjp_fn, [args[i] for i in diff_idx], len(outs), out_shapes, name=_name or getattr(fn, "__name__", "op"))
    wrapped = tuple(_wrap_value(v, stop_gradient=not _is_float_array(v), node=node if _is_float_array(v) else None, out_idx=i) for i, v in enumerate(outs))
    import weakref

    node.out_refs = [weakref.ref(t) for t in wrapped]
    return wrapped if multi else wrapped[0]
