"""StringTensor + FasterTokenizer: host-side text-in-the-graph parity.

Parity anchors: paddle/phi/core/string_tensor.h (pstring DenseTensor sibling)
and operators/string/faster_tokenizer_op.cc (BERT-style tokenization as an
in-graph op so a served model accepts raw strings).

TPU framing: strings never touch the accelerator — the reference keeps them
on CPU too. StringTensor is a shaped host container; FasterTokenizer is a
host-side stage producing the int32 (input_ids, token_type_ids) arrays the
device graph consumes. It slots directly into a FleetExecutor serving chain
ahead of a Predictor stage.
"""
from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["StringTensor", "FasterTokenizer"]


class StringTensor:
    """A shaped array of strings (reference phi::StringTensor)."""

    def __init__(self, data, shape: Optional[Sequence[int]] = None):
        arr = np.asarray(data, dtype=object)
        if shape is not None:
            arr = arr.reshape(tuple(shape))
        self._arr = arr

    @property
    def shape(self) -> Tuple[int, ...]:
        return tuple(self._arr.shape)

    @property
    def ndim(self) -> int:
        return self._arr.ndim

    def numel(self) -> int:
        return int(self._arr.size)

    def reshape(self, shape) -> "StringTensor":
        return StringTensor(self._arr.reshape(tuple(shape)))

    def __getitem__(self, idx):
        out = self._arr[idx]
        return StringTensor(out) if isinstance(out, np.ndarray) else out

    def tolist(self) -> List:
        return self._arr.tolist()

    def __iter__(self):
        return iter(self._arr)

    def __len__(self):
        return len(self._arr)

    def __repr__(self):
        return f"StringTensor(shape={self.shape}, data={self._arr.tolist()!r})"


def _basic_tokenize(text: str, do_lower_case: bool) -> List[str]:
    """Whitespace + punctuation split (reference BasicTokenizer in
    faster_tokenizer_op.h, minus CJK special-casing)."""
    if do_lower_case:
        text = text.lower()
    out: List[str] = []
    buf = []
    for ch in text:
        if ch.isspace():
            if buf:
                out.append("".join(buf))
                buf = []
        elif not ch.isalnum():
            if buf:
                out.append("".join(buf))
                buf = []
            out.append(ch)
        else:
            buf.append(ch)
    if buf:
        out.append("".join(buf))
    return out


class FasterTokenizer:
    """BERT WordPiece tokenizer as a host op (reference
    faster_tokenizer_op.cc): greedy longest-match-first subwords with ##
    continuation, [CLS]/[SEP] framing, pair encoding with token_type_ids,
    padding + truncation to fixed shapes for the device graph."""

    def __init__(self, vocab: Dict[str, int], do_lower_case: bool = True,
                 unk_token: str = "[UNK]", cls_token: str = "[CLS]",
                 sep_token: str = "[SEP]", pad_token: str = "[PAD]",
                 max_input_chars_per_word: int = 100):
        self.vocab = dict(vocab)
        self.do_lower_case = do_lower_case
        self.unk, self.cls, self.sep, self.pad = unk_token, cls_token, sep_token, pad_token
        for tok in (unk_token, cls_token, sep_token, pad_token):
            if tok not in self.vocab:
                raise ValueError(f"special token {tok!r} missing from vocab")
        self.max_chars = max_input_chars_per_word

    def _wordpiece(self, word: str) -> List[int]:
        if len(word) > self.max_chars:
            return [self.vocab[self.unk]]
        ids, start = [], 0
        while start < len(word):
            end = len(word)
            cur = None
            while start < end:
                piece = word[start:end]
                if start > 0:
                    piece = "##" + piece
                if piece in self.vocab:
                    cur = self.vocab[piece]
                    break
                end -= 1
            if cur is None:
                return [self.vocab[self.unk]]
            ids.append(cur)
            start = end
        return ids

    def _encode_one(self, text: str) -> List[int]:
        ids: List[int] = []
        for w in _basic_tokenize(text, self.do_lower_case):
            ids.extend(self._wordpiece(w))
        return ids

    def __call__(self, text, text_pair=None, max_seq_len: int = 128,
                 pad_to_max_seq_len: bool = True):
        """texts: StringTensor | str | list[str] → (input_ids, token_type_ids)
        int32 [batch, max_seq_len] numpy arrays (the device-graph inputs)."""
        if isinstance(text, str):
            text = [text]
        if isinstance(text_pair, str):
            text_pair = [text_pair]
        texts = [str(s) for s in text]
        pairs = None
        if text_pair is not None:
            pairs = [str(s) for s in text_pair]
            if len(pairs) != len(texts):
                raise ValueError("text and text_pair batch sizes differ")
        n_special = 3 if pairs is not None else 2  # [CLS] a [SEP] (b [SEP])
        if max_seq_len < n_special + (2 if pairs is not None else 1):
            raise ValueError(f"max_seq_len={max_seq_len} leaves no room for "
                             f"content beside the {n_special} special tokens")
        cls_id, sep_id, pad_id = self.vocab[self.cls], self.vocab[self.sep], self.vocab[self.pad]
        rows, segs = [], []
        for i, t in enumerate(texts):
            a = self._encode_one(t)
            b = self._encode_one(pairs[i]) if pairs is not None else None
            # truncate longest-first to fit specials (reference truncation);
            # an empty pair text keeps its [SEP]/segment framing so batch
            # rows stay consistently shaped
            budget = max_seq_len - n_special
            while len(a) + len(b or []) > budget:
                tgt = a if len(a) >= len(b or []) else b
                tgt.pop()
            ids = [cls_id] + a + [sep_id] + (b + [sep_id] if b is not None else [])
            seg = [0] * (len(a) + 2) + ([1] * (len(b) + 1) if b is not None else [])
            if pad_to_max_seq_len:
                ids += [pad_id] * (max_seq_len - len(ids))
                seg += [0] * (max_seq_len - len(seg))
            rows.append(ids)
            segs.append(seg)
        width = max_seq_len if pad_to_max_seq_len else max((len(r) for r in rows), default=0)
        rows = [r + [pad_id] * (width - len(r)) for r in rows]
        segs = [s + [0] * (width - len(s)) for s in segs]
        out_ids = np.asarray(rows, np.int32).reshape(len(rows), width)
        out_segs = np.asarray(segs, np.int32).reshape(len(rows), width)
        return out_ids, out_segs
