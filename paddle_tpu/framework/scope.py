"""Scope/Variable tree (parity: paddle/fluid/framework/scope.h:78 +
pybind _Scope, python/paddle/static global_scope).

The reference executor resolves every op operand by name through a
hierarchical Scope; under XLA the compiled program owns its buffers, so the
Scope here is the *user-facing* name registry: Executor.run publishes
parameter and fetch values into the global scope after each run, and
``scope.find_var(name).get_tensor()`` serves the classic inspection /
manual-checkpoint workflows.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

__all__ = ["Scope", "Variable", "global_scope", "scope_guard"]


class Variable:
    """Named slot holding one tensor value (reference framework::Variable)."""

    def __init__(self, name: str):
        self.name = name
        self._value = None

    def get_tensor(self):
        return self

    def set(self, value, place=None):
        import jax.numpy as jnp

        self._value = jnp.asarray(np.asarray(value))

    def __array__(self, dtype=None):
        if self._value is None:
            raise ValueError(f"Variable {self.name!r} holds no value yet")
        arr = np.asarray(self._value)
        return arr.astype(dtype) if dtype else arr

    def shape(self):
        return tuple(self._value.shape) if self._value is not None else None

    def numpy(self):
        return np.asarray(self)


class Scope:
    """Hierarchical name → Variable map (scope.h semantics: ``var`` creates
    locally, ``find_var`` searches up the parent chain, ``new_scope`` makes
    a kid, ``drop_kids`` releases the subtree)."""

    def __init__(self, parent: Optional["Scope"] = None):
        self._vars: Dict[str, Variable] = {}
        self._parent = parent
        self._kids: List["Scope"] = []

    def var(self, name: str) -> Variable:
        if name not in self._vars:
            self._vars[name] = Variable(name)
        return self._vars[name]

    def find_var(self, name: str) -> Optional[Variable]:
        s: Optional[Scope] = self
        while s is not None:
            if name in s._vars:
                return s._vars[name]
            s = s._parent
        return None

    def erase(self, names) -> None:
        for n in names if isinstance(names, (list, tuple)) else [names]:
            self._vars.pop(n, None)

    def new_scope(self) -> "Scope":
        kid = Scope(self)
        self._kids.append(kid)
        return kid

    def drop_kids(self) -> None:
        self._kids.clear()

    def local_var_names(self) -> List[str]:
        return sorted(self._vars)


_GLOBAL = Scope()
_ACTIVE = [_GLOBAL]


def global_scope() -> Scope:
    return _ACTIVE[-1]


def scope_guard(scope: Scope):
    """Context manager swapping the active global scope (reference
    paddle.static.scope_guard)."""
    import contextlib

    @contextlib.contextmanager
    def ctx():
        _ACTIVE.append(scope)
        try:
            yield
        finally:
            _ACTIVE.pop()

    return ctx()
