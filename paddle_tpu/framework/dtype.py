"""Dtype registry.

Paddle parity: dtype strings/enum of ``VarDesc.VarType`` (reference:
paddle/fluid/framework/framework.proto:91) mapped onto JAX dtypes. On TPU the
native matmul dtype is bfloat16; float64 is emulated and discouraged.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# canonical name -> jnp dtype
_DTYPES = {
    "bool": jnp.bool_,
    "uint8": jnp.uint8,
    "int8": jnp.int8,
    "int16": jnp.int16,
    "int32": jnp.int32,
    "int64": jnp.int64,
    "float16": jnp.float16,
    "bfloat16": jnp.bfloat16,
    "float32": jnp.float32,
    "float64": jnp.float64,
    "complex64": jnp.complex64,
    "complex128": jnp.complex128,
}

_ALIASES = {
    "fp16": "float16",
    "bf16": "bfloat16",
    "fp32": "float32",
    "fp64": "float64",
    "half": "float16",
    "float": "float32",
    "double": "float64",
    "int": "int32",
    "long": "int64",
}

_default_dtype = "float32"


def convert_dtype(dtype):
    """Normalize a dtype spec (str / np / jnp dtype) to its canonical name."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        name = _ALIASES.get(dtype, dtype)
        if name in _DTYPES:
            return name
        raise ValueError(f"unsupported dtype {dtype!r}")
    # jnp/np dtype objects or scalar types
    name = np.dtype(dtype).name if not hasattr(dtype, "name") else dtype.name
    name = _ALIASES.get(name, name)
    if name in _DTYPES:
        return name
    raise ValueError(f"unsupported dtype {dtype!r}")


_X64_DOWNCAST = {"int64": "int32", "uint64": "uint32", "float64": "float32", "complex128": "complex64"}


def to_jax_dtype(dtype):
    if dtype is None:
        return None
    name = convert_dtype(dtype)
    import jax

    if not jax.config.jax_enable_x64 and name in _X64_DOWNCAST:
        # Paddle defaults indices to int64; on TPU (x64 off) we canonically run
        # int32/float32 — the paddle-visible dtype name is preserved by callers.
        name = _X64_DOWNCAST[name]
    return _DTYPES[name]


def get_default_dtype():
    return _default_dtype


def set_default_dtype(dtype):
    global _default_dtype
    name = convert_dtype(dtype)
    if name not in ("float16", "bfloat16", "float32", "float64"):
        raise TypeError(f"set_default_dtype only accepts floating dtypes, got {dtype!r}")
    _default_dtype = name


def is_floating(dtype) -> bool:
    return jnp.issubdtype(np.dtype(to_jax_dtype(dtype)), np.floating) or convert_dtype(dtype) in (
        "bfloat16",
        "float16",
    )
