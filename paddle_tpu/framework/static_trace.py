"""Static-graph capture: Program IR recorded at the ``primitive`` chokepoint.

Paddle parity: the Program IR + static frontend (reference
paddle/fluid/framework/framework.proto:236 ProgramDesc,
python/paddle/fluid/framework.py:4795 Program / :1222 Variable / :2549
Operator). TPU-first design: there is no protobuf op schema — every tensor op
already funnels through :func:`paddle_tpu.framework.core.primitive` with a
pure jax function, so a "Program" is the recorded list of those calls, shape
inference is ``jax.eval_shape`` (the InferMeta analog), and execution compiles
the whole op list into ONE XLA computation via ``jax.jit`` (the
InterpreterCore/new_executor analog — scheduling, fusion, GC and stream
management all belong to XLA).
"""
from __future__ import annotations

import itertools
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np


class SymbolicValue:
    """Shape/dtype-only placeholder flowing through a Program (VarDesc analog)."""

    __slots__ = ("shape", "dtype", "name")

    def __init__(self, shape, dtype, name):
        self.shape = tuple(shape)
        self.dtype = dtype
        self.name = name

    @property
    def ndim(self):
        return len(self.shape)

    @property
    def size(self):
        return int(np.prod(self.shape)) if self.shape else 1

    def __repr__(self):
        return f"SymbolicValue(name={self.name!r}, shape={self.shape}, dtype={self.dtype})"


def is_symbolic(v) -> bool:
    return isinstance(v, SymbolicValue)


def guard_inplace(op_name: str, *tensors) -> None:
    """Raise a clear error for in-place mutation of symbolic values — static
    programs are pure dataflow; in-place ops have no recordable meaning."""
    if current_program() is None:
        return
    for t in tensors:
        if t is not None and is_symbolic(getattr(t, "_value", None)):
            raise RuntimeError(
                f"{op_name} mutates a symbolic Variable in static-graph mode; "
                "use the out-of-place form (e.g. y = x + 1) instead")


# stand-in extents for -1/None dims during build-time shape inference.
# eval_shape runs twice with two distinct probe extents; output dims that
# differ between probes are recorded as -1 (the reference propagates -1
# through InferMeta the same way — framework.proto VarDesc dims)
_DYN_PLACEHOLDER = 4
_DYN_PLACEHOLDER_B = 8


class Op:
    """One recorded primitive call (OpDesc analog: fn + attrs + var refs)."""

    __slots__ = ("fn", "kwargs", "inputs", "outputs", "name", "dyn_fallback")

    def __init__(self, fn, kwargs, inputs, outputs, name):
        self.fn = fn            # pure jax function of positional arrays
        self.kwargs = kwargs    # static attributes
        self.inputs = inputs    # list of ('sym', SymbolicValue)|('tensor', Tensor)|('const', value)
        self.outputs = outputs  # list of SymbolicValue
        self.name = name
        # set by record_op when the second dynamic-dim shape probe failed:
        # "TypeName: message" of the rejection — the analysis pass PTA005
        # surfaces it (the output shape may bake the probe extent in)
        self.dyn_fallback = None


class Program:
    """Recorded op list + feed registry (ProgramDesc analog, single block —
    control flow is jax.lax inside an op's fn, not nested blocks)."""

    _ids = itertools.count()

    def __init__(self):
        self.id = next(Program._ids)
        self.ops: List[Op] = []
        self.feeds: Dict[str, SymbolicValue] = {}
        self._name_counter = itertools.count()
        # set by Optimizer.minimize in static mode:
        self.optimizer = None
        self.loss_var: Optional[SymbolicValue] = None
        self.grad_vars: Dict[str, SymbolicValue] = {}  # param name -> grad var
        # deferred stateful-buffer updates (BatchNorm running stats): the
        # Executor commits env[sym.name] into the Tensor after each run
        self.buffer_writes: List[Tuple[Any, SymbolicValue]] = []
        self.random_seed = 0

    # ---------------------------------------------------------------- build
    def fresh_name(self, hint: str) -> str:
        return f"{hint}_{self.id}_{next(self._name_counter)}"

    def add_feed(self, name: str, shape, dtype) -> SymbolicValue:
        if name in self.feeds:
            raise ValueError(f"duplicate feed name {name!r}")
        sv = SymbolicValue(shape, dtype, name)
        self.feeds[name] = sv
        return sv

    @property
    def version(self) -> int:
        return len(self.ops)

    def global_block(self):  # reference Program.global_block() parity
        return self

    def clone(self, for_test: bool = False) -> "Program":
        """Reference Program.clone (framework.py): ``for_test=True`` flips
        recorded rng ops (dropout etc.) to inference via the reserved
        ``__train_flag__`` feed instead of rewriting op attrs. Ops/feeds are
        shared (the recorded list is append-only per version)."""
        import copy

        c = copy.copy(self)
        c.id = next(Program._ids)
        c.ops = list(self.ops)
        c.feeds = dict(self.feeds)
        c.grad_vars = dict(self.grad_vars)
        c.buffer_writes = list(self.buffer_writes)
        c.for_test = for_test
        if for_test:  # reference clone(for_test=True) prunes the backward
            c.optimizer = None
            c.loss_var = None
            c.grad_vars = {}
            # eval-mode runs must not commit BatchNorm running-stat updates
            c.buffer_writes = []
        return c

    def analyze(self, fetch_list=None, **kwargs):
        """Run the registered static-analysis passes over this program
        (paddle_tpu.analysis). ``fetch_list`` (Tensors, SymbolicValues or
        names) anchors liveness for the dead-op pass; without it every sink
        op counts as a result. Returns a list of ``Diagnostic``s — the
        Executor runs this automatically under ``FLAGS_static_check``."""
        from ..analysis import analyze_program

        return analyze_program(self, fetch=fetch_list, **kwargs)

    def all_parameters(self):
        """Trainable concrete Tensors referenced by recorded ops."""
        seen, out = set(), []
        for op in self.ops:
            for kind, ref in op.inputs:
                if kind == "tensor" and not ref.stop_gradient and id(ref) not in seen:
                    seen.add(id(ref))
                    out.append(ref)
        return out

    def tensor_refs(self):
        """All concrete Tensors referenced (params + buffers + constants),
        in first-use order."""
        seen, out = set(), []
        for op in self.ops:
            for kind, ref in op.inputs:
                if kind == "tensor" and id(ref) not in seen:
                    seen.add(id(ref))
                    out.append(ref)
        return out

    # ------------------------------------------------------------ interpret
    def interpret(self, env: Dict[str, Any], tensor_vals: Dict[int, Any]) -> Dict[str, Any]:
        """Evaluate the op list. ``env``: symbolic name -> array (feeds);
        ``tensor_vals``: id(Tensor) -> array for referenced concrete tensors.
        Mutates and returns ``env`` including all op outputs."""
        for op in self.ops:
            vals = []
            for kind, ref in op.inputs:
                if kind == "sym":
                    if ref.name not in env:
                        raise KeyError(
                            f"op {op.name!r} reads {ref.name!r} which is neither "
                            f"a feed of this run nor produced by an earlier op")
                    vals.append(env[ref.name])
                elif kind == "tensor":
                    vals.append(tensor_vals[id(ref)])
                else:
                    vals.append(ref)
            out = op.fn(*vals, **op.kwargs)
            outs = out if isinstance(out, (tuple, list)) else (out,)
            if len(outs) != len(op.outputs):
                raise RuntimeError(
                    f"op {op.name!r} returned {len(outs)} output(s) at run "
                    f"time but {len(op.outputs)} were recorded at trace time; "
                    "an op fn's output structure must not depend on run-time "
                    "state")
            for sv, v in zip(op.outputs, outs):
                env[sv.name] = v
        return env

    def __repr__(self):
        lines = [f"Program(id={self.id}, feeds={list(self.feeds)}, ops={len(self.ops)})"]
        for op in self.ops:
            ins = ", ".join(
                ref.name if kind == "sym" else (getattr(ref, "name", "") or f"tensor@{id(ref):x}") if kind == "tensor" else repr(ref)
                for kind, ref in op.inputs)
            outs = ", ".join(o.name for o in op.outputs)
            lines.append(f"  {outs} = {op.name}({ins})")
        return "\n".join(lines)


class _TraceState(threading.local):
    stack: List[Program]

    def __init__(self):
        self.stack = []


_STATE = _TraceState()


def current_program() -> Optional[Program]:
    return _STATE.stack[-1] if _STATE.stack else None


def push_program(p: Program) -> None:
    _STATE.stack.append(p)


def pop_program() -> Program:
    return _STATE.stack.pop()


def record_op(fn: Callable, args: Sequence[Any], kwargs: Dict[str, Any], name: str):
    """Record one primitive call into the current program; returns Variables
    (Tensors wrapping SymbolicValue) mirroring fn's output structure."""
    from .core import Tensor, _wrap_value

    prog = current_program()
    assert prog is not None

    # dynamic dims (-1 / None in static.data) get a placeholder extent for
    # shape inference only; Executor.run re-traces with the fed shapes, so a
    # new batch size is just a fresh jit specialization (XLA is static-shape)
    inputs: List[Tuple[str, Any]] = []
    any_diff = False
    has_dyn = False
    for a in args:
        if isinstance(a, Tensor):
            v = a._value
            if is_symbolic(v):
                inputs.append(("sym", v))
                has_dyn = has_dyn or any(d < 0 for d in v.shape)
            else:
                inputs.append(("tensor", a))
            if not a.stop_gradient:
                any_diff = True
        elif is_symbolic(a):
            inputs.append(("sym", a))
            has_dyn = has_dyn or any(d < 0 for d in a.shape)
        else:
            inputs.append(("const", a))

    def _specs_with(ph):
        specs = []
        for kind, ref in inputs:
            if kind == "sym":
                specs.append(jax.ShapeDtypeStruct(
                    tuple(ph if d < 0 else d for d in ref.shape), ref.dtype))
            elif kind == "tensor":
                v = ref._value
                specs.append(jax.ShapeDtypeStruct(v.shape, v.dtype))
            else:
                specs.append(ref)
        return specs

    out_spec = jax.eval_shape(lambda *xs: fn(*xs, **kwargs), *_specs_with(_DYN_PLACEHOLDER))
    multi = isinstance(out_spec, (tuple, list))
    out_specs = tuple(out_spec) if multi else (out_spec,)
    out_shapes = [tuple(s.shape) for s in out_specs]
    dyn_fallback = None
    if has_dyn:
        # second probe: output dims that track an input's dynamic dim change
        # with it — record those as -1 instead of baking the placeholder in
        try:
            spec_b = jax.eval_shape(lambda *xs: fn(*xs, **kwargs), *_specs_with(_DYN_PLACEHOLDER_B))
            specs_b = tuple(spec_b) if isinstance(spec_b, (tuple, list)) else (spec_b,)
            out_shapes = [
                tuple(-1 if da != db else da for da, db in zip(sa.shape, sb.shape))
                for sa, sb in zip(out_specs, specs_b)
            ]
        except (TypeError, ValueError, IndexError, ArithmeticError) as e:
            # shape fn rejects the probe extent: keep the static guess, but
            # mark the op — the first probe's extent may be baked into its
            # output shape, which the PTA005 analysis pass surfaces
            dyn_fallback = f"{type(e).__name__}: {e}"
    outputs = [SymbolicValue(shp, s.dtype, prog.fresh_name(name or "op"))
               for shp, s in zip(out_shapes, out_specs)]
    rec = Op(fn, dict(kwargs), inputs, outputs, name or getattr(fn, "__name__", "op"))
    rec.dyn_fallback = dyn_fallback
    prog.ops.append(rec)

    wrapped = tuple(_wrap_value(sv, stop_gradient=not any_diff) for sv in outputs)
    return wrapped if multi else wrapped[0]
