"""ctypes bindings to the paddle_tpu native runtime (csrc/).

The reference framework's runtime around the compute path is C++ (allocator
``memory/allocation/``, TCPStore ``distributed/store/tcp_store.cc``, profiler
``platform/profiler/``, data feed ``framework/data_feed.cc``); on TPU the
device side of all of that is PJRT/XLA, and the host side lives in ``csrc/``
as one C-ABI shared library built here on first use with g++ (the image has
no pybind11; ctypes keeps the binding dependency-free).

Build artifacts are cached under ``build/`` keyed by a hash of the sources, so
the first import after a source change recompiles once and every later import
dlopens the cached .so.
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from pathlib import Path
from typing import Optional

_REPO_ROOT = Path(__file__).resolve().parent.parent.parent
_CSRC = _REPO_ROOT / "csrc"
_BUILD_DIR = _REPO_ROOT / "build"

_lib: Optional[ctypes.CDLL] = None
_lib_err: Optional[str] = None
_lock = threading.Lock()


def _source_hash() -> str:
    h = hashlib.sha256()
    for src in sorted(_CSRC.glob("*")):
        h.update(src.name.encode())
        h.update(src.read_bytes())
    return h.hexdigest()[:16]


def _compile() -> Path:
    _BUILD_DIR.mkdir(exist_ok=True)
    so = _BUILD_DIR / f"libpaddle_tpu_native-{_source_hash()}.so"
    if so.exists():
        return so
    srcs = sorted(str(p) for p in _CSRC.glob("*.cc"))
    tmp = so.with_suffix(f".so.tmp.{os.getpid()}")  # per-process: concurrent
    # builders each link their own file; os.replace publishes atomically
    cmd = ["g++", "-O2", "-fPIC", "-shared", "-std=c++17", "-pthread",
           *srcs, "-o", str(tmp)]
    subprocess.run(cmd, check=True, capture_output=True, text=True)
    os.replace(tmp, so)
    return so


def _declare(lib: ctypes.CDLL) -> None:
    c = ctypes
    sigs = {
        "pt_buffer_free": (None, [c.c_void_p]),
        # channel
        "pt_channel_create": (c.c_void_p, [c.c_uint64]),
        "pt_channel_put": (c.c_int, [c.c_void_p, c.c_void_p, c.c_uint64]),
        "pt_channel_get": (c.c_int64, [c.c_void_p, c.POINTER(c.c_void_p)]),
        "pt_channel_close": (None, [c.c_void_p]),
        "pt_channel_size": (c.c_uint64, [c.c_void_p]),
        "pt_channel_destroy": (None, [c.c_void_p]),
        # tracer
        "pt_trace_enable": (None, [c.c_int]),
        "pt_trace_enabled": (c.c_int, []),
        "pt_trace_begin": (None, [c.c_char_p, c.c_char_p]),
        "pt_trace_end": (None, []),
        "pt_trace_instant": (None, [c.c_char_p, c.c_char_p]),
        "pt_trace_counter": (None, [c.c_char_p, c.c_double]),
        "pt_trace_event_count": (c.c_uint64, []),
        "pt_trace_clear": (None, []),
        "pt_trace_export": (c.c_int, [c.c_char_p, c.c_char_p]),
        # stats
        "pt_stat_add": (None, [c.c_char_p, c.c_int64]),
        "pt_stat_set": (None, [c.c_char_p, c.c_int64]),
        "pt_stat_get": (c.c_int64, [c.c_char_p]),
        "pt_stat_peak": (c.c_int64, [c.c_char_p]),
        "pt_stat_reset": (None, [c.c_char_p]),
        "pt_stat_clear": (None, []),
        "pt_stat_names": (c.c_int64, [c.c_char_p, c.c_int64]),
        # arena
        "pt_arena_create": (c.c_void_p, [c.c_uint64]),
        "pt_arena_alloc": (c.c_void_p, [c.c_void_p, c.c_uint64]),
        "pt_arena_free": (c.c_int, [c.c_void_p, c.c_void_p]),
        "pt_arena_allocated": (c.c_uint64, [c.c_void_p]),
        "pt_arena_reserved": (c.c_uint64, [c.c_void_p]),
        "pt_arena_destroy": (None, [c.c_void_p]),
        # store
        "pt_store_server_start": (c.c_void_p, [c.c_int]),
        "pt_store_server_port": (c.c_int, [c.c_void_p]),
        "pt_store_server_stop": (None, [c.c_void_p]),
        "pt_store_client_create": (c.c_void_p, [c.c_char_p, c.c_int, c.c_int]),
        "pt_store_client_destroy": (None, [c.c_void_p]),
        "pt_store_set": (c.c_int, [c.c_void_p, c.c_char_p, c.c_void_p, c.c_uint64]),
        "pt_store_get": (c.c_int64, [c.c_void_p, c.c_char_p, c.POINTER(c.c_void_p), c.c_int]),
        "pt_store_add": (c.c_int64, [c.c_void_p, c.c_char_p, c.c_int64]),
        "pt_store_del": (c.c_int, [c.c_void_p, c.c_char_p]),
        "pt_store_num_keys": (c.c_int64, [c.c_void_p]),
        # feed
        "pt_feed_create": (c.c_void_p, [c.c_char_p, c.c_uint64, c.c_uint64, c.c_int,
                                        c.c_uint64, c.c_int, c.c_uint64, c.c_int]),
        "pt_feed_start_epoch": (None, [c.c_void_p]),
        "pt_feed_next": (c.c_uint64, [c.c_void_p, c.POINTER(c.c_void_p)]),
        "pt_feed_destroy": (None, [c.c_void_p]),
    }
    for name, (restype, argtypes) in sigs.items():
        fn = getattr(lib, name)
        fn.restype = restype
        fn.argtypes = argtypes


def load_native() -> ctypes.CDLL:
    """Build (cached) and dlopen the native library. Raises on failure."""
    global _lib, _lib_err
    with _lock:
        if _lib is not None:
            return _lib
        if _lib_err is not None:
            raise RuntimeError(f"native library unavailable: {_lib_err}")
        try:
            so = _compile()
            lib = ctypes.CDLL(str(so))
            _declare(lib)
            _lib = lib
            return lib
        except subprocess.CalledProcessError as e:  # pragma: no cover
            _lib_err = f"compile failed: {e.stderr[-2000:] if e.stderr else e}"
            raise RuntimeError(f"native library unavailable: {_lib_err}") from e
        except OSError as e:  # pragma: no cover
            _lib_err = str(e)
            raise RuntimeError(f"native library unavailable: {_lib_err}") from e


def native_available() -> bool:
    try:
        load_native()
        return True
    except RuntimeError:  # pragma: no cover
        return False


def _take_buffer(lib: ctypes.CDLL, ptr: ctypes.c_void_p, length: int) -> bytes:
    data = ctypes.string_at(ptr, length)
    lib.pt_buffer_free(ptr)
    return data


class Channel:
    """Bounded blocking byte channel (csrc/channel.h)."""

    def __init__(self, capacity: int = 8):
        self._lib = load_native()
        self._h = self._lib.pt_channel_create(capacity)

    def put(self, data: bytes) -> bool:
        return self._lib.pt_channel_put(self._h, data, len(data)) == 0

    def get(self) -> Optional[bytes]:
        out = ctypes.c_void_p()
        n = self._lib.pt_channel_get(self._h, ctypes.byref(out))
        if n < 0:
            return None
        return _take_buffer(self._lib, out, n)

    def close(self):
        self._lib.pt_channel_close(self._h)

    def __len__(self):
        return self._lib.pt_channel_size(self._h)

    def __del__(self):
        if getattr(self, "_h", None):
            self._lib.pt_channel_destroy(self._h)
            self._h = None


class HostArena:
    """Auto-growth best-fit host arena (csrc/arena.cc)."""

    def __init__(self, chunk_size: int = 8 << 20):
        self._lib = load_native()
        self._h = self._lib.pt_arena_create(chunk_size)

    def alloc(self, size: int) -> int:
        p = self._lib.pt_arena_alloc(self._h, size)
        if not p:
            raise MemoryError(f"host arena alloc of {size} bytes failed")
        return p

    def free(self, ptr: int) -> None:
        if self._lib.pt_arena_free(self._h, ptr) != 0:
            raise ValueError("pointer not owned by this arena")

    @property
    def allocated(self) -> int:
        return self._lib.pt_arena_allocated(self._h)

    @property
    def reserved(self) -> int:
        return self._lib.pt_arena_reserved(self._h)

    def __del__(self):
        if getattr(self, "_h", None):
            self._lib.pt_arena_destroy(self._h)
            self._h = None


# ----------------------------------------------------------------- stats API
def stat_add(name: str, delta: int) -> None:
    load_native().pt_stat_add(name.encode(), delta)


def stat_set(name: str, value: int) -> None:
    load_native().pt_stat_set(name.encode(), value)


def stat_get(name: str) -> int:
    return load_native().pt_stat_get(name.encode())


def stat_peak(name: str) -> int:
    return load_native().pt_stat_peak(name.encode())


def stat_names() -> list[str]:
    lib = load_native()
    need = lib.pt_stat_names(None, 0)
    buf = ctypes.create_string_buffer(need)
    lib.pt_stat_names(buf, need)
    s = buf.value.decode()
    return s.split("\n") if s else []
