"""CLI: ``python -m paddle_tpu.analysis <module-or-script-or-dir> ...``

Runs the dy2static pre-flight linter over the targets' Python source
(no target code is imported or executed — modules resolve via find_spec).
Exit status: 0 clean / warnings only, 1 when error-severity diagnostics are
found (or any finding under ``--strict``), 2 on usage errors.
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import List

from .ast_lint import lint_path
from .diagnostics import SEVERITIES, Diagnostic


def main(argv: List[str] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m paddle_tpu.analysis",
        description="dy2static pre-flight lint over scripts, packages or "
                    "dotted module names (source-only; nothing is executed)")
    parser.add_argument("targets", nargs="+",
                        help=".py file, directory, or dotted module name "
                             "(e.g. examples/train_gpt.py, paddle_tpu.models.gpt)")
    parser.add_argument("--strict", action="store_true",
                        help="exit 1 on any diagnostic, not just errors")
    parser.add_argument("--min-severity", choices=SEVERITIES, default="info",
                        help="hide diagnostics below this level")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="emit diagnostics as a JSON array")
    args = parser.parse_args(argv)

    diags: List[Diagnostic] = []
    for target in args.targets:
        try:
            diags.extend(lint_path(target))
        except (OSError, ValueError) as e:
            print(f"error: {target}: {e}", file=sys.stderr)
            return 2

    floor = SEVERITIES.index(args.min_severity)
    shown = [d for d in diags if SEVERITIES.index(d.severity) >= floor]
    if args.as_json:
        print(json.dumps([{
            "code": d.code, "severity": d.severity, "message": d.message,
            "hint": d.hint, "file": d.file, "line": d.line, "col": d.col,
        } for d in shown], indent=2))
    else:
        for d in shown:
            print(d)
        counts = {s: sum(1 for d in diags if d.severity == s) for s in SEVERITIES}
        summary = ", ".join(f"{n} {s}" for s, n in counts.items() if n) or "clean"
        print(f"checked {len(args.targets)} target(s): {summary}")

    if any(d.severity == "error" for d in diags):
        return 1
    if args.strict and diags:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
