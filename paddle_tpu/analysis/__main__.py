"""CLI: ``python -m paddle_tpu.analysis <module-or-script-or-dir> ...``

Three modes:

- default — the dy2static pre-flight linter over the targets' Python source
  (no target code is imported or executed — modules resolve via find_spec);
- ``--hygiene`` — the dispatch-hygiene analyzer (PTA3xx) over the same
  Python-source targets: host syncs in traced code, recompile hazards,
  donation aliasing, nondeterminism in traced/seed paths, and unbounded
  host-state growth on serving tick loops;
- ``--hlo`` — the SPMD sharding analyzer (PTA2xx) over lowered-program HLO
  text files (``Compiled.as_text()`` dumps, ``XLA_FLAGS=--xla_dump_to``
  output): implicit all-gathers and spec-mismatch reshards with bytes-moved
  estimates, collective counts and the schedule fingerprint; ``--decode``
  applies the serving rule (PTA203: any collective fires per token) and
  ``--hbm-budget`` checks the text-derived per-device memory floor (PTA204).

Exit status: 0 clean / warnings only, 1 when error-severity diagnostics are
found (or any finding under ``--strict``), 2 on usage errors.
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import List

from .ast_lint import lint_path
from .diagnostics import SEVERITIES, Diagnostic
from .hygiene import HYGIENE_CODES

_CODE_LISTING = """\
diagnostic codes:
  PTA0xx — Program IR passes (FLAGS_static_check / Executor pre-flight):
    PTA001 dead op                    PTA005 baked dynamic dim [error]
    PTA002 unused feed                PTA006 duplicate computation (CSE)
    PTA003 implicit dtype promotion   PTA007 oversized closed-over constant
    PTA004 f16/bf16 reduction (AMP hazard)
  PTA1xx — dy2static source lint (default mode):
    PTA100 syntax error [error]       PTA103 break/continue in try/with
    PTA101 return inside a loop       PTA104 in-place mutation under if
    PTA102 tuple-target for loop      PTA105 side effect under trace
  PTA2xx — SPMD/HLO sharding passes (--hlo, FLAGS_shard_check):
    PTA201 implicit full-gather       PTA204 per-device HBM over budget [error]
    PTA202 spec-mismatch reshard      PTA205 collective-schedule divergence
    PTA203 collective per decoded token (serving)
    PTA206 large param fully replicated on a multi-device mesh
  PTA3xx — dispatch hygiene (--hygiene, FLAGS_sanitize at runtime):
""" + "".join(f"    {code} {text}\n" for code, text in sorted(HYGIENE_CODES.items()))


def _analyze_hlo_file(path: str, args) -> tuple:
    """(diagnostics, report dict) for one HLO text file."""
    from . import hlo as _hlo
    from . import spmd as _spmd

    with open(path, "r", encoding="utf-8") as f:
        text = f.read()
    opts = _spmd.ShardCheckOptions(decode=args.decode,
                                   hbm_budget_mb=args.hbm_budget or None)
    diags, collectives = _spmd.analyze_hlo_text(text, opts, label=path)
    floor = _hlo.entry_memory_lower_bound(text)
    if args.hbm_budget and floor > args.hbm_budget * (1 << 20):
        diags.append(Diagnostic(  # noqa: PTA104 (host-side CLI code)
            "PTA204", "error",
            f"per-device memory floor for {path} is ~{floor / (1 << 20):.1f} "
            f"MiB (entry parameters + largest result), over the --hbm-budget "
            f"of {args.hbm_budget:g} MiB",
            hint="this is a lower bound from text alone; the runtime check "
                 "(FLAGS_shard_check + FLAGS_hbm_budget_mb) uses XLA's full "
                 "memory_analysis"))
    report = {
        "file": path,
        "collectives": _hlo.collective_counts(collectives),
        "collective_count": len(collectives),
        "reshard_bytes": _hlo.total_moved_bytes(collectives),
        "memory_floor_bytes": floor,
        "fingerprint": _hlo.schedule_fingerprint(collectives),
        "schedule": [c.signature() for c in collectives],
    }
    return diags, report


def main(argv: List[str] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m paddle_tpu.analysis",
        description="static analysis CLI: dy2static pre-flight lint over "
                    "scripts/packages/modules (default), the dispatch-"
                    "hygiene analyzer (--hygiene), or the SPMD sharding "
                    "analyzer over lowered HLO text (--hlo)",
        epilog=_CODE_LISTING,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("targets", nargs="+",
                        help=".py file, directory, or dotted module name; "
                             "with --hlo: HLO text file(s)")
    parser.add_argument("--hygiene", action="store_true",
                        help="run the PTA3xx dispatch-hygiene passes (host "
                             "syncs in traced code, recompile hazards, "
                             "donation aliasing, nondeterminism, unbounded "
                             "host ledgers) instead of the dy2static lint")
    parser.add_argument("--hlo", action="store_true",
                        help="treat targets as lowered-program HLO text and "
                             "run the PTA2xx sharding passes")
    parser.add_argument("--decode", action="store_true",
                        help="with --hlo: apply the serving decode rule "
                             "(PTA203 — a compiled-in collective fires on "
                             "every generated token)")
    parser.add_argument("--hbm-budget", type=float, default=0.0, metavar="MB",
                        help="with --hlo: per-device memory budget in MiB "
                             "(PTA204 on the text-derived floor)")
    parser.add_argument("--strict", action="store_true",
                        help="exit 1 on any diagnostic, not just errors")
    parser.add_argument("--min-severity", choices=SEVERITIES, default="info",
                        help="hide diagnostics below this level")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="emit diagnostics as a JSON array (with --hlo: "
                             "one report object per file)")
    args = parser.parse_args(argv)
    if args.hygiene and args.hlo:
        print("error: --hygiene and --hlo are mutually exclusive",  # noqa: PTA105 (host-side CLI code)
              file=sys.stderr)
        return 2

    def _as_dict(d: Diagnostic) -> dict:
        return {"code": d.code, "severity": d.severity, "message": d.message,
                "hint": d.hint, "file": d.file, "line": d.line, "col": d.col,
                "op": d.op, "var": d.var}

    diags: List[Diagnostic] = []
    reports: List[dict] = []
    for target in args.targets:
        try:
            if args.hlo:
                d, rep = _analyze_hlo_file(target, args)
                diags.extend(d)  # noqa: PTA104 (host-side CLI code)
                rep["findings"] = [_as_dict(x) for x in d]  # noqa: PTA104 (host-side CLI code)
                reports.append(rep)  # noqa: PTA104 (host-side CLI code)
            elif args.hygiene:
                from .hygiene import check_path
                diags.extend(check_path(target))  # noqa: PTA104 (host-side CLI code)
            else:
                diags.extend(lint_path(target))  # noqa: PTA104 (host-side CLI code)
        except (OSError, ValueError) as e:
            print(f"error: {target}: {e}", file=sys.stderr)  # noqa: PTA105 (host-side CLI code)
            return 2  # noqa: PTA101 (host-side CLI code)

    floor = SEVERITIES.index(args.min_severity)
    shown = [d for d in diags if SEVERITIES.index(d.severity) >= floor]
    if args.as_json:
        if args.hlo:
            print(json.dumps(reports if len(reports) != 1 else reports[0],  # noqa: PTA105 (host-side CLI code)
                             indent=2))
        else:
            print(json.dumps([_as_dict(d) for d in shown], indent=2))  # noqa: PTA105 (host-side CLI code)
    else:
        for d in shown:
            print(d)  # noqa: PTA105 (host-side CLI code)
        if args.hlo:
            for rep in reports:
                sched = ", ".join(f"{k} x{n}" for k, n in
                                  sorted(rep["collectives"].items())) or "none"
                print(f"{rep['file']}: {rep['collective_count']} collective(s) "  # noqa: PTA105 (host-side CLI code)
                      f"[{sched}], ~{rep['reshard_bytes']:,} bytes moved/device"
                      f"/dispatch, memory floor {rep['memory_floor_bytes']:,} "
                      f"bytes, schedule {rep['fingerprint'][:16]}")
        counts = {s: sum(1 for d in diags if d.severity == s) for s in SEVERITIES}
        summary = ", ".join(f"{n} {s}" for s, n in counts.items() if n) or "clean"
        print(f"checked {len(args.targets)} target(s): {summary}")  # noqa: PTA105 (host-side CLI code)

    if any(d.severity == "error" for d in diags):
        return 1
    if args.strict and diags:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
