"""paddle_tpu.analysis — static analysis over the two IRs the framework
records.

Paddle parity: the L5 IR pass layer (paddle/fluid/framework/ir, ~190 graph
passes) and the inference analyzer (inference/analysis/analyzer.cc) inspect
and validate the ProgramDesc before the Executor/AnalysisPredictor run it.
The optimizing passes are XLA's job in this design; this package keeps the
*diagnostic* half, over both IRs we already have:

- the recorded :class:`~paddle_tpu.framework.static_trace.Program` — a
  def-use graph (:mod:`analysis.graph`) feeding registered passes
  (:mod:`analysis.passes`) that emit stable ``PTA0xx`` diagnostics, and
- the Python AST dy2static transpiles — a pre-flight linter
  (:mod:`analysis.ast_lint`, ``PTA1xx``) that points at unsupported
  constructs with file:line before any tracer error can occur, and
- the lowered SPMD program — the post-GSPMD HLO of a compiled-but-not-yet-
  dispatched executable (:mod:`analysis.spmd` + :mod:`analysis.hlo`,
  ``PTA2xx``): implicit all-gathers, spec-mismatch reshards, decode-loop
  collectives, HBM-budget overruns, cross-rank schedule divergence, and
- dispatch hygiene (:mod:`analysis.hygiene`, ``PTA3xx``): host syncs in
  traced code, recompile hazards, donation aliasing, nondeterminism and
  unbounded host-state growth — statically, with a runtime counterpart
  (:mod:`analysis.sanitizer`) behind ``FLAGS_sanitize``.

Entry points:
  ``Program.analyze(fetch_list)``          — run the IR passes
  ``Executor.run`` under ``FLAGS_static_check`` — auto-check per new program
  ``Executor.run``/``TrainStep``/``DecodeEngine``/``Engine.prepare`` under
  ``FLAGS_shard_check``                    — SPMD pre-flight per specialization
  ``TrainStep.explain(analyze=True)``      — lazy PTA2xx verdict per row
  ``paddle.jit.to_static(fn, lint=True)``  — pre-flight AST lint
  ``python -m paddle_tpu.analysis <target>`` — CLI over files/modules/dirs
  ``python -m paddle_tpu.analysis --hlo dump.txt`` — CLI over HLO text
  ``python -m paddle_tpu.analysis --hygiene <target>`` — PTA3xx passes
  ``FLAGS_sanitize=1``                     — runtime dispatch sanitizer
"""
from __future__ import annotations

from .ast_lint import (
    lint_file,
    lint_function,
    lint_module,
    lint_path,
    lint_source,
)
from .diagnostics import (
    SEVERITIES,
    Diagnostic,
    ProgramAnalysisError,
    format_report,
    max_severity,
)
from .graph import RESERVED_FEEDS, DefUseGraph
from .hygiene import (
    HYGIENE_CODES,
    check_file,
    check_module,
    check_path,
    check_source,
)
from .passes import (
    AnalysisContext,
    analyze_program,
    register_pass,
    registered_passes,
)
from .spmd import (
    ShardCheckOptions,
    SpmdReport,
    analyze_compiled,
    analyze_hlo_text,
    analyze_jit,
    shard_check,
    verify_collective_schedule,
)

__all__ = [
    "AnalysisContext",
    "DefUseGraph",
    "Diagnostic",
    "HYGIENE_CODES",
    "ProgramAnalysisError",
    "RESERVED_FEEDS",
    "SEVERITIES",
    "ShardCheckOptions",
    "SpmdReport",
    "analyze_compiled",
    "analyze_hlo_text",
    "analyze_jit",
    "analyze_program",
    "check_file",
    "check_module",
    "check_path",
    "check_source",
    "format_report",
    "lint_file",
    "lint_function",
    "lint_module",
    "lint_path",
    "lint_source",
    "max_severity",
    "register_pass",
    "registered_passes",
    "shard_check",
    "verify_collective_schedule",
]
