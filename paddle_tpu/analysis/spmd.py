"""SPMD sharding analyzer: ``PTA2xx`` passes over lowered programs.

The Program-IR passes (``PTA0xx``) and the AST linter (``PTA1xx``) both look
at what the *user wrote*; nothing inspected what actually runs on the chips.
A mis-placed ``PartitionSpec`` silently turns into a full all-gather, a
per-token collective in the serving decode loop, or an OOM discovered
minutes into compile. These passes walk the lowered-but-not-yet-dispatched
program — the post-GSPMD HLO retained by the observability AOT
``lower().compile()`` capture — plus the sharding annotations the runtime
already holds (``dist_spec`` params, ``TrainStep`` state shardings), and
turn each hazard into a structured :class:`~.diagnostics.Diagnostic`
**before dispatch**:

  PTA201  implicit full-gather of a sharded array (replication blow-up,
          with estimated bytes moved per device per dispatch)
  PTA202  spec-mismatch reshard between producer and consumer (a
          collective XLA inserted to feed a contraction)
  PTA203  collective inside a serving decode program (fires every token)
  PTA204  per-device memory estimate exceeds ``FLAGS_hbm_budget_mb`` [error]
  PTA205  cross-rank collective-schedule divergence (op-sequence/shape
          fingerprint exchanged through ``TCPStore``)            [error]
  PTA206  large parameter left fully replicated on a multi-device mesh

Entry points:
  ``shard_check(compiled, ...)``      — the ``FLAGS_shard_check`` wiring
  ``analyze_compiled(compiled, ...)`` — one executable -> SpmdReport
  ``verify_collective_schedule(...)`` — the PTA205 cross-rank exchange
  ``python -m paddle_tpu.analysis --hlo dump.txt`` — files/CLI

The JSON side (:meth:`SpmdReport.to_json`) is deliberately machine-first:
resharding bytes, collective schedule and per-device memory for any
candidate mesh/spec assignment — the evaluator the ROADMAP's cost-model
auto-parallel planner searches against.
"""
from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from . import hlo as _hlo
from .diagnostics import Diagnostic, ProgramAnalysisError

__all__ = [
    "ShardCheckOptions",
    "SpmdReport",
    "analyze_hlo_text",
    "analyze_params",
    "analyze_compiled",
    "analyze_jit",
    "verify_collective_schedule",
    "shard_check",
]

#: op_name tails that mark a collective as inserted to SERVE a contraction:
#: the producer's layout did not match what the consumer needed, i.e. a
#: producer/consumer PartitionSpec mismatch (PTA202). Deliberate user
#: collectives (lax.ppermute in the pipeline, MoE all_to_all) carry their
#: own op_name and are not reshards.
_CONTRACTION_TOKENS = ("dot_general", "dot", "conv", "einsum")


@dataclass
class ShardCheckOptions:
    """Per-check knobs. ``None`` budget defers to ``FLAGS_hbm_budget_mb``
    (0 = unlimited). The byte thresholds tier severity: a finding below the
    threshold is reported as ``info`` (visible in the JSON verdict, silent
    in the warnings stream) — tiny-model gathers are noise, the same spec
    at production shapes is the finding."""

    hbm_budget_mb: Optional[float] = None
    allgather_warn_bytes: int = 1 << 20      # PTA201/PTA202 warning floor
    replicated_param_bytes: int = 8 << 20    # PTA206 floor
    decode: bool = False                     # serving decode program (PTA203)


def _budget_mb(options: ShardCheckOptions) -> float:
    if options.hbm_budget_mb is not None:
        return float(options.hbm_budget_mb)
    from ..framework.flags import flag

    return float(flag("FLAGS_hbm_budget_mb"))


# ------------------------------------------------------------------ passes
def _tiered(bytes_moved: int, floor: int) -> str:
    return "warning" if bytes_moved >= floor else "info"


def analyze_hlo_text(hlo_text: str, options: Optional[ShardCheckOptions] = None,
                     label: str = "") -> Tuple[List[Diagnostic], List[_hlo.HloCollective]]:
    """PTA201/PTA202/PTA203 over one lowered program's HLO text.

    Returns ``(diagnostics, collectives)`` — the collective list feeds the
    schedule fingerprint and the report JSON even when no pass fires.
    """
    options = options or ShardCheckOptions()
    collectives = _hlo.parse_collectives(hlo_text)
    diags: List[Diagnostic] = []
    where = f" in {label}" if label else ""
    for c in collectives:
        moved = _hlo.moved_bytes(c)
        forced_tail = c.op_name.rsplit("/", 1)[-1] if c.op_name else ""
        is_reshard = any(tok in forced_tail for tok in _CONTRACTION_TOKENS)
        if c.kind == "all-gather":
            diags.append(Diagnostic(
                "PTA201", _tiered(moved, options.allgather_warn_bytes),
                f"implicit full-gather{where}: {c.describe()} — a sharded "
                "value is materialized replicated on every device of the "
                "group",
                hint="add/align a with_sharding_constraint (or the param's "
                     "PartitionSpec) so the consumer reads the shard it "
                     "already holds; if the gather is intended (ZeRO-3 "
                     "weights), this is its per-dispatch cost",
                op=c.name, var=c.source or None))
        if is_reshard and c.kind in ("all-gather", "all-to-all",
                                     "collective-permute"):
            diags.append(Diagnostic(
                "PTA202", _tiered(moved, options.allgather_warn_bytes),
                f"spec-mismatch reshard{where}: producer sharding does not "
                f"match what '{forced_tail}' consumes — XLA inserted "
                f"{c.describe()}",
                hint="make the producer's output spec and the consumer's "
                     "operand spec agree (classic fix: column-parallel into "
                     "row-parallel, contracting dim sharded on both sides)",
                op=c.name, var=c.source or None))
        if options.decode:
            diags.append(Diagnostic(
                "PTA203", "warning",
                f"collective inside a serving decode program{where}: "
                f"{c.describe()} — the decode hot loop pays this on every "
                "generated token",
                hint="keep single-host decode programs collective-free; on "
                     "an mp-sharded engine, budget it deliberately (it "
                     "bounds per-token latency)",
                op=c.name, var=c.source or None))
    return diags, collectives


def analyze_params(params: Dict[str, Any], shardings: Dict[str, Any],
                   options: Optional[ShardCheckOptions] = None,
                   label: str = "") -> List[Diagnostic]:
    """PTA206: large params left fully replicated on a multi-device mesh.

    ``params`` maps name -> array (or anything with shape/dtype);
    ``shardings`` maps name -> NamedSharding / PartitionSpec.
    """
    import numpy as np

    options = options or ShardCheckOptions()
    diags: List[Diagnostic] = []
    where = f" in {label}" if label else ""
    for name, arr in params.items():
        sh = shardings.get(name)
        if sh is None:
            continue
        mesh = getattr(sh, "mesh", None)
        ndev = int(getattr(mesh, "size", 1) or 1)
        if ndev <= 1:
            continue
        replicated = getattr(sh, "is_fully_replicated", None)
        if replicated is None:  # bare PartitionSpec
            replicated = all(e is None for e in tuple(sh))
        if not replicated:
            continue
        nbytes = int(np.prod(arr.shape)) * np.dtype(arr.dtype).itemsize if hasattr(arr, "shape") else 0
        if nbytes < options.replicated_param_bytes:
            continue
        diags.append(Diagnostic(
            "PTA206", "warning",
            f"parameter {name!r}{where} ({tuple(arr.shape)}, ~{nbytes:,} "
            f"bytes) is fully replicated on a {ndev}-device mesh — "
            f"{ndev}x the HBM of a sharded layout",
            hint="give it a PartitionSpec over an existing mesh axis "
                 "(shard_tensor / dist_spec), or ZeRO-shard the optimizer "
                 "state over 'sdp'",
            var=name))
    return diags


# ------------------------------------------------------------------ report
@dataclass
class SpmdReport:
    """The machine-readable verdict for one lowered program."""

    label: str = ""
    kind: str = ""
    diagnostics: List[Diagnostic] = field(default_factory=list)
    collectives: List[_hlo.HloCollective] = field(default_factory=list)
    fingerprint: str = ""
    moved_bytes: int = 0
    peak_bytes: Optional[int] = None
    hbm_budget_mb: float = 0.0

    @property
    def errors(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == "error"]

    def counts(self) -> Dict[str, int]:
        return _hlo.collective_counts(self.collectives)

    def summary(self) -> Dict[str, Any]:
        """Flat dict for run-log events / bench JSON / report rows."""
        sev = {s: sum(1 for d in self.diagnostics if d.severity == s)
               for s in ("info", "warning", "error")}
        return {
            "label": self.label,
            "kind": self.kind,
            "collectives": self.counts(),
            "collective_count": len(self.collectives),
            "reshard_bytes": self.moved_bytes,
            "peak_bytes": self.peak_bytes,
            "fingerprint": self.fingerprint,
            "codes": sorted({d.code for d in self.diagnostics}),
            "diagnostics": sev,
        }

    def to_json(self) -> Dict[str, Any]:
        """Full verdict: summary + per-collective rows + diagnostics — the
        objective-function record a mesh/spec search consumes."""
        out = self.summary()
        out["schedule"] = [{
            "kind": c.kind, "name": c.name, "index": c.index,
            "group_size": c.group_size, "num_groups": c.num_groups,
            "bytes_moved": _hlo.moved_bytes(c),
            "result_shapes": [f"{dt}{list(dims)}" for dt, dims in c.result_shapes],
            "op_name": c.op_name, "source": c.source,
        } for c in self.collectives]
        out["findings"] = [{
            "code": d.code, "severity": d.severity, "message": d.message,
            "hint": d.hint, "op": d.op, "var": d.var,
        } for d in self.diagnostics]
        return out


def analyze_compiled(compiled, label: str = "", kind: str = "",
                     options: Optional[ShardCheckOptions] = None,
                     params: Optional[Dict[str, Any]] = None,
                     param_shardings: Optional[Dict[str, Any]] = None) -> SpmdReport:
    """Run every locally-decidable PTA2xx pass over one XLA ``Compiled``
    executable (PTA205 needs the cross-rank exchange — see
    :func:`verify_collective_schedule`). Never raises on analysis gaps: an
    executable that exposes no HLO text or memory stats just yields an
    emptier report — the analyzer must not break dispatch.
    """
    options = options or ShardCheckOptions()
    report = SpmdReport(label=label, kind=kind)
    try:
        text = compiled.as_text()
    except Exception:
        text = ""
    if text:
        report.diagnostics, report.collectives = analyze_hlo_text(
            text, options, label=label)
        report.fingerprint = _hlo.schedule_fingerprint(report.collectives)
        report.moved_bytes = _hlo.total_moved_bytes(report.collectives)
    # PTA204: per-device memory estimate vs the HBM budget
    budget = _budget_mb(options)
    report.hbm_budget_mb = budget
    try:
        from ..observability.introspect import cost_summary

        report.peak_bytes = cost_summary(compiled).get("peak_bytes")
    except Exception:
        report.peak_bytes = None
    if budget and report.peak_bytes and report.peak_bytes > budget * (1 << 20):
        report.diagnostics.append(Diagnostic(
            "PTA204", "error",
            f"per-device memory estimate for {label or 'program'} is "
            f"~{report.peak_bytes / (1 << 20):.1f} MiB, over the "
            f"FLAGS_hbm_budget_mb budget of {budget:g} MiB — this OOMs at "
            "dispatch, not at annotation time",
            hint="shard the largest replicated tensors (PTA206 names them), "
                 "enable remat/offload, or raise the budget if the device "
                 "really has the headroom"))
    # PTA206: replicated large params
    if params and param_shardings:
        report.diagnostics.extend(
            analyze_params(params, param_shardings, options, label=label))
    return report


def analyze_jit(jitfn, args: Tuple, label: str = "",
                options: Optional[ShardCheckOptions] = None, **kw) -> SpmdReport:
    """Lower + compile ``jitfn`` on ``args`` (AOT, nothing dispatched) and
    analyze the executable — the pre-flight spelling for callers that have
    not compiled yet (``Engine.prepare``, tests, the planner)."""
    from ..observability.introspect import aot_compile

    compiled, _info = aot_compile(jitfn, args)
    if compiled is None:
        return SpmdReport(label=label, kind="aot-unavailable")
    return analyze_compiled(compiled, label=label, options=options, **kw)


# ---------------------------------------------------------------- PTA205
def verify_collective_schedule(store, rank: int, world_size: int,
                               report_or_fingerprint, tag: str = "spmd",
                               timeout: Optional[float] = None,
                               max_ops: int = 512) -> List[Diagnostic]:
    """PTA205: exchange each rank's collective-schedule fingerprint through
    a :class:`~paddle_tpu.distributed.store.TCPStore` and diagnose
    divergence BEFORE any collective is dispatched.

    A rank whose lowered program issues a different collective sequence
    (extra reshard, different shape, different order) deadlocks the whole
    job at runtime; ``diagnostic_barrier`` can only name the hang after it
    happens. Here every rank publishes ``(fingerprint, op signatures)``
    under ``__shard_check__/<tag>/<rank>`` and compares against every peer;
    mismatches come back as PTA205 **error** diagnostics naming the peer
    rank and the first divergent schedule position.

    ``tag`` must be fresh per checked program (e.g. include the
    specialization label) — store keys persist.
    """
    if isinstance(report_or_fingerprint, SpmdReport):
        ops = [c.signature() for c in report_or_fingerprint.collectives]
        fp = report_or_fingerprint.fingerprint
    else:
        fp, ops = str(report_or_fingerprint), []
    payload = json.dumps({"fp": fp, "n": len(ops), "ops": ops[:max_ops]})
    store.set(f"__shard_check__/{tag}/{rank}", payload)
    diags: List[Diagnostic] = []
    for peer in range(world_size):
        if peer == rank:
            continue
        raw = store.get(f"__shard_check__/{tag}/{peer}", timeout=timeout)
        theirs = json.loads(raw if isinstance(raw, str) else raw.decode())
        if theirs["fp"] == fp:
            continue
        their_ops = theirs.get("ops", [])
        pos = next((i for i, (a, b) in enumerate(zip(ops, their_ops)) if a != b),
                   min(len(ops), len(their_ops)))
        mine_at = ops[pos] if pos < len(ops) else "<end of schedule>"
        theirs_at = their_ops[pos] if pos < len(their_ops) else "<end of schedule>"
        diags.append(Diagnostic(
            "PTA205", "error",
            f"collective schedule diverges from rank {peer} at position "
            f"{pos}: rank {rank} issues {mine_at}, rank {peer} issues "
            f"{theirs_at} (rank {rank}: {len(ops)} collectives, rank "
            f"{peer}: {theirs.get('n', len(their_ops))}) — dispatching this "
            "program deadlocks the job",
            hint="the ranks lowered different programs: check per-rank "
                 "batch shapes, flags and code version; this is the "
                 "pre-flight form of the hang diagnostic_barrier reports "
                 "after the fact"))
    return diags


# ----------------------------------------------------------------- wiring
def shard_check(compiled, component: str, label: str = "", kind: str = "",
                options: Optional[ShardCheckOptions] = None,
                params: Optional[Dict[str, Any]] = None,
                param_shardings: Optional[Dict[str, Any]] = None,
                store=None, rank: int = 0, world_size: int = 1,
                raise_on_error: bool = True) -> SpmdReport:
    """The ``FLAGS_shard_check`` body, run once per new specialization
    (mirroring ``FLAGS_static_check``): analyze, count, log, then surface —
    warnings via the warnings module, error-severity findings (PTA204
    budget, PTA205 divergence) raise :class:`ProgramAnalysisError` *before*
    the executable is ever dispatched.
    """
    import warnings as _warnings

    from ..observability import runlog as _runlog
    from ..observability.metrics import counter_inc

    report = analyze_compiled(compiled, label=label, kind=kind,
                              options=options, params=params,
                              param_shardings=param_shardings)
    if store is not None and world_size > 1:
        report.diagnostics.extend(verify_collective_schedule(
            store, rank, world_size, report, tag=f"{component}/{label or kind}"))
    counter_inc("analysis.shard_checks")
    counter_inc("analysis.diagnostics", len(report.diagnostics))
    counter_inc("analysis.collectives", len(report.collectives))
    errors = report.errors
    if errors:
        counter_inc("analysis.errors", len(errors))
    _runlog.emit("shard_check", component=component, **report.summary())
    for d in report.diagnostics:
        if d.severity == "warning":
            _warnings.warn(f"FLAGS_shard_check: {d}", stacklevel=3)
    if errors and raise_on_error:
        # PTA204/205 abort the dispatch — leave a flight-recorder dump so
        # the post-mortem carries the analysis verdict and the event tail
        from ..observability import flightrec as _flightrec

        err = ProgramAnalysisError(errors)
        _flightrec.dump("analysis_error", err, component=component,
                        label=label, kind=kind,
                        codes=[d.code for d in errors])
        raise err
    return report
