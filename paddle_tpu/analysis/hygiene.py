"""Dispatch-hygiene analyzer: the PTA3xx static AST passes.

Third analysis family, after the Program IR passes (PTA0xx) and the
dy2static pre-flight lint (PTA1xx): these passes look for the *dispatch
hygiene* bug classes that bit this repo live — host syncs on traced hot
paths, silent recompile churn, donated-buffer aliasing (the PR-10 bug),
nondeterminism on the bitwise-replay contract, and per-request host state
that grows without bound in serving tick loops. Purely source-level (same
discipline as :mod:`.ast_lint`: nothing is imported or executed); the
runtime counterpart lives in :mod:`.sanitizer` behind ``FLAGS_sanitize``.

Codes:
  PTA301 host sync in traced code (.item()/bool()/int()/float()/
         np.asarray on traced values, print in traced/scan/step bodies)
  PTA302 recompile hazard: data-derived Python value flowing into a
         shape/slice position — every new value compiles a new program
  PTA303 donation-aliasing hazard: a state-leaf reference held across a
         donated dispatch (reuse crashes on the deleted buffer)
  PTA304 nondeterminism in a traced or seed-derivation path (time.*,
         random.*, os.urandom, unordered-set iteration)
  PTA305 unbounded host-state growth in a serving/fleet tick loop
         (append-without-GC on a per-request ledger)

A function is *traced* when it is decorated ``@to_static``/``@jit``/
``@checkpoint`` (or a ``partial`` thereof), referenced by name in a call
to ``jax.jit``/``lax.scan``/``lax.cond``/``vmap``/``grad``/``shard_map``/
``pallas_call``/``scan_steps``/…, or nested inside a traced function.
``# noqa: PTA3xx`` on the flagged line suppresses a finding (bare
``# noqa`` suppresses all) — same opt-out as the PTA1xx lint.
"""
from __future__ import annotations

import ast
import importlib.util
import os
from typing import Dict, List, Optional, Set

from .ast_lint import _noqa_lines
from .diagnostics import Diagnostic

__all__ = ["HYGIENE_CODES", "check_source", "check_file", "check_module",
           "check_path"]

#: registered code -> one-line finding (CLI help + README drift guard)
HYGIENE_CODES = {
    "PTA301": "host sync in traced code (.item()/bool()/int()/float()/"
              "np.asarray on traced values, print under trace)",
    "PTA302": "recompile hazard: data-derived Python value flows into a "
              "shape/slice position",
    "PTA303": "donation-aliasing hazard: state leaf held across a donated "
              "dispatch",
    "PTA304": "nondeterminism in a traced or seed-derivation path",
    "PTA305": "unbounded host-state growth in a serving tick loop",
}

# calls whose function-name arguments become traced bodies
_TRACE_CALLS = {
    "jit", "scan", "while_loop", "fori_loop", "cond", "switch", "vmap",
    "pmap", "grad", "value_and_grad", "shard_map", "pallas_call",
    "checkpoint", "remat", "scan_steps", "to_static", "custom_vjp",
    "custom_jvp",
}
_TRACED_DECORATORS = {"jit", "to_static", "checkpoint", "remat",
                      "custom_vjp", "custom_jvp"}
# attribute accesses that yield static (non-traced) values
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size"}
# calls that never return traced values
_HOST_FUNCS = {"len", "range", "enumerate", "zip", "isinstance", "getattr",
               "hasattr", "type", "id", "repr", "str", "format"}
# device->host sync methods
_SYNC_METHODS = {"item", "tolist", "numpy"}
# constructors/ops with a shape-position first argument (PTA302 sinks)
_SHAPE_FNS = {"reshape", "zeros", "ones", "full", "empty", "arange",
              "linspace", "tile", "broadcast_to"}
# dispatch-like calls that donate state buffers (PTA303)
_DISPATCH_CALLS = {"run_steps", "decode_step", "prefill_step", "prefill",
                   "_dispatch", "step"}
# names whose terminal marks a state tree (PTA303 alias sources)
_STATE_NAMES = {"state", "_state"}
# methods that make a class a serving/tick loop owner (PTA305 roots)
_TICK_METHODS = {"step", "tick", "run", "serve", "poll", "loop", "drain",
                 "submit", "harvest", "run_steps"}
# container growth / shrink vocabulary (PTA305)
_GROW_METHODS = {"append", "add", "extend", "appendleft", "setdefault"}
_SHRINK_METHODS = {"pop", "popitem", "popleft", "clear", "remove", "discard",
                   "difference_update"}
# nondeterminism vocabulary (PTA304)
_TIME_FNS = {"time", "time_ns", "monotonic", "monotonic_ns", "perf_counter",
             "perf_counter_ns"}
_RANDOM_FNS = {"random", "randint", "randrange", "choice", "choices",
               "shuffle", "uniform", "sample", "getrandbits", "gauss",
               "normalvariate", "randbytes"}


def _terminal(node) -> Optional[str]:
    """Last component of a Name/Attribute chain (``jax.lax.scan`` ->
    ``scan``), or None for computed callees."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _dotted(node) -> Optional[str]:
    """Full dotted path when the chain is Names/Attributes only."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)  # noqa: PTA104 (host-side analyzer code)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)  # noqa: PTA104 (host-side analyzer code)
        return ".".join(reversed(parts))
    return None


def _self_attr(node) -> Optional[str]:
    """``self.X`` -> ``X`` (else None)."""
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _ordered_stmts(body) -> List[ast.stmt]:
    """Statements of a function body flattened in source order, descending
    into compound statements but NOT into nested function/class scopes."""
    out: List[ast.stmt] = []

    def _flat(stmts):
        for s in stmts:
            out.append(s)  # noqa: PTA104 (host-side analyzer code)
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
                continue
            for field in ("body", "orelse", "finalbody"):
                _flat(getattr(s, field, []) or [])
            for h in getattr(s, "handlers", []) or []:
                _flat(h.body)

    _flat(body)
    return out


def _exprs_of(stmt) -> List[ast.expr]:
    """The expressions belonging to one statement (not its nested block
    bodies — those are separate statements in the ordered walk)."""
    out = []
    for field, value in ast.iter_fields(stmt):  # noqa: PTA102 (host-side analyzer code)
        if field in ("body", "orelse", "finalbody", "handlers"):
            continue
        if isinstance(value, ast.expr):
            out.append(value)  # noqa: PTA104 (host-side analyzer code)
        elif isinstance(value, list):
            out.extend(v for v in value if isinstance(v, ast.expr))  # noqa: PTA104 (host-side analyzer code)
    return out


def _walk_no_scopes(node):
    """ast.walk that does not descend into nested function/class scopes."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        for child in ast.iter_child_nodes(n):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef, ast.Lambda)):
                continue
            stack.append(child)  # noqa: PTA104 (host-side analyzer code)


class _Emitter:
    def __init__(self, filename: str, offset: int):
        self.diags: List[Diagnostic] = []
        self.filename = filename
        self.offset = offset

    def emit(self, code: str, node, message: str, hint: str = "",
             severity: str = "warning"):
        self.diags.append(Diagnostic(
            code, severity, message, hint=hint, file=self.filename,
            line=(node.lineno + self.offset) if hasattr(node, "lineno") else None,
            col=getattr(node, "col_offset", None)))


# =====================================================================
# PTA301 + PTA304: traced-function passes
# =====================================================================

class _TracedBodyPass:
    """Host-sync (PTA301) and nondeterminism (PTA304) inside ONE traced
    function body. Taint = values derived from the function's parameters
    (the traced operands); static derivations (``.shape``/``len``) are
    exempt so shape math never false-positives."""

    def __init__(self, em: _Emitter, fdef, check_determinism_only=False):
        self.em = em
        self.fdef = fdef
        self.determinism_only = check_determinism_only
        args = fdef.args
        names = [a.arg for a in (args.posonlyargs + args.args + args.kwonlyargs)]
        if args.vararg:
            names.append(args.vararg.arg)  # noqa: PTA104 (host-side analyzer code)
        if args.kwarg:
            names.append(args.kwarg.arg)  # noqa: PTA104 (host-side analyzer code)
        self.taint: Set[str] = {n for n in names if n not in ("self", "cls")}

    # ----------------------------------------------------------- taint
    def _tainted(self, node) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.taint
        if isinstance(node, ast.Attribute):
            return node.attr not in _STATIC_ATTRS and self._tainted(node.value)
        if isinstance(node, ast.Subscript):
            return self._tainted(node.value)
        if isinstance(node, ast.BinOp):
            return self._tainted(node.left) or self._tainted(node.right)
        if isinstance(node, ast.UnaryOp):
            return self._tainted(node.operand)
        if isinstance(node, ast.Compare):
            return (self._tainted(node.left)
                    or any(self._tainted(c) for c in node.comparators))
        if isinstance(node, ast.BoolOp):
            return any(self._tainted(v) for v in node.values)
        if isinstance(node, ast.IfExp):
            return self._tainted(node.body) or self._tainted(node.orelse)
        if isinstance(node, (ast.Tuple, ast.List)):
            return any(self._tainted(e) for e in node.elts)
        if isinstance(node, ast.Starred):
            return self._tainted(node.value)
        if isinstance(node, ast.Call):
            t = _terminal(node.func)
            if t in _HOST_FUNCS or t in _SYNC_METHODS:
                return False
            if isinstance(node.func, ast.Attribute) and self._tainted(node.func.value):
                return True
            return any(self._tainted(a) for a in node.args) or any(
                self._tainted(kw.value) for kw in node.keywords)
        return False

    # ------------------------------------------------------------- run
    def run(self):
        for stmt in _ordered_stmts(self.fdef.body):
            for expr in _exprs_of(stmt):
                for sub in _walk_no_scopes(expr):
                    if isinstance(sub, ast.Call):
                        if not self.determinism_only:
                            self._check_sync(sub)
                        self._check_entropy(sub)
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                self._check_set_iteration(stmt)
            self._propagate(stmt)

    def _propagate(self, stmt):
        if isinstance(stmt, ast.Assign):
            tainted = self._tainted(stmt.value)
            for tgt in stmt.targets:
                if isinstance(tgt, ast.Name):
                    if tainted:
                        self.taint.add(tgt.id)  # noqa: PTA104 (host-side analyzer code)
                    else:
                        self.taint.discard(tgt.id)  # noqa: PTA104 (host-side analyzer code)
        elif isinstance(stmt, ast.AugAssign) and isinstance(stmt.target, ast.Name):
            if self._tainted(stmt.value):
                self.taint.add(stmt.target.id)  # noqa: PTA104 (host-side analyzer code)
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None \
                and isinstance(stmt.target, ast.Name):
            if self._tainted(stmt.value):
                self.taint.add(stmt.target.id)  # noqa: PTA104 (host-side analyzer code)
            else:
                self.taint.discard(stmt.target.id)  # noqa: PTA104 (host-side analyzer code)

    # --------------------------------------------------------- PTA301
    def _check_sync(self, call: ast.Call):
        fn = self.fdef.name
        if isinstance(call.func, ast.Name) and call.func.id == "print":
            self.em.emit(
                "PTA301", call,
                f"print() inside traced function {fn!r}: runs once at trace "
                "time with abstract values and forces a host round-trip if "
                "the value is materialized",
                hint="use jax.debug.print, or fetch and print outside the "
                     "traced body")
            return
        t = _terminal(call.func)
        if (t in _SYNC_METHODS and isinstance(call.func, ast.Attribute)
                and self._tainted(call.func.value)):
            self.em.emit(
                "PTA301", call,
                f".{t}() on a traced value inside {fn!r}: a device->host "
                "sync per dispatch — the hot path serializes on it",
                hint="keep the value on-device (lax.cond/where) or read it "
                     "back once outside the traced body")
        elif (isinstance(call.func, ast.Name)
              and call.func.id in ("bool", "int", "float")
              and call.args and self._tainted(call.args[0])):
            self.em.emit(
                "PTA301", call,
                f"{call.func.id}() on a traced value inside {fn!r}: forces "
                "concretization — TracerBoolConversionError at trace time or "
                "a silent host sync",
                hint="branch with lax.cond / jnp.where instead of a Python "
                     "conversion")
        elif (t in ("asarray", "array")
              and isinstance(call.func, ast.Attribute)
              and _terminal(call.func.value) in ("np", "numpy")
              and any(self._tainted(a) for a in call.args)):
            self.em.emit(
                "PTA301", call,
                f"np.{t}() on a traced value inside {fn!r}: device->host "
                "materialization in the traced body",
                hint="use jnp instead of np inside traced code")

    # --------------------------------------------------------- PTA304
    def _check_entropy(self, call: ast.Call):
        fn = self.fdef.name
        dotted = _dotted(call.func) or ""
        parts = dotted.split(".")
        t = parts[-1] if parts else ""
        base = parts[-2] if len(parts) > 1 else ""
        if base == "time" and t in _TIME_FNS:
            self.em.emit(
                "PTA304", call,
                f"time.{t}() in {fn!r}: wall-clock entropy in a "
                "traced/seed-derivation path breaks bitwise replay",
                hint="derive timestamps outside and pass them in, or fold a "
                     "deterministic counter")
        elif base == "random" and t in _RANDOM_FNS:
            if len(parts) >= 3 and parts[-3] in ("np", "numpy"):
                self.em.emit(
                    "PTA304", call,
                    f"np.random.{t}() in {fn!r}: the legacy global numpy "
                    "RNG is process-order-dependent state",
                    hint="use np.random.default_rng(seed) or "
                         "framework.random")
            else:
                self.em.emit(
                    "PTA304", call,
                    f"random.{t}() in {fn!r}: the global Python RNG breaks "
                    "the bitwise-replay contract",
                    hint="fold a paddle.seed-derived key instead")
        elif dotted == "np.random.default_rng" or dotted == "numpy.random.default_rng":
            if not call.args and not call.keywords:
                self.em.emit(
                    "PTA304", call,
                    f"np.random.default_rng() with no seed in {fn!r}: "
                    "OS-entropy seeding, different every run",
                    hint="pass an explicit seed")
        elif dotted == "os.urandom" or base == "secrets" or dotted in (
                "uuid.uuid1", "uuid.uuid4"):
            self.em.emit(
                "PTA304", call,
                f"{dotted}() in {fn!r}: OS entropy in a "
                "traced/seed-derivation path",
                hint="derive ids/keys from the run seed "
                     "(framework.random / trace.new_trace_id)")

    def _check_set_iteration(self, stmt):
        it = stmt.iter
        is_set = isinstance(it, ast.Set) or (
            isinstance(it, ast.Call) and isinstance(it.func, ast.Name)
            and it.func.id in ("set", "frozenset"))
        if is_set:
            self.em.emit(
                "PTA304", stmt,
                f"iteration over an unordered set in {self.fdef.name!r}: "
                "element order is hash-seed-dependent, so derived values "
                "differ across processes",
                hint="iterate sorted(...) for a deterministic order")


# =====================================================================
# PTA302: recompile hazard (host functions)
# =====================================================================

class _RecompilePass:
    """Data-derived Python values (``.item()``/``.tolist()`` readbacks and
    arithmetic thereof) flowing into shape/slice positions: every new value
    is a new signature, so the dispatch compiles per VALUE. Quantization
    (``//``, ``%``, ``>>`` — the bucketing fix) breaks the taint."""

    def __init__(self, em: _Emitter, fdef):
        self.em = em
        self.fdef = fdef
        self.taint: Set[str] = set()

    def _seed_expr(self, node) -> bool:
        """An expression that reads array DATA back as a Python value."""
        if isinstance(node, ast.Call):
            t = _terminal(node.func)
            if t in ("item", "tolist"):
                return True
            if (isinstance(node.func, ast.Name) and t in ("int", "float")
                    and node.args):
                return self._seed_expr(node.args[0]) or self._tainted(node.args[0])
            if t in ("asarray", "array") and isinstance(node.func, ast.Attribute) \
                    and _terminal(node.func.value) in ("np", "numpy"):
                return True  # int(np.asarray(x)) — the readback chain
        return False

    def _tainted(self, node) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.taint
        if isinstance(node, ast.BinOp):
            if isinstance(node.op, (ast.FloorDiv, ast.Mod, ast.RShift)):
                return False  # quantized to a bucket: churn bounded
            return self._tainted(node.left) or self._tainted(node.right)
        if isinstance(node, ast.UnaryOp):
            return self._tainted(node.operand)
        if isinstance(node, ast.Call):
            t = _terminal(node.func)
            if isinstance(node.func, ast.Name) and t in ("int", "float"):
                return any(self._tainted(a) or self._seed_expr(a)
                           for a in node.args)
            return False  # helper calls assumed to normalize/bucket
        if isinstance(node, ast.IfExp):
            return self._tainted(node.body) or self._tainted(node.orelse)
        return False

    def run(self):
        for stmt in _ordered_stmts(self.fdef.body):
            for expr in _exprs_of(stmt):
                for sub in _walk_no_scopes(expr):
                    self._check_sinks(sub)
            if isinstance(stmt, ast.Assign):
                tainted = self._seed_expr(stmt.value) or self._tainted(stmt.value)
                for tgt in stmt.targets:
                    if isinstance(tgt, ast.Name):
                        if tainted:
                            self.taint.add(tgt.id)  # noqa: PTA104 (host-side analyzer code)
                        else:
                            self.taint.discard(tgt.id)  # noqa: PTA104 (host-side analyzer code)
            elif isinstance(stmt, ast.AugAssign) and isinstance(stmt.target, ast.Name):
                if self._seed_expr(stmt.value) or self._tainted(stmt.value):
                    self.taint.add(stmt.target.id)  # noqa: PTA104 (host-side analyzer code)

    def _check_sinks(self, node):
        fn = self.fdef.name
        if (isinstance(node, ast.Subscript) and isinstance(node.ctx, ast.Load)
                and isinstance(node.slice, ast.Slice)):
            for bound in (node.slice.lower, node.slice.upper):
                if bound is not None and (self._tainted(bound)
                                          or self._seed_expr(bound)):
                    self.em.emit(
                        "PTA302", node,
                        f"data-derived slice bound in {fn!r}: the sliced "
                        "extent changes per value, so every dispatch "
                        "compiles a fresh program",
                        hint="pad/bucket to a fixed set of extents "
                             "(round up with // bucket * bucket)")
                    return  # noqa: PTA101 (host-side analyzer code)
        elif isinstance(node, ast.Call) and _terminal(node.func) in _SHAPE_FNS:
            candidates = list(node.args)
            candidates += [kw.value for kw in node.keywords
                           if kw.arg == "shape"]
            for arg in candidates:
                vals = arg.elts if isinstance(arg, (ast.Tuple, ast.List)) else [arg]
                if any(self._tainted(v) or self._seed_expr(v) for v in vals):
                    self.em.emit(
                        "PTA302", node,
                        f"data-derived value in a shape position "
                        f"({_terminal(node.func)}) in {fn!r}: a new shape "
                        "per value means a new XLA compile per dispatch",
                        hint="bucket the extent to a fixed ladder before it "
                             "reaches the shape")
                    return  # noqa: PTA101 (host-side analyzer code)


# =====================================================================
# PTA303: donation-aliasing hazard
# =====================================================================

class _DonationAliasPass:
    """A reference into a state tree (``x = self.state[...]``/``state[...]``)
    taken BEFORE a donating dispatch and used AFTER it: the dispatch donated
    the underlying buffer, so the held leaf is deleted — the PR-10 bug."""

    def __init__(self, em: _Emitter, fdef):
        self.em = em
        self.fdef = fdef

    @staticmethod
    def _state_subscript(node) -> bool:
        """RHS reads a leaf out of something called ``state``."""
        for sub in _walk_no_scopes(node):
            if isinstance(sub, ast.Subscript):
                base = sub.value
                while isinstance(base, ast.Subscript):
                    base = base.value
                t = _terminal(base)
                if t in _STATE_NAMES:
                    return True  # noqa: PTA101 (host-side analyzer code)
        return False

    @staticmethod
    def _is_dispatch(node) -> bool:
        if not isinstance(node, ast.Call):
            return False
        t = _terminal(node.func)
        return t in _DISPATCH_CALLS

    def run(self):
        stmts = _ordered_stmts(self.fdef.body)
        aliases: Dict[str, int] = {}        # name -> line the leaf was taken
        dispatch_lines: List[int] = []
        events = []                          # (line, kind, payload)
        for stmt in stmts:
            if isinstance(stmt, ast.Assign) and self._state_subscript(stmt.value):
                for tgt in stmt.targets:
                    if isinstance(tgt, ast.Name):
                        events.append((stmt.lineno, "alias", tgt.id))  # noqa: PTA104 (host-side analyzer code)
            elif isinstance(stmt, ast.Assign):
                for tgt in stmt.targets:
                    if isinstance(tgt, ast.Name):
                        events.append((stmt.lineno, "rebind", tgt.id))  # noqa: PTA104 (host-side analyzer code)
            for expr in _exprs_of(stmt):
                for sub in _walk_no_scopes(expr):
                    if self._is_dispatch(sub):
                        events.append((sub.lineno, "dispatch", None))  # noqa: PTA104 (host-side analyzer code)
                    elif isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load):
                        events.append((sub.lineno, "use", (sub.id, sub)))  # noqa: PTA104 (host-side analyzer code)
        events.sort(key=lambda e: e[0])
        flagged: Set[str] = set()
        for line, kind, payload in events:  # noqa: PTA102 (host-side analyzer code)
            if kind == "alias":
                aliases[payload] = line  # noqa: PTA104 (host-side analyzer code)
            elif kind == "rebind":
                aliases.pop(payload, None)  # noqa: PTA104 (host-side analyzer code)
            elif kind == "dispatch":
                dispatch_lines.append(line)  # noqa: PTA104 (host-side analyzer code)
            elif kind == "use":
                name, node = payload
                taken = aliases.get(name)
                if taken is None or name in flagged:
                    continue
                if any(taken < d < line for d in dispatch_lines):
                    flagged.add(name)  # noqa: PTA104 (host-side analyzer code)
                    self.em.emit(
                        "PTA303", node,
                        f"state leaf {name!r} (taken at line "
                        f"{taken + self.em.offset}) used after a donated "
                        f"dispatch in {self.fdef.name!r}: the dispatch "
                        "donated its buffer, so this reference is deleted",
                        hint="re-read the leaf from the post-dispatch state "
                             "(donation moves, it does not copy)")


# =====================================================================
# PTA305: unbounded host-state growth
# =====================================================================

class _LedgerGrowthPass:
    """Per-class: a ``self.<container>`` that GROWS in a method reachable
    from a serving-tick entry point (step/run/submit/…) and never shrinks
    anywhere in the class — the per-request ledger leak."""

    def __init__(self, em: _Emitter, cdef: ast.ClassDef):
        self.em = em
        self.cdef = cdef
        self.methods: Dict[str, ast.FunctionDef] = {
            n.name: n for n in cdef.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}

    def _reachable_from_ticks(self) -> Set[str]:
        roots = [n for n in self.methods if n in _TICK_METHODS]
        seen: Set[str] = set(roots)
        queue = list(roots)
        while queue:
            m = queue.pop()
            for sub in ast.walk(self.methods[m]):
                if isinstance(sub, ast.Call):
                    callee = _self_attr(sub.func)
                    if callee in self.methods and callee not in seen:
                        seen.add(callee)  # noqa: PTA104 (host-side analyzer code)
                        queue.append(callee)  # noqa: PTA104 (host-side analyzer code)
        return seen

    def _growth_sites(self, fdef):
        """(attr, node, how) growth sites on self.<attr> in one method."""
        for sub in ast.walk(fdef):
            if isinstance(sub, ast.Assign):
                for tgt in sub.targets:
                    if isinstance(tgt, ast.Subscript):
                        attr = _self_attr(tgt.value)
                        if attr is not None:
                            yield attr, sub, "setitem"
            elif isinstance(sub, ast.Call) and isinstance(sub.func, ast.Attribute):
                if sub.func.attr in _GROW_METHODS:
                    attr = _self_attr(sub.func.value)
                    if attr is not None:
                        yield attr, sub, sub.func.attr

    def _shrink_attrs(self) -> Set[str]:
        out: Set[str] = set()
        for name, fdef in self.methods.items():  # noqa: PTA102 (host-side analyzer code)
            for sub in ast.walk(fdef):
                if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Attribute):
                    if sub.func.attr in _SHRINK_METHODS:
                        attr = _self_attr(sub.func.value)
                        if attr is not None:
                            out.add(attr)  # noqa: PTA104 (host-side analyzer code)
                elif isinstance(sub, ast.Delete):
                    for tgt in sub.targets:
                        if isinstance(tgt, ast.Subscript):
                            attr = _self_attr(tgt.value)
                            if attr is not None:
                                out.add(attr)  # noqa: PTA104 (host-side analyzer code)
                        else:
                            attr = _self_attr(tgt)
                            if attr is not None:
                                out.add(attr)  # noqa: PTA104 (host-side analyzer code)
                elif isinstance(sub, ast.Assign) and name != "__init__":
                    for tgt in sub.targets:
                        attr = _self_attr(tgt)
                        if attr is not None:
                            out.add(attr)  # whole-container rebind = reset  # noqa: PTA104 (host-side analyzer code)
        return out

    def run(self):
        reachable = self._reachable_from_ticks()
        if not reachable:
            return
        shrinks = self._shrink_attrs()
        flagged: Set[str] = set()
        for mname in sorted(reachable):
            for attr, node, how in self._growth_sites(self.methods[mname]):  # noqa: PTA102 (host-side analyzer code)
                if attr in shrinks or attr in flagged:
                    continue
                flagged.add(attr)  # noqa: PTA104 (host-side analyzer code)
                self.em.emit(
                    "PTA305", node,
                    f"self.{attr} grows ({how}) in "
                    f"{self.cdef.name}.{mname}() — reachable from a serving "
                    "tick loop — and never shrinks anywhere in the class: "
                    "per-request host state leaks for the process lifetime",
                    hint="GC delivered entries past a keep-last-k bound "
                         "(see the fleet ledger GC)")


# =====================================================================
# frontends (mirror ast_lint)
# =====================================================================

def _collect_traced_names(tree) -> Set[str]:
    names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _terminal(node.func) in _TRACE_CALLS:
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, ast.Name):
                    names.add(arg.id)  # noqa: PTA104 (host-side analyzer code)
    return names


def _is_traced_def(fdef, traced_names: Set[str]) -> bool:
    if fdef.name in traced_names:
        return True
    for dec in fdef.decorator_list:
        if isinstance(dec, ast.Call):
            t = _terminal(dec.func)
            if t in _TRACED_DECORATORS:
                return True  # noqa: PTA101 (host-side analyzer code)
            if t == "partial" and any(
                    _terminal(a) in _TRACED_DECORATORS for a in dec.args):
                return True  # noqa: PTA101 (host-side analyzer code)
        elif _terminal(dec) in _TRACED_DECORATORS:
            return True  # noqa: PTA101 (host-side analyzer code)
    return False


def _seedish(fdef) -> bool:
    name = fdef.name.lower()
    return any(k in name for k in ("seed", "rng", "random"))


def check_source(src: str, filename: str = "<source>",
                 offset: int = 0) -> List[Diagnostic]:
    """Run every PTA3xx pass over one source blob. ``# noqa`` handling,
    sorting and the parse-failure code (PTA100) match :func:`.ast_lint.
    lint_source`."""
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [Diagnostic("PTA100", "error", f"source does not parse: {e.msg}",
                           file=filename, line=(e.lineno or 0) + offset,
                           col=e.offset)]
    em = _Emitter(filename, offset)
    traced_names = _collect_traced_names(tree)

    def visit(node, in_traced: bool):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                traced = in_traced or _is_traced_def(child, traced_names)
                if traced:
                    _TracedBodyPass(em, child).run()
                elif _seedish(child):
                    _TracedBodyPass(em, child,
                                    check_determinism_only=True).run()
                _RecompilePass(em, child).run()
                _DonationAliasPass(em, child).run()
                visit(child, traced)
            elif isinstance(child, ast.ClassDef):
                _LedgerGrowthPass(em, child).run()
                visit(child, in_traced)
            else:
                visit(child, in_traced)

    visit(tree, False)
    diags = em.diags
    noqa = _noqa_lines(src)
    if noqa:
        def suppressed(d: Diagnostic) -> bool:
            if d.line is None:
                return False
            codes = noqa.get(d.line - offset)
            if codes is None and (d.line - offset) not in noqa:
                return False
            return codes is None or d.code in codes

        diags = [d for d in diags if not suppressed(d)]
    diags.sort(key=lambda d: (d.line or 0, d.col or 0, d.code))
    return diags


def check_file(path: str) -> List[Diagnostic]:
    with open(path, "r", encoding="utf-8") as f:
        diags = check_source(f.read(), filename=path)
    # observability: pre-declared counters + a run-log event per dirty file
    # (the `observability report` hygiene section aggregates these)
    from ..observability import runlog as _runlog
    from ..observability.metrics import counter_inc as _counter_inc

    _counter_inc("hygiene.files_checked")
    if diags:
        _counter_inc("hygiene.findings", len(diags))
        _runlog.emit("hygiene", file=path, findings=len(diags),
                     codes=sorted({d.code for d in diags}))
    return diags


def check_module(name: str) -> List[Diagnostic]:
    """Analyze a module by dotted name WITHOUT importing (find_spec only)."""
    try:
        spec = importlib.util.find_spec(name)
    except (ImportError, ValueError):
        spec = None
    if spec is None or not spec.origin or not spec.origin.endswith(".py"):
        raise ValueError(f"cannot locate Python source for module {name!r}")
    return check_file(spec.origin)


def check_path(target: str) -> List[Diagnostic]:
    """Analyze a .py file, every .py under a directory, or a dotted module."""
    if os.path.isdir(target):
        diags: List[Diagnostic] = []
        for root, _dirs, files in os.walk(target):  # noqa: PTA102 (host-side analyzer code)
            for f in sorted(files):
                if f.endswith(".py"):
                    diags.extend(check_file(os.path.join(root, f)))  # noqa: PTA104 (host-side analyzer code)
        return diags
    if os.path.isfile(target) or target.endswith(".py"):
        return check_file(target)
    return check_module(target)
