"""Def-use dataflow graph over a recorded :class:`Program`.

Paddle parity: ``paddle/fluid/framework/ir/graph.h`` builds an SSA graph
(var nodes + op nodes) from the ProgramDesc for the ~190 IR passes. Here the
Program is already SSA — every ``SymbolicValue`` has exactly one producing
``Op`` (or is a feed), so the graph is two dicts keyed by value name plus
derived liveness/reachability queries. Passes (analysis/passes.py) consume
this instead of re-walking ``program.ops``.
"""
from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence, Set

#: feeds the runtime injects itself (Executor.run); never user-fed, never
#: reported as unused, excluded from dtype lint (uint32 plumbing).
RESERVED_FEEDS = ("__rng_key__", "__train_flag__")


class DefUseGraph:
    """Producer/consumer maps + liveness over one Program.

    - ``producers[name]`` -> index of the op producing value ``name``
    - ``consumers[name]`` -> indices of ops reading value ``name``
    - feeds appear only in ``consumers`` (no producing op)
    """

    def __init__(self, program):
        self.program = program
        self.ops = list(program.ops)
        self.producers: Dict[str, int] = {}
        self.consumers: Dict[str, List[int]] = {}
        for i, op in enumerate(self.ops):
            for sv in op.outputs:
                self.producers[sv.name] = i
            for kind, ref in op.inputs:
                if kind == "sym":
                    self.consumers.setdefault(ref.name, []).append(i)

    # ------------------------------------------------------------- queries
    def sink_ops(self) -> List[int]:
        """Ops none of whose outputs are read by another op."""
        return [i for i, op in enumerate(self.ops)
                if not any(sv.name in self.consumers for sv in op.outputs)]

    def root_names(self, fetch: Optional[Iterable[str]] = None) -> Set[str]:
        """Value names that must stay live: explicit fetch targets (or, when
        none are given, every sink output), plus the loss, named grads and
        deferred buffer writes the Executor commits after each run."""
        p = self.program
        roots: Set[str] = set()
        if fetch is not None:
            roots.update(fetch)
        else:
            for i in self.sink_ops():
                roots.update(sv.name for sv in self.ops[i].outputs)
        if getattr(p, "loss_var", None) is not None:
            roots.add(p.loss_var.name)
        roots.update(sv.name for sv in getattr(p, "grad_vars", {}).values())
        roots.update(sym.name for _, sym in getattr(p, "buffer_writes", []))
        return roots

    def live_ops(self, fetch: Optional[Iterable[str]] = None) -> Set[int]:
        """Indices of ops reachable (via def-use edges, walking backward)
        from the root set — the ops the Executor actually needs to run."""
        live: Set[int] = set()
        stack = [self.producers[n] for n in self.root_names(fetch)
                 if n in self.producers]
        while stack:
            i = stack.pop()
            if i in live:
                continue
            live.add(i)
            for kind, ref in self.ops[i].inputs:
                if kind == "sym" and ref.name in self.producers:
                    stack.append(self.producers[ref.name])
        return live

    def live_values(self, fetch: Optional[Iterable[str]] = None) -> Set[str]:
        """Names of feeds and op outputs read by any live op, plus the roots."""
        names = set(self.root_names(fetch))
        for i in self.live_ops(fetch):
            for kind, ref in self.ops[i].inputs:
                if kind == "sym":
                    names.add(ref.name)
        return names

    def unused_feeds(self) -> List[str]:
        """User feeds no op ever reads (reserved runtime feeds excluded)."""
        return [n for n in self.program.feeds
                if n not in RESERVED_FEEDS and n not in self.consumers]

    def consumers_of(self, name: str) -> List[int]:
        return list(self.consumers.get(name, ()))

    def producer_of(self, name: str) -> Optional[int]:
        return self.producers.get(name)
