"""Lowered-program (HLO) text parsing for the SPMD sharding analyzer.

The only artifact that shows what actually runs on the chips is the
post-SPMD-partitioning HLO of a compiled executable (``Compiled.as_text()``):
that is where GSPMD has already turned every sharding annotation into
concrete ``all-gather`` / ``all-reduce`` / ``all-to-all`` /
``collective-permute`` / ``reduce-scatter`` instructions with real shapes
and replica groups. This module extracts that collective schedule as
structured records — shapes, dtypes, group sizes, estimated bytes moved per
device, and the XLA ``metadata op_name`` naming the op that *forced* the
collective (a reshard inserted to feed a ``dot_general`` carries the dot's
op_name) — for the ``PTA2xx`` passes in :mod:`.spmd`.

Nothing here imports jax: the input is plain HLO text, so the parser also
serves the CLI (``python -m paddle_tpu.analysis --hlo dump.txt``) on files
produced by ``XLA_FLAGS=--xla_dump_to`` or ``Compiled.as_text()`` from any
process.
"""
from __future__ import annotations

import hashlib
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = [
    "HloCollective",
    "COLLECTIVE_KINDS",
    "parse_shapes",
    "shape_bytes",
    "parse_collectives",
    "collective_counts",
    "moved_bytes",
    "total_moved_bytes",
    "schedule_fingerprint",
    "entry_memory_lower_bound",
]

#: collective opcodes the SPMD partitioner inserts (async ``-start`` forms
#: included; their ``-done`` halves are bookkeeping and are skipped)
COLLECTIVE_KINDS = (
    "all-gather", "all-reduce", "reduce-scatter", "all-to-all",
    "collective-permute", "collective-broadcast",
)

_DTYPE_BYTES = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
    "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3b11fnuz": 1,
}

# one typed array shape: "f32[4,32,192]{1,0,2}" (layout suffix optional)
_SHAPE_RE = re.compile(r"\b([a-z]\d*[a-z0-9]*)\[([\d,]*)\](?:\{[^}]*\})?")
# one named instruction: "%name = <result-shape(s)> opcode(...)"
_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%([\w.\-]+)\s*=\s*(.*?)\s+"
    r"(" + "|".join(COLLECTIVE_KINDS) + r")(-start)?\(")
_GROUPS_LIST_RE = re.compile(r"replica_groups=\{\{([^=]*?)\}\}")
_GROUPS_IOTA_RE = re.compile(r"replica_groups=\[(\d+),(\d+)\]")
_CHANNEL_RE = re.compile(r"channel_id=(\d+)")
_OPNAME_RE = re.compile(r'op_name="([^"]*)"')
_SOURCE_RE = re.compile(r'source_file="([^"]*)"(?:\s+source_line=(\d+))?')
_PARAM_RE = re.compile(r"^\s*(?:ROOT\s+)?%[\w.\-]+\s*=\s*(.*?)\s+parameter\(\d+\)")


def parse_shapes(text: str) -> List[Tuple[str, Tuple[int, ...]]]:
    """Every typed array shape in ``text`` as ``(dtype, dims)`` — a tuple
    result like ``(f32[8]{0}, f32[8]{0})`` yields one entry per element."""
    out = []
    for dt, dims in _SHAPE_RE.findall(text):
        if dt not in _DTYPE_BYTES:
            continue  # opcode fragments that merely look like a dtype
        out.append((dt, tuple(int(d) for d in dims.split(",") if d)))
    return out


def shape_bytes(shapes: Sequence[Tuple[str, Tuple[int, ...]]]) -> int:
    total = 0
    for dt, dims in shapes:
        n = 1
        for d in dims:
            n *= d
        total += n * _DTYPE_BYTES.get(dt, 4)
    return total


@dataclass
class HloCollective:
    """One collective instruction lifted out of optimized HLO text."""

    kind: str                                   # e.g. "all-gather"
    name: str                                   # HLO instruction name
    index: int                                  # order within the module
    line: int                                   # 1-based line in the text
    result_shapes: List[Tuple[str, Tuple[int, ...]]] = field(default_factory=list)
    operand_shapes: List[Tuple[str, Tuple[int, ...]]] = field(default_factory=list)
    group_size: int = 1                         # devices per replica group
    num_groups: int = 1
    channel_id: Optional[int] = None
    op_name: str = ""                           # metadata: the forcing op
    source: str = ""                            # "file:line" when recorded

    @property
    def result_bytes(self) -> int:
        return shape_bytes(self.result_shapes)

    @property
    def operand_bytes(self) -> int:
        return shape_bytes(self.operand_shapes)

    def signature(self) -> str:
        """Order/shape fingerprint row: stable across ranks iff the rank
        compiled the same collective at the same schedule position."""
        shapes = ";".join(f"{dt}{list(dims)}" for dt, dims in self.result_shapes)
        return f"{self.kind}[g{self.group_size}x{self.num_groups}]({shapes})"

    def describe(self) -> str:
        loc = f" at {self.source}" if self.source else ""
        via = f" (inserted for {self.op_name.rsplit('/', 1)[-1]})" if self.op_name else ""
        return (f"{self.kind} '{self.name}' over {self.group_size}-device "
                f"groups, ~{moved_bytes(self):,} bytes moved per device per "
                f"dispatch{via}{loc}")


def _parse_groups(line: str) -> Tuple[int, int]:
    """(group_size, num_groups) from either replica-group spelling:
    explicit ``{{0,1},{2,3}}`` or iota ``[num_groups,group_size]<=[N]``."""
    m = _GROUPS_IOTA_RE.search(line)
    if m:
        return int(m.group(2)), int(m.group(1))
    m = _GROUPS_LIST_RE.search(line)
    if m:
        groups = [g for g in m.group(1).split("},{")]
        first = [t for t in groups[0].split(",") if t.strip()]
        return max(1, len(first)), max(1, len(groups))
    return 1, 1


def parse_collectives(hlo_text: str) -> List[HloCollective]:
    """Every collective instruction in ``hlo_text``, in program order.

    Works on the optimized (post-partitioning) module text; async pairs are
    collapsed onto their ``-start`` half so each transfer counts once.
    """
    out: List[HloCollective] = []
    for lineno, line in enumerate(hlo_text.splitlines(), 1):
        m = _INSTR_RE.match(line)
        if m is None:
            continue
        name, result, kind = m.group(1), m.group(2), m.group(3)
        # operands: everything inside the call parens, up to the attr list
        tail = line[m.end():]
        depth, end = 1, len(tail)
        for i, ch in enumerate(tail):
            if ch == "(":
                depth += 1
            elif ch == ")":
                depth -= 1
                if depth == 0:
                    end = i
                    break
        operands = tail[:end]
        gsz, ngr = _parse_groups(line)
        ch_m = _CHANNEL_RE.search(line)
        op_m = _OPNAME_RE.search(line)
        src_m = _SOURCE_RE.search(line)
        src = ""
        if src_m:
            src = src_m.group(1).rsplit("/", 1)[-1]
            if src_m.group(2):
                src += f":{src_m.group(2)}"
        out.append(HloCollective(
            kind=kind, name=name, index=len(out), line=lineno,
            result_shapes=parse_shapes(result),
            operand_shapes=parse_shapes(operands),
            group_size=gsz, num_groups=ngr,
            channel_id=int(ch_m.group(1)) if ch_m else None,
            op_name=op_m.group(1) if op_m else "",
            source=src))
    return out


def collective_counts(collectives: Sequence[HloCollective]) -> Dict[str, int]:
    counts: Dict[str, int] = {}
    for c in collectives:
        counts[c.kind] = counts.get(c.kind, 0) + 1
    return counts


def moved_bytes(c: HloCollective) -> int:
    """Estimated bytes moved per device for one execution of ``c``.

    Standard ring-algorithm accounting over a group of g devices:
    all-gather / reduce-scatter / all-to-all move (g-1)/g of the full
    buffer; all-reduce is a reduce-scatter + all-gather (2x); a permute
    sends the whole shard once. An estimate, not a measurement — but it is
    exact enough to rank reshards and to make "this PartitionSpec costs
    40 MB of gathers per step" a machine-checkable statement.
    """
    if c.kind in ("collective-permute", "collective-broadcast"):
        # point-to-point: groups are source_target_pairs, the shard moves once
        return int(c.result_bytes)
    g = max(1, c.group_size)
    if g == 1:
        return 0
    frac = (g - 1) / g
    if c.kind == "all-gather":
        return int(c.result_bytes * frac)
    if c.kind == "reduce-scatter":
        return int(c.operand_bytes * frac)
    if c.kind == "all-reduce":
        return int(2 * c.result_bytes * frac)
    if c.kind == "all-to-all":
        return int(c.result_bytes * frac)
    if c.kind in ("collective-permute", "collective-broadcast"):
        return int(c.result_bytes)
    return int(c.result_bytes)


def total_moved_bytes(collectives: Sequence[HloCollective]) -> int:
    return sum(moved_bytes(c) for c in collectives)


def schedule_fingerprint(collectives: Sequence[HloCollective]) -> str:
    """Digest of the ordered (kind, groups, shapes) sequence. Two ranks
    whose lowered programs would issue different collective sequences —
    the deadlock class ``diagnostic_barrier`` only catches after it hangs —
    get different fingerprints *before* dispatch."""
    h = hashlib.sha256()
    for c in collectives:
        h.update(c.signature().encode())
        h.update(b"\n")
    return h.hexdigest()


def entry_memory_lower_bound(hlo_text: str) -> int:
    """A cheap per-device memory floor from text alone: the entry
    computation's parameter buffers plus the largest single instruction
    result. The live-set peak is at least this; the real analyzer prefers
    ``Compiled.memory_analysis()`` and uses this only for ``--hlo`` files
    where no executable exists."""
    param_bytes = 0
    largest = 0
    in_entry = False
    for line in hlo_text.splitlines():
        if line.startswith("ENTRY"):
            in_entry = True
            continue
        if in_entry and line.startswith("}"):
            in_entry = False
        m = _PARAM_RE.match(line) if in_entry else None
        if m:
            param_bytes += shape_bytes(parse_shapes(m.group(1)))
            continue
        if in_entry and "=" in line:
            head = line.split("=", 1)[1]
            paren = head.find("(")
            largest = max(largest, shape_bytes(parse_shapes(
                head[:paren] if paren > 0 else head)))
    return param_bytes + largest
