"""Analysis passes over the Program IR, each emitting stable ``PTA0xx``
diagnostics.

Paddle parity: the reference feeds every ProgramDesc through an IR pass
framework (~190 graph passes, paddle/fluid/framework/ir/*) before the
Executor / AnalysisPredictor touch it. The optimizing passes are XLA's job
here; what this module keeps is the *diagnostic* half — the checks that catch
a wrong or wasteful graph before it compiles, with op/var names attached
instead of a runtime JAX traceback.

Registered passes (see README "Static analysis" for the full table):
  PTA001 dead op                 PTA005 baked dynamic dim (error)
  PTA002 unused feed             PTA006 duplicate computation (CSE)
  PTA003 implicit dtype promotion PTA007 oversized closed-over constant
  PTA004 f16/bf16 reduction (AMP hazard)
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

import numpy as np

from .diagnostics import Diagnostic
from .graph import RESERVED_FEEDS, DefUseGraph


class AnalysisContext:
    """Per-run knobs the passes read."""

    def __init__(self, fetch: Optional[List[str]] = None,
                 const_capture_threshold: int = 1 << 16):
        self.fetch = fetch
        # elements above which a closed-over constant is reported (PTA007);
        # 65536 f32 elements = 256 KiB baked into every compiled executable
        self.const_capture_threshold = const_capture_threshold


PassFn = Callable[[Any, DefUseGraph, AnalysisContext], Iterable[Diagnostic]]
_REGISTRY: Dict[str, Tuple[str, PassFn]] = {}


def register_pass(code: str, name: str):
    """Register an analysis pass under a stable diagnostic code."""

    def deco(fn: PassFn) -> PassFn:
        if code in _REGISTRY:
            raise ValueError(f"duplicate analysis pass code {code}")
        _REGISTRY[code] = (name, fn)
        return fn

    return deco


def registered_passes() -> Dict[str, str]:
    """code -> pass name, in registration order."""
    return {code: name for code, (name, _) in _REGISTRY.items()}


def _fetch_names(fetch) -> Optional[List[str]]:
    """Normalize a fetch list of Tensors / SymbolicValues / names."""
    if fetch is None:
        return None
    if not isinstance(fetch, (list, tuple, set)):
        fetch = [fetch]
    names = []
    for f in fetch:
        if isinstance(f, str):
            names.append(f)
            continue
        v = getattr(f, "_value", f)        # Tensor -> SymbolicValue
        name = getattr(v, "name", None)
        if name:
            names.append(name)
    return names


def analyze_program(program, fetch=None, passes: Optional[Iterable[str]] = None,
                    const_capture_threshold: int = 1 << 16) -> List[Diagnostic]:
    """Run the registered passes over ``program``; returns all diagnostics.

    ``fetch`` (names, Tensors or SymbolicValues) anchors liveness — without
    it every sink op counts as a result and the dead-op pass stays silent.
    ``passes`` restricts to a subset of codes.
    """
    ctx = AnalysisContext(fetch=_fetch_names(fetch),
                          const_capture_threshold=const_capture_threshold)
    graph = DefUseGraph(program)
    out: List[Diagnostic] = []
    for code, (name, fn) in _REGISTRY.items():
        if passes is not None and code not in passes:
            continue
        out.extend(fn(program, graph, ctx))
    return out


# --------------------------------------------------------------- dtype utils
def _np_dtype(dt):
    try:
        return np.dtype(dt)
    except TypeError:
        return None  # jax extended dtypes (PRNG keys) — not lintable


def _is_float(dt) -> bool:
    dt = _np_dtype(dt)
    return dt is not None and np.issubdtype(dt, np.floating)


def _is_int(dt) -> bool:
    dt = _np_dtype(dt)
    return (dt is not None and np.issubdtype(dt, np.integer)
            and dt != np.dtype(bool))


def _is_half(dt) -> bool:
    dt = _np_dtype(dt)
    if dt is None:
        return False
    return dt == np.float16 or dt.name == "bfloat16"


def _input_dtypes(op):
    """(dtype, description) per array-ish input; reserved runtime feeds and
    python scalars (weak-typed under JAX) are skipped."""
    out = []
    for kind, ref in op.inputs:
        if kind == "sym":
            if ref.name in RESERVED_FEEDS:
                continue
            out.append((ref.dtype, ref.name))
        elif kind == "tensor":
            v = getattr(ref, "_value", None)
            if v is not None and hasattr(v, "dtype"):
                out.append((v.dtype, getattr(ref, "name", None) or "tensor"))
        else:  # const: only concrete arrays carry a committed dtype
            if hasattr(ref, "dtype") and hasattr(ref, "shape"):
                out.append((ref.dtype, "const"))
    return out


# -------------------------------------------------------------------- passes
@register_pass("PTA001", "dead-op")
def _dead_op_pass(program, graph: DefUseGraph, ctx: AnalysisContext):
    """Ops not reachable from the fetch targets (needs an explicit fetch
    list to be meaningful — every sink is a root otherwise)."""
    live = graph.live_ops(ctx.fetch)
    for i, op in enumerate(graph.ops):
        if i in live:
            continue
        outs = ", ".join(sv.name for sv in op.outputs)
        yield Diagnostic(
            "PTA001", "warning",
            f"op #{i} is not reachable from the fetch targets "
            f"(outputs: {outs}); the Executor still traces and compiles it",
            hint="drop the dead call at build time, or add its output to "
                 "fetch_list if it was meant as a result",
            op=op.name, var=op.outputs[0].name if op.outputs else None)


@register_pass("PTA002", "unused-feed")
def _unused_feed_pass(program, graph: DefUseGraph, ctx: AnalysisContext):
    for name in graph.unused_feeds():
        yield Diagnostic(
            "PTA002", "warning",
            f"feed {name!r} is declared by static.data but never read by any op",
            hint="remove the static.data call (or stop passing the array — "
                 "unused feeds still ship host->device every run)",
            var=name)


# ops that legitimately mix integer and floating inputs (lookups/indexing/
# explicit conversions) — excluded from the int/float promotion lint
_INT_FLOAT_ALLOW = (
    "embedding", "gather", "take", "index", "one_hot", "lookup", "cast",
    "astype", "scatter", "where", "bincount", "unique", "topk", "sort",
    "searchsorted", "roll", "repeat", "tile", "pad", "interpolate", "slice",
    "put_along_axis", "dropout", "rng", "eye", "full", "arange", "linspace",
)


@register_pass("PTA003", "dtype-lint")
def _dtype_pass(program, graph: DefUseGraph, ctx: AnalysisContext):
    """Implicit precision changes at op boundaries: f32/f64 mixes, silently
    widened float64 outputs, and int/float promotion in arithmetic."""
    for op in graph.ops:
        ins = _input_dtypes(op)
        if not ins:
            continue
        name_l = op.name.lower()
        floats = [(d, n) for d, n in ins if _is_float(d)]
        ints = [(d, n) for d, n in ins if _is_int(d)]
        f32 = [n for d, n in floats if _np_dtype(d) == np.float32]
        f64 = [n for d, n in floats if _np_dtype(d) == np.float64]
        if f32 and f64:
            yield Diagnostic(
                "PTA003", "warning",
                f"mixes float32 ({', '.join(f32)}) and float64 "
                f"({', '.join(f64)}) inputs; XLA promotes to float64 "
                "(or silently downcasts when x64 is off)",
                hint="cast the float64 side explicitly (astype('float32')) "
                     "so the intent is recorded",
                op=op.name, var=f64[0])
            continue
        out_f64 = [sv for sv in op.outputs if _np_dtype(sv.dtype) == np.float64]
        if out_f64 and floats and not f64:
            yield Diagnostic(
                "PTA003", "warning",
                f"produces float64 {out_f64[0].name!r} from non-float64 "
                "inputs — an implicit widening (usually a stray numpy "
                "float64 constant)",
                hint="pin the constant/op dtype to float32",
                op=op.name, var=out_f64[0].name)
            continue
        if (ints and floats
                and any(_is_float(sv.dtype) for sv in op.outputs)
                and not any(tok in name_l for tok in _INT_FLOAT_ALLOW)):
            yield Diagnostic(
                "PTA003", "warning",
                f"mixes integer ({', '.join(n for _, n in ints)}) and "
                f"floating ({', '.join(n for _, n in floats)}) inputs; the "
                "integer side is promoted to float implicitly",
                hint="cast the integer input explicitly if the promotion is "
                     "intended",
                op=op.name, var=ints[0][1])


# op-name tokens that imply a many-to-few reduction whose accumulator
# precision matters
_REDUCTION_TOKENS = ("sum", "mean", "softmax", "logsumexp", "var", "std",
                     "norm", "prod", "cross_entropy", "cumsum", "logcumsumexp")


@register_pass("PTA004", "amp-reduction")
def _amp_reduction_pass(program, graph: DefUseGraph, ctx: AnalysisContext):
    """Reductions recorded at f16/bf16 end to end: the accumulator inherits
    the half dtype, so long sums lose precision (the AMP black-list exists
    for exactly these ops)."""
    for op in graph.ops:
        name_l = op.name.lower()
        if not any(tok in name_l for tok in _REDUCTION_TOKENS):
            continue
        ins = _input_dtypes(op)
        half_in = [n for d, n in ins if _is_half(d)]
        half_out = [sv for sv in op.outputs if _is_half(sv.dtype)]
        if half_in and half_out:
            dt = _np_dtype(half_out[0].dtype)
            yield Diagnostic(
                "PTA004", "warning",
                f"reduction runs in {dt.name if dt else 'half'} end to end "
                f"(inputs {', '.join(half_in)}); the accumulator loses "
                "precision on long reductions",
                hint="upcast to float32 before reducing and cast back "
                     "(the amp O1 black-list does this automatically)",
                op=op.name, var=half_out[0].name)


@register_pass("PTA005", "dynamic-dim-bake")
def _dynamic_dim_pass(program, graph: DefUseGraph, ctx: AnalysisContext):
    """The shape probe for a dynamic (-1) input dim failed on the second
    extent, so record_op kept the first probe's guess — the op's output
    shape may silently bake the placeholder extent in and go wrong the
    moment a real batch size differs from it."""
    from ..framework.static_trace import _DYN_PLACEHOLDER

    for i, op in enumerate(graph.ops):
        fb = getattr(op, "dyn_fallback", None)
        if not fb:
            continue
        shapes = ", ".join(str(tuple(sv.shape)) for sv in op.outputs)
        yield Diagnostic(
            "PTA005", "error",
            f"op #{i} consumes a dynamic (-1) dim but its shape fn rejected "
            f"the second probe extent ({fb}); output shape(s) {shapes} are "
            f"the first probe's guess and may bake the placeholder extent "
            f"{_DYN_PLACEHOLDER} in",
            hint="make the op shape-polymorphic over the dynamic dim (derive "
                 "sizes from x.shape instead of literals), or declare the "
                 "dim static in static.data",
            op=op.name, var=op.outputs[0].name if op.outputs else None)


# ------------------------------------------------- structural value numbering
def _const_key(ref):
    if isinstance(ref, (bool, int, float, complex, str, bytes, type(None))):
        return ("scalar", ref)
    if hasattr(ref, "shape") and hasattr(ref, "dtype"):
        try:
            arr = np.asarray(ref)
            if arr.size <= 4096:  # hash small constants by value
                return ("arr", arr.shape, str(arr.dtype), arr.tobytes())
        except Exception:
            pass
        return ("bigarr", tuple(getattr(ref, "shape", ())), id(ref))
    if isinstance(ref, (tuple, list)):
        return ("seq", type(ref).__name__, tuple(_const_key(x) for x in ref))
    return ("obj", id(ref))


def _cell_key(v):
    if isinstance(v, (bool, int, float, complex, str, bytes, type(None))):
        return v
    if isinstance(v, (tuple, list)):
        return tuple(_cell_key(x) for x in v)
    return ("obj", id(v))


def _fn_key(fn):
    """Structural identity of an op fn: shared code object + captured cell
    values. Two closures over the same def with equal captures compute the
    same function."""
    code = getattr(fn, "__code__", None)
    cells = ()
    closure = getattr(fn, "__closure__", None)
    if closure:
        cells = tuple(_cell_key(c.cell_contents) for c in closure)
    return (id(code) if code is not None else id(fn), cells)


def _kwargs_key(kwargs):
    return tuple(sorted((k, repr(v)[:256]) for k, v in kwargs.items()))


@register_pass("PTA006", "duplicate-computation")
def _duplicate_pass(program, graph: DefUseGraph, ctx: AnalysisContext):
    """Value numbering over (fn, attrs, input value numbers): two ops with
    identical structure recompute the same values — a CSE opportunity XLA
    only recovers when the duplicates land in one jit scope."""
    vn: Dict[str, Any] = {}
    table: Dict[Any, int] = {}
    for i, op in enumerate(graph.ops):
        in_keys = []
        for kind, ref in op.inputs:
            if kind == "sym":
                in_keys.append(vn.get(ref.name, ("feed", ref.name)))
            elif kind == "tensor":
                in_keys.append(("tensor", id(ref)))
            else:
                in_keys.append(_const_key(ref))
        key = (op.name, _fn_key(op.fn), _kwargs_key(op.kwargs), tuple(in_keys))
        try:
            hash(key)
        except TypeError:
            key = ("unhashable", i)
        if key in table:
            j = table[key]
            prev = graph.ops[j]
            # duplicates share value numbers so chains dedupe transitively
            for sv, psv in zip(op.outputs, prev.outputs):
                vn[sv.name] = vn[psv.name]
            yield Diagnostic(
                "PTA006", "warning",
                f"op #{i} recomputes op #{j} ('{prev.name}' -> "
                f"{prev.outputs[0].name if prev.outputs else '?'}): same fn, "
                "attrs and inputs",
                hint=f"reuse {prev.outputs[0].name if prev.outputs else 'its output'} "
                     "instead of re-recording the call",
                op=op.name, var=op.outputs[0].name if op.outputs else None)
        else:
            table[key] = i
            for k, sv in enumerate(op.outputs):
                vn[sv.name] = ("out", i, k)


@register_pass("PTA007", "oversized-capture")
def _capture_pass(program, graph: DefUseGraph, ctx: AnalysisContext):
    """Large arrays captured as ``const`` inputs are baked into every
    compiled executable as literals (one copy per feed-shape
    specialization) instead of being passed as runtime buffers."""
    thresh = ctx.const_capture_threshold
    for i, op in enumerate(graph.ops):
        for kind, ref in op.inputs:
            if kind != "const" or not (hasattr(ref, "shape") and hasattr(ref, "dtype")):
                continue
            size = int(np.prod(ref.shape)) if len(getattr(ref, "shape", ())) else 1
            if size <= thresh:
                continue
            nbytes = getattr(ref, "nbytes", size)
            yield Diagnostic(
                "PTA007", "warning",
                f"op #{i} closes over a constant array of {size} elements "
                f"(~{int(nbytes)} bytes, shape {tuple(ref.shape)}); it is "
                "baked into every compiled executable for this program",
                hint="pass it as a Tensor (runtime buffer, shared across "
                     "specializations) or feed it via static.data",
                op=op.name)
