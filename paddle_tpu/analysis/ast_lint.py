"""dy2static pre-flight linter: find the constructs the AST transpiler
documents as unsupported *before* tracing, with source line numbers.

Reference: the dygraph_to_static transformers
(python/paddle/fluid/dygraph/dygraph_to_static/*) silently leave unsupported
shapes untouched; when the offending condition is traced, the failure
surfaces deep inside JAX as ``TracerBoolConversionError`` with no pointer to
the user's line. This linter walks the Python AST (the same envelope checks
jit/dy2static.py applies while rewriting — its mutating-call tables are
imported, single source of truth) and reports each hazard as a ``PTA1xx``
:class:`Diagnostic` carrying file:line.

Codes:
  PTA100 syntax error (source does not parse)          [error]
  PTA101 return inside a loop                          [warning]
  PTA102 tuple-target for loop                         [warning]
  PTA103 break/continue inside try/with                [warning]
  PTA104 in-place mutation inside a conditional block  [warning]
  PTA105 side effect under trace (print/global store)  [info]

All of these run fine natively; they break only when the governing condition
or loop bound is a traced tensor — which is exactly when dy2static would have
needed to rewrite them and could not.
"""
from __future__ import annotations

import ast
import importlib.util
import inspect
import os
import textwrap
from typing import List, Optional

from .diagnostics import Diagnostic


def _mutating_tables():
    """dy2static's in-place-call envelope (lazy: keeps import order loose)."""
    from ..jit.dy2static import MUTATING_METHODS, is_inplace_call

    return MUTATING_METHODS, is_inplace_call


class _FunctionLinter:
    """Lints ONE function body. Nested defs/lambdas/classes are separate
    scopes (dy2static treats them so) and are linted on their own."""

    def __init__(self, diags: List[Diagnostic], filename: str, offset: int):
        self.diags = diags
        self.filename = filename
        self.offset = offset
        self.mutating, self.is_inplace_call = _mutating_tables()

    def emit(self, code, severity, node, message, hint=""):
        self.diags.append(Diagnostic(
            code, severity, message, hint=hint, file=self.filename,
            line=(node.lineno + self.offset) if hasattr(node, "lineno") else None,
            col=getattr(node, "col_offset", None)))

    def lint(self, fdef):
        for stmt in fdef.body:
            self._walk(stmt, loop=0, trywith=0, branch=0)

    # ------------------------------------------------------------- walking
    def _walk(self, node, loop: int, trywith: int, branch: int):
        """loop: enclosing loop count; trywith: try/with blocks entered
        *inside the innermost loop*; branch: enclosing If/While/For count."""
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return  # separate scope
        if isinstance(node, ast.Return):
            if loop:
                self.emit(
                    "PTA101", "warning", node,
                    "return inside a loop: dy2static cannot rewrite it; a "
                    "traced loop bound/condition dies as "
                    "TracerBoolConversionError here",
                    hint="assign to a result variable, break, and return "
                         "after the loop")
        elif isinstance(node, (ast.Break, ast.Continue)):
            if loop and trywith:
                kw = "break" if isinstance(node, ast.Break) else "continue"
                self.emit(
                    "PTA103", "warning", node,
                    f"{kw} inside try/with: dy2static refuses to relocate it "
                    "out of the handler block, so the loop is left unrewritten",
                    hint=f"move the {kw} out of the try/with (set a flag "
                         "inside, test it after)")
        elif isinstance(node, ast.For):
            if isinstance(node.target, (ast.Tuple, ast.List)):
                self.emit(
                    "PTA102", "warning", node,
                    "tuple-target for loop: dy2static only rewrites "
                    "`for <name> in range(...)`; traced iterables here fail "
                    "at trace time",
                    hint="iterate an index over range(len(...)) and unpack "
                         "inside the body")
            self._stmt_exprs(node.iter, branch)
            self._walk_block(node.body + node.orelse, loop + 1, 0, branch + 1,
                             node)
            return
        elif isinstance(node, (ast.While, ast.AsyncFor)):
            if isinstance(node, ast.While):
                self._stmt_exprs(node.test, branch)
            self._walk_block(node.body + node.orelse, loop + 1, 0, branch + 1,
                             node)
            return
        elif isinstance(node, ast.If):
            self._stmt_exprs(node.test, branch)
            self._walk_block(node.body + node.orelse, loop, trywith, branch + 1,
                             node)
            return
        elif isinstance(node, ast.Try):
            blocks = node.body + node.orelse + node.finalbody
            for h in node.handlers:
                blocks += h.body
            self._walk_block(blocks, loop, trywith + 1, branch, node)
            return
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            self._walk_block(node.body, loop, trywith + 1, branch, node)
            return
        elif isinstance(node, ast.Global):
            self.emit(
                "PTA105", "info", node,
                f"global store ({', '.join(node.names)}): runs once at trace "
                "time, not per execution of the compiled program",
                hint="return the value instead of writing a global")

        # statement-level expression scanning (mutations, prints); compound
        # statements not special-cased above (e.g. match) recurse instead so
        # nothing is scanned twice
        children = list(ast.iter_child_nodes(node))
        if any(isinstance(c, ast.stmt) for c in children):
            for child in children:
                if isinstance(child, ast.stmt):
                    self._walk(child, loop, trywith, branch)
        else:
            self._stmt_exprs(node, branch)

    def _walk_block(self, stmts, loop, trywith, branch, parent):
        for s in stmts:
            self._walk(s, loop, trywith, branch)

    # ------------------------------------------------- expression hazards
    def _stmt_exprs(self, node, branch: int):
        for sub in ast.walk(node):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef, ast.Lambda)):
                continue
            if branch and isinstance(sub, (ast.Subscript, ast.Attribute)) \
                    and isinstance(sub.ctx, ast.Store):
                kind = "subscript" if isinstance(sub, ast.Subscript) else "attribute"
                self.emit(
                    "PTA104", "warning", sub,
                    f"in-place {kind} store inside a conditional block: under "
                    "a traced predicate both branches execute at trace time, "
                    "so the mutation applies even when the branch is not taken",
                    hint="rebind a fresh value and merge it through the "
                         "branch outputs instead of mutating")
            elif branch and isinstance(sub, ast.Call) and self.is_inplace_call(sub):
                self.emit(
                    "PTA104", "warning", sub,
                    f"in-place call .{sub.func.attr}() inside a conditional "
                    "block: silently applied for the untaken branch when the "
                    "predicate is traced",
                    hint="use the out-of-place form and merge the result")
            elif (branch and isinstance(sub, ast.Expr)
                  and isinstance(sub.value, ast.Call)
                  and isinstance(sub.value.func, ast.Attribute)
                  and sub.value.func.attr in self.mutating):
                self.emit(
                    "PTA104", "warning", sub,
                    f"mutating call .{sub.value.func.attr}() inside a "
                    "conditional block: dy2static refuses to trace the "
                    "branch, and the mutation is wrong if it does trace",
                    hint="collect into a new container and merge it through "
                         "the branch outputs")
            elif (isinstance(sub, ast.Call) and isinstance(sub.func, ast.Name)
                  and sub.func.id == "print"):
                self.emit(
                    "PTA105", "info", sub,
                    "print() under trace runs once at trace time with "
                    "abstract values, not per execution",
                    hint="use paddle_tpu debugging hooks or fetch the value "
                         "and print outside the traced function")


# ------------------------------------------------------------------ frontends
_NOQA_RE = None  # compiled lazily (module import stays regex-free)


def _noqa_lines(src: str):
    """line number -> suppressed codes (None = all) from ``# noqa`` /
    ``# noqa: PTA104,PTA102`` comments — the standard opt-out for host-side
    code the linter cannot prove is never traced (e.g. a checkpoint-loading
    loop inside a model file)."""
    import re

    global _NOQA_RE
    if _NOQA_RE is None:
        _NOQA_RE = re.compile(r"#\s*noqa(?::\s*([A-Z0-9, ]+))?", re.IGNORECASE)
    out = {}
    for lineno, line in enumerate(src.splitlines(), 1):
        if "#" not in line:
            continue
        m = _NOQA_RE.search(line)
        if not m:
            continue
        codes = m.group(1)
        out[lineno] = (None if not codes
                       else {c.strip().upper() for c in codes.split(",") if c.strip()})
    return out


def lint_source(src: str, filename: str = "<source>", offset: int = 0) -> List[Diagnostic]:
    """Lint every function defined in ``src``; module-level code is skipped
    (it runs on the host exactly once and is never traced). A ``# noqa``
    comment on the flagged line suppresses its findings (``# noqa: PTA104``
    for one code, bare ``# noqa`` for all)."""
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [Diagnostic("PTA100", "error", f"source does not parse: {e.msg}",
                           file=filename, line=(e.lineno or 0) + offset,
                           col=e.offset)]
    diags: List[Diagnostic] = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _FunctionLinter(diags, filename, offset).lint(node)
    noqa = _noqa_lines(src)
    if noqa:
        def suppressed(d: Diagnostic) -> bool:
            if d.line is None:
                return False
            codes = noqa.get(d.line - offset)
            if codes is None and (d.line - offset) not in noqa:
                return False
            return codes is None or d.code in codes

        diags = [d for d in diags if not suppressed(d)]
    diags.sort(key=lambda d: (d.line or 0, d.col or 0, d.code))
    return diags


def lint_function(fn) -> List[Diagnostic]:
    """Lint one Python function (the ``to_static(lint=True)`` entry point).
    Reported line numbers match the function's defining file."""
    fn = inspect.unwrap(fn)
    fn = getattr(fn, "__func__", fn)  # bound method -> function
    code = getattr(fn, "__code__", None)
    if code is None:
        return []
    lines, start = inspect.getsourcelines(fn)
    src = textwrap.dedent("".join(lines))
    return lint_source(src, filename=code.co_filename or "<dy2static>",
                       offset=start - 1)


def lint_file(path: str) -> List[Diagnostic]:
    with open(path, "r", encoding="utf-8") as f:
        return lint_source(f.read(), filename=path)


def lint_module(name: str) -> List[Diagnostic]:
    """Lint a module by dotted name WITHOUT importing (find_spec only)."""
    try:
        spec = importlib.util.find_spec(name)
    except (ImportError, ValueError):  # missing parent package etc.
        spec = None
    if spec is None or not spec.origin or not spec.origin.endswith(".py"):
        raise ValueError(f"cannot locate Python source for module {name!r}")
    return lint_file(spec.origin)


def lint_path(target: str) -> List[Diagnostic]:
    """Lint a .py file, every .py under a directory, or a dotted module."""
    if os.path.isdir(target):
        diags: List[Diagnostic] = []
        for root, _dirs, files in os.walk(target):
            for f in sorted(files):
                if f.endswith(".py"):
                    diags.extend(lint_file(os.path.join(root, f)))
        return diags
    if os.path.isfile(target) or target.endswith(".py"):
        return lint_file(target)
    return lint_module(target)
