"""Diagnostic model shared by the Program IR passes and the dy2static linter.

Paddle parity: the reference's IR pass framework reports through
``paddle/fluid/framework/ir/pass.h`` + the inference analyzer's
``argument/analysis_passes``; error text there is free-form C++ ``LOG``
output. Here every finding is a structured :class:`Diagnostic` with a stable
``PTA`` code so tests, CI gates and editors can match on it.

Code space:
  PTA0xx — Program IR passes (paddle_tpu.analysis.passes)
  PTA1xx — dy2static pre-flight AST lint (paddle_tpu.analysis.ast_lint)
  PTA2xx — SPMD sharding analyzer over lowered programs
           (paddle_tpu.analysis.spmd / analysis.hlo)
  PTA3xx — dispatch-hygiene AST passes: host syncs, recompile hazards,
           donation aliasing, nondeterminism, unbounded host ledgers
           (paddle_tpu.analysis.hygiene; runtime half: analysis.sanitizer)
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Sequence

#: severity order, least to most severe
SEVERITIES = ("info", "warning", "error")


@dataclass
class Diagnostic:
    """One analysis finding: stable code, severity, location and a fix hint."""

    code: str                       # stable id, e.g. "PTA001"
    severity: str                   # "info" | "warning" | "error"
    message: str
    hint: str = ""
    op: Optional[str] = None        # recorded Op name (IR passes)
    var: Optional[str] = None       # SymbolicValue / feed name (IR passes)
    file: Optional[str] = None      # source file (AST lint)
    line: Optional[int] = None      # 1-based source line (AST lint)
    col: Optional[int] = None

    def __post_init__(self):
        if self.severity not in SEVERITIES:
            raise ValueError(f"unknown severity {self.severity!r}")

    @property
    def location(self) -> str:
        if self.file is not None:
            pos = f"{self.file}:{self.line}" if self.line is not None else self.file
            return pos if self.col is None else f"{pos}:{self.col}"
        parts = []
        if self.op:
            parts.append(f"op '{self.op}'")
        if self.var:
            parts.append(f"var '{self.var}'")
        return ", ".join(parts)

    def __str__(self):
        loc = self.location
        head = f"{self.code} [{self.severity}]"
        body = f"{loc}: {self.message}" if loc else self.message
        return f"{head} {body}" + (f" (hint: {self.hint})" if self.hint else "")


def max_severity(diagnostics: Sequence[Diagnostic]) -> Optional[str]:
    """Most severe level present, or None for an empty list."""
    worst = -1
    for d in diagnostics:
        worst = max(worst, SEVERITIES.index(d.severity))
    return SEVERITIES[worst] if worst >= 0 else None


def format_report(diagnostics: Sequence[Diagnostic]) -> str:
    """Human-readable multi-line report (one diagnostic per line + summary)."""
    if not diagnostics:
        return "no diagnostics"
    lines = [str(d) for d in diagnostics]
    counts = {s: sum(1 for d in diagnostics if d.severity == s) for s in SEVERITIES}
    summary = ", ".join(f"{n} {s}{'s' if n != 1 else ''}"
                        for s, n in counts.items() if n)
    lines.append(f"-- {len(diagnostics)} diagnostic(s): {summary}")
    return "\n".join(lines)


class ProgramAnalysisError(RuntimeError):
    """Raised (under ``FLAGS_static_check``) when error-severity diagnostics
    are found before a program compiles."""

    def __init__(self, diagnostics: Sequence[Diagnostic]):
        self.diagnostics = list(diagnostics)
        super().__init__(
            "static analysis found error-severity diagnostics:\n"
            + format_report(self.diagnostics))
