"""Runtime dispatch sanitizer (``FLAGS_sanitize``) — the dynamic half of
the PTA3xx dispatch-hygiene family (:mod:`.hygiene` is the static half).

The three bug classes that actually bit this repo live — and that no static
pass can prove absent — get runtime guards on every hot-path dispatch:

- **implicit host transfers** (:func:`transfer_scope`): the compiled-
  executable call runs under ``jax.transfer_guard("disallow")``, so a
  device->host readback (``float(arr)``, ``np.asarray`` on a device array)
  or an un-staged host->device upload smuggled into the dispatch raises
  with the offending op named instead of silently serializing the hot
  path. Intended transfers (feeding a numpy batch, reading results back)
  stay OUTSIDE the scope — callers make them explicit first.
- **recompile churn** (:func:`note_compile`): every ``_dispatch`` site
  records the signatures it compiled per logical callsite; more than
  ``FLAGS_sanitize_max_recompiles`` distinct signatures raises/warns a
  structured :class:`RecompileChurnError` naming the diffing aval — the
  machine-checked form of the few-compiled-programs invariant the tests
  pin by hand-written counter asserts.
- **donated-state aliasing** (:func:`check_state` / :func:`poison`):
  dispatching with a donated-and-deleted state leaf raises a structured
  :class:`StaleStateError` naming the leaf path *before* XLA's opaque
  deleted-buffer crash — the PR-10 bug class, extending the Executor's
  ``StaleHandleError`` story to TrainStep/DecodeEngine donated leaves.
- **host-ledger growth** (:func:`note_ledger`): the runtime counterpart of
  the PTA305 static pass — a per-request ledger on a serving tick loop
  growing past its configured bound warns (raises under strict).

Every trip emits a ``sanitizer`` run-log event and bumps a pre-declared
``sanitizer.*`` counter; the whole module is a no-op when ``FLAGS_sanitize``
is off (one dict lookup per dispatch).
"""
from __future__ import annotations

import contextlib
import threading
import warnings
from typing import Any, Dict, List, Tuple

from ..framework.flags import flag
from ..observability import runlog as _runlog
from ..observability.metrics import counter_inc

__all__ = [
    "enabled", "strict", "RecompileChurnError", "StaleStateError",
    "LedgerGrowthError", "transfer_scope", "note_compile", "check_state",
    "poison", "sweep_tensors", "note_ledger", "reset", "stats",
]


def enabled() -> bool:
    return bool(flag("FLAGS_sanitize"))


def strict() -> bool:
    return bool(flag("FLAGS_sanitize_strict"))


# =====================================================================
# structured errors
# =====================================================================

class RecompileChurnError(RuntimeError):
    """One logical dispatch callsite compiled more distinct signatures than
    ``FLAGS_sanitize_max_recompiles`` — the shape/dtype of some argument is
    churning per call, so every dispatch pays a fresh XLA compile.
    ``diff`` names the aval that changed between the last two signatures."""

    def __init__(self, component: str, callsite: str, count: int,
                 limit: int, diff: str):
        self.component = component
        self.callsite = callsite
        self.count = int(count)
        self.limit = int(limit)
        self.diff = diff
        super().__init__(
            f"recompile churn at {component}[{callsite}]: {count} distinct "
            f"signatures compiled (> FLAGS_sanitize_max_recompiles={limit}); "
            f"{diff}. Pad/bucket the churning argument or lift it out of the "
            f"traced signature.")


class StaleStateError(RuntimeError):
    """A donated state leaf was reused after its buffer was deleted. The
    structured pre-flight form of XLA's deleted-buffer crash: ``leaf``
    names the offending tree path so the aliasing bug is one grep away."""

    def __init__(self, component: str, leaf: str, label: str = ""):
        self.component = component
        self.leaf = leaf
        self.label = label
        where = f"{component}[{label}]" if label else component
        super().__init__(
            f"stale donated state at {where}: leaf {leaf!r} references a "
            f"deleted (donated) buffer. The dispatch donated this leaf and "
            f"the live value moved to the returned state — refresh the held "
            f"reference instead of reusing the donated one.")


class LedgerGrowthError(RuntimeError):
    """A per-request host ledger on a serving tick loop grew past its
    configured bound — the runtime form of the PTA305 static finding."""

    def __init__(self, component: str, ledger: str, size: int, bound: int):
        self.component = component
        self.ledger = ledger
        self.size = int(size)
        self.bound = int(bound)
        super().__init__(
            f"unbounded host-state growth at {component}.{ledger}: "
            f"{size} entries > bound {bound}. Delivered requests must be "
            f"GC'd past keep-last-k or the serving process leaks per-request "
            f"state forever.")


# =====================================================================
# transfer guard
# =====================================================================

@contextlib.contextmanager
def transfer_scope(label: str):
    """Scope ``jax.transfer_guard("disallow")`` around one hot-path
    dispatch. Implicit device<->host transfers inside raise (jax names the
    offending transfer); explicit ``jax.device_put``/``device_get`` stay
    allowed. No-op when the sanitizer is off or this jax build has no
    transfer guard."""
    if not enabled():
        yield
        return
    import jax

    try:
        guard = jax.transfer_guard("disallow")
    except Exception:  # older jax: no guard — sanitizer degrades gracefully
        yield
        return
    try:
        with guard:
            yield
    except Exception as exc:
        if "transfer" in str(exc).lower():
            counter_inc("sanitizer.host_transfers")
            _runlog.emit("sanitizer", kind="host_transfer", label=label,
                         error=f"{type(exc).__name__}: {exc}")
        raise


def explicit_device(tree):
    """Make the intended host->device upload of ``tree``'s numpy leaves
    explicit (``jnp.asarray``) so the dispatch itself runs transfer-clean
    under :func:`transfer_scope`. Device arrays pass through untouched."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    def _put(leaf):
        if isinstance(leaf, (np.ndarray, np.generic, int, float, bool)):
            return jnp.asarray(leaf)
        return leaf

    return jax.tree_util.tree_map(_put, tree)


# =====================================================================
# recompile-churn sentinel
# =====================================================================

# (component, callsite) -> ordered list of distinct signatures compiled
_SIGS: Dict[Tuple[str, str], List[Any]] = {}
_LOCK = threading.Lock()


def _describe(v: Any) -> str:
    if (isinstance(v, tuple) and len(v) == 2 and isinstance(v[0], tuple)
            and isinstance(v[1], str)):
        shape, dtype = v
        return f"{dtype}[{','.join(str(d) for d in shape)}]"
    return repr(v)


def _diff_sigs(prev: Any, cur: Any) -> str:
    """Name the first aval that differs between two signatures — the
    churning argument the error message must point at."""
    pt = prev if isinstance(prev, tuple) else (prev,)
    ct = cur if isinstance(cur, tuple) else (cur,)
    for i in range(max(len(pt), len(ct))):
        a = pt[i] if i < len(pt) else "<absent>"
        b = ct[i] if i < len(ct) else "<absent>"
        if a != b:
            return (f"diffing aval: arg {i} changed "  # noqa: PTA101 (host-side sanitizer code)
                    f"{_describe(a)} -> {_describe(b)}")
    return "diffing aval: signature count differs but no leaf diff found"


def note_compile(component: str, callsite: str, sig: Any) -> None:
    """Record one fresh compile at a logical dispatch callsite. Callers
    invoke this ONLY on a specialization-cache miss; over
    ``FLAGS_sanitize_max_recompiles`` distinct signatures the sentinel
    warns (raises under ``FLAGS_sanitize_strict``) with the diffing aval
    named."""
    if not enabled():
        return
    key = (component, str(callsite))
    with _LOCK:
        sigs = _SIGS.setdefault(key, [])
        if sig in sigs:
            return
        sigs.append(sig)
        count = len(sigs)
        prev = sigs[-2] if count > 1 else None
    counter_inc("sanitizer.compiles_seen")
    limit = int(flag("FLAGS_sanitize_max_recompiles"))
    if limit <= 0 or count <= limit:
        return
    diff = _diff_sigs(prev, sig)
    err = RecompileChurnError(component, str(callsite), count, limit, diff)
    counter_inc("sanitizer.recompile_churn")
    _runlog.emit("sanitizer", kind="recompile_churn", component=component,
                 callsite=str(callsite), signatures=count, limit=limit,
                 diff=diff)
    if strict():
        raise err
    warnings.warn(str(err), RuntimeWarning, stacklevel=3)


# =====================================================================
# donated-state poisoning
# =====================================================================

def check_state(component: str, tree, label: str = "") -> None:
    """Raise :class:`StaleStateError` naming the leaf path if any leaf of
    ``tree`` references a deleted (donated) buffer — BEFORE the dispatch
    hands it to XLA and crashes with an opaque deleted-buffer error."""
    if not enabled():
        return
    import jax

    for path, leaf in jax.tree_util.tree_leaves_with_path(tree):  # noqa: PTA102 (host-side sanitizer code)
        deleted = getattr(leaf, "is_deleted", None)
        if deleted is not None and deleted():
            counter_inc("sanitizer.stale_state")
            name = jax.tree_util.keystr(path)
            _runlog.emit("sanitizer", kind="stale_state", component=component,
                         leaf=name, label=label)
            raise StaleStateError(component, name, label)


class _PoisonedArray:
    """Replacement for a Tensor ``_value`` whose buffer a dispatch donated:
    ANY use raises the structured :class:`StaleStateError` instead of an
    XLA deleted-buffer crash (the same protocol as the Executor's
    ``_StaleArray``, extended to TrainStep/DecodeEngine donated leaves)."""

    __slots__ = ("_err",)

    def __init__(self, err: StaleStateError):
        object.__setattr__(self, "_err", err)

    def _raise(self, *a, **k):
        raise object.__getattribute__(self, "_err")

    def __getattr__(self, name):
        self._raise()

    __array__ = __repr__ = __len__ = __iter__ = __bool__ = _raise
    __add__ = __radd__ = __mul__ = __rmul__ = __getitem__ = _raise


def poison(component: str, leaf_name: str, label: str = "") -> _PoisonedArray:
    return _PoisonedArray(StaleStateError(component, leaf_name, label))


def sweep_tensors(component: str, named_tensors, label: str = "") -> int:
    """After a donating dispatch: replace every Tensor ``_value`` that now
    references a deleted buffer with a poison that raises a structured
    :class:`StaleStateError` on any use. ``named_tensors`` yields
    ``(name, tensor)``. Returns the number of leaves poisoned."""
    if not enabled():
        return 0
    n = 0
    for name, t in named_tensors:  # noqa: PTA102 (host-side sanitizer code)
        v = getattr(t, "_value", None)
        if isinstance(v, _PoisonedArray):  # swept on an earlier dispatch
            continue
        deleted = getattr(v, "is_deleted", None)
        if deleted is not None and deleted():
            t._value = poison(component, name, label)  # noqa: PTA104 (host-side sanitizer code)
            n += 1
    if n:
        counter_inc("sanitizer.leaves_poisoned", n)
        _runlog.emit("sanitizer", kind="poisoned", component=component,
                     leaves=n, label=label)
    return n


# =====================================================================
# host-ledger growth sentinel (runtime PTA305)
# =====================================================================

_LEDGER_WARNED: set = set()


def note_ledger(component: str, ledger: str, size: int, bound: int) -> None:
    """Runtime PTA305: a per-request host ledger on a serving tick loop
    exceeding ``bound`` entries warns once per ledger (raises under
    ``FLAGS_sanitize_strict``). Fleet/scheduler keep-last-k GC keeps
    bounded ledgers far below this."""
    if not enabled() or bound <= 0 or size <= bound:
        return
    counter_inc("sanitizer.ledger_growth")
    key = (component, ledger)
    err = LedgerGrowthError(component, ledger, size, bound)
    if key not in _LEDGER_WARNED:
        _LEDGER_WARNED.add(key)  # noqa: PTA104 (host-side sanitizer code)
        _runlog.emit("sanitizer", kind="ledger_growth", component=component,
                     ledger=ledger, size=int(size), bound=int(bound))
    if strict():
        raise err
    warnings.warn(str(err), RuntimeWarning, stacklevel=3)


# =====================================================================
# bookkeeping
# =====================================================================

def reset() -> None:
    """Drop all per-callsite signature history and ledger warn state
    (tests; a fresh serving process starts clean by construction)."""
    with _LOCK:
        _SIGS.clear()
    _LEDGER_WARNED.clear()


def stats() -> Dict[str, Any]:
    with _LOCK:
        return {f"{c}[{s}]": len(v) for (c, s), v in _SIGS.items()}
