"""paddle.callbacks namespace (reference python/paddle/callbacks/__init__.py)
— re-exports the hapi callback protocol."""
from .hapi.callbacks import (  # noqa: F401
    Callback,
    EarlyStopping,
    LRScheduler,
    ModelCheckpoint,
    ProgBarLogger,
)

try:  # optional members, mirrors reference availability
    from .hapi.callbacks import ReduceLROnPlateau, VisualDL  # noqa: F401
except ImportError:
    pass
