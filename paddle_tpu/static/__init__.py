"""paddle_tpu.static — static-graph frontend.

Paddle parity: ``paddle.static`` (reference python/paddle/static/__init__.py;
Program python/paddle/fluid/framework.py:4795; Executor
python/paddle/fluid/executor.py:1108; append_backward
python/paddle/fluid/backward.py:1555; save/load_inference_model
python/paddle/fluid/io.py). TPU-first: the Program records primitive calls
(framework/static_trace.py), ``Executor.run`` compiles the whole program —
forward, backward (jax.value_and_grad ≈ append_backward's grad-op emission)
and optimizer update — into ONE jitted XLA computation, which is the
new_executor/InterpreterCore and ParallelExecutor path collapsed into the XLA
scheduler. ``save_inference_model`` serializes StableHLO via jax.export
instead of a ProgramDesc protobuf.
"""
from __future__ import annotations

import contextlib
import pickle
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, _wrap_value, unwrap
from ..framework.dtype import to_jax_dtype
from ..framework.scope import Scope, Variable as ScopeVariable, global_scope, scope_guard
from ..framework.static_trace import (
    Program,
    SymbolicValue,
    current_program,
    is_symbolic,
    pop_program,
    push_program,
)

__all__ = [
    "Program", "program_guard", "default_main_program", "default_startup_program",
    "data", "Executor", "append_backward", "CompiledProgram", "InputSpec",
    "save_inference_model", "load_inference_model", "enable_static",
    "disable_static", "in_dynamic_mode", "gradients", "name_scope", "py_func",
    "global_scope", "scope_guard", "Scope", "StaleHandleError",
    "NonFiniteError",
]

_default_main = Program()
_default_startup = Program()
_static_enabled = False


def default_main_program() -> Program:
    return _default_main


def default_startup_program() -> Program:
    return _default_startup


def enable_static() -> None:
    """paddle.enable_static parity: subsequent ops record into the default
    main program instead of executing eagerly."""
    global _static_enabled
    if not _static_enabled:
        push_program(_default_main)
        _static_enabled = True


def disable_static() -> None:
    global _static_enabled
    if _static_enabled:
        pop_program()
        _static_enabled = False


def in_dynamic_mode() -> bool:
    return current_program() is None


@contextlib.contextmanager
def program_guard(main_program: Program, startup_program: Optional[Program] = None):
    """Route op recording into ``main_program`` (reference
    fluid.program_guard). ``startup_program`` is accepted for parity; params
    initialize eagerly on creation, so startup is an empty program."""
    push_program(main_program)
    try:
        yield
    finally:
        pop_program()


@contextlib.contextmanager
def name_scope(prefix: str):  # cosmetic parity; names are per-program unique
    yield


def data(name: str, shape: Sequence[int], dtype: str = "float32", lod_level: int = 0) -> Tensor:
    """Feed placeholder (reference paddle.static.data). ``shape`` may use
    None/-1 for the batch dim; it is resolved at the first Executor.run from
    the fed array (static shapes are an XLA requirement — a new batch shape
    triggers a fresh compile, matching jit semantics)."""
    prog = current_program()
    if prog is None:
        raise RuntimeError("static.data requires enable_static() or a program_guard")
    shape = tuple(-1 if s is None else int(s) for s in shape)
    sv = prog.add_feed(name, shape, to_jax_dtype(dtype))
    t = _wrap_value(sv, stop_gradient=True)
    t.name = name
    return t


def append_backward(loss: Tensor, parameter_list=None, no_grad_set=None):
    """Register grad computation for ``loss``; returns [(param, grad_var)].

    Reference fluid/backward.py:1555 walks ops in reverse emitting grad ops;
    here the backward graph comes from jax.value_and_grad at run time over the
    recorded forward, so this only names the grad outputs."""
    prog = current_program() or _default_main
    if not (isinstance(loss, Tensor) and is_symbolic(loss._value)):
        raise TypeError("append_backward expects a symbolic loss Variable from this program")
    prog.loss_var = loss._value
    params = list(parameter_list) if parameter_list else prog.all_parameters()
    if no_grad_set:
        excluded = {id(t) for t in no_grad_set}
        params = [p for p in params if id(p) not in excluded]
    ref_ids = {id(x) for x in prog.tensor_refs()}
    out = []
    for i, p in enumerate(params):
        if id(p) not in ref_ids:
            continue
        gname = f"{p.name or f'param_{i}'}@GRAD"
        sv = SymbolicValue(tuple(p._value.shape), p._value.dtype, gname)
        prog.grad_vars[id(p)] = sv
        gv = _wrap_value(sv, stop_gradient=True)
        gv.name = gname
        out.append((p, gv))
    return out


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """paddle.static.gradients parity (single target)."""
    t = targets[0] if isinstance(targets, (list, tuple)) else targets
    pg = append_backward(t, parameter_list=list(inputs) if isinstance(inputs, (list, tuple)) else [inputs])
    return [g for _, g in pg]


class CompiledProgram:
    """Parity shim (reference compiler.py CompiledProgram / ParallelExecutor):
    jit compilation happens in Executor.run regardless; this only carries the
    program through the same API shape."""

    def __init__(self, program: Program, build_strategy=None):
        self._program = program


class InputSpec:
    def __init__(self, shape, dtype="float32", name=None):
        self.shape = tuple(-1 if s is None else int(s) for s in shape)
        self.dtype = to_jax_dtype(dtype)
        self.name = name


class StaleHandleError(RuntimeError):
    """A handle fetched from ``Executor.run`` whose device buffer was since
    donated back to the compiled program (``FLAGS_executor_donate``)."""


class NonFiniteError(FloatingPointError):
    """``FLAGS_check_nan_inf`` on the jitted Executor path: a fetched output
    or gradient came back NaN/Inf. Carries the FIRST offending variable name
    (``.name``) — the finite checks are fused into the compiled program
    (one reduction per checked var, no extra dispatch); only the tiny flag
    scalars sync to host."""

    def __init__(self, name: str, kind: str = "fetch"):
        self.name = name
        self.kind = kind
        super().__init__(
            f"Executor.run: {kind} variable {name!r} contains NaN/Inf "
            "(FLAGS_check_nan_inf is set; the check is fused into the "
            "compiled program)")


class _StaleArray:
    """Poison value installed into Tensors whose buffer a donated run
    consumed: any use (shape/dtype/np.asarray/ops) raises StaleHandleError
    with the donation story instead of XLA's opaque deleted-buffer crash."""

    __slots__ = ("_msg",)

    def __init__(self, msg):
        object.__setattr__(self, "_msg", msg)

    def __getattr__(self, name):
        raise StaleHandleError(object.__getattribute__(self, "_msg"))

    def __array__(self, dtype=None, copy=None):
        raise StaleHandleError(object.__getattribute__(self, "_msg"))

    def __repr__(self):
        return "<stale donated handle>"


class _RunPlan:
    """Per-specialization run plan: everything ``Executor.run`` previously
    recomputed every call — param/other Tensor lists, the compiled fn, and
    the scope-publish targets — resolved once at build time so the per-run
    hot path is: read feed arrays, call, write back."""

    __slots__ = ("fn", "params", "others", "train", "donate", "check",
                 "scope", "param_vars", "fetch_vars", "compiled", "cost",
                 "label", "spmd", "shard_error")

    def __init__(self, fn, params, others, train, donate, label="", check=False):
        self.fn = fn
        self.params = params
        self.others = others
        self.train = train
        self.donate = donate
        self.check = check         # FLAGS_check_nan_inf fused finite checks
        self.scope = None          # scope the publish targets below belong to
        self.param_vars = ()       # [(param Tensor, scope Variable)]
        self.fetch_vars = {}       # fetch name -> scope Variable
        self.compiled = None       # AOT XLA executable (set at first run)
        self.cost = None           # observability.cost_summary of `compiled`
        self.label = label         # human-readable specialization id
        self.spmd = None           # FLAGS_shard_check verdict (SpmdReport summary)
        self.shard_error = None    # sticky PTA2xx error: re-raised every run

    def bind_scope(self, gs, fetch_names):
        if self.scope is not gs:
            self.scope = gs
            self.param_vars = tuple((p, gs.var(p.name)) for p in self.params
                                    if getattr(p, "name", None))
            self.fetch_vars = {n: gs.var(n) for n in fetch_names if n}


class Executor:
    """Compiles and runs Programs (reference executor.py:1108 Executor.run →
    here: one jax.jit per (program version, feed/fetch signature) cached like
    _ExecutorCache; parameter/optimizer state round-trips through the concrete
    Tensors so eager code observes static updates and vice versa).

    Hot-path overhead is amortized per specialization: a cached
    :class:`_RunPlan` keeps the param/other id lists and scope-publish
    targets, so a cache-hit ``run`` does no program walking and no
    ``gs.var`` lookups. With ``FLAGS_executor_donate`` training runs donate
    ``param_vals`` and the optimizer state into the compiled program
    (``donate_argnums``) — parameter memory stays flat — and any previously
    fetched handle aliasing a donated buffer is invalidated to raise
    :class:`StaleHandleError` on use. ``return_numpy=False`` fetches return
    device-resident Tensors without forcing a host sync. Dispatch accounting
    (runs / cache_hits / cache_misses / compiles / donated_runs) is exported
    via ``paddle_tpu.profiler.counters('executor.')``."""

    # compiled programs kept per executor; beyond this LRU bound the oldest
    # recompiles on next use (varying feed shapes would otherwise accumulate
    # jitted programs without bound — reference _ExecutorCache is similarly
    # bounded by program identity)
    _CACHE_CAPACITY = 64

    def __init__(self, place=None):
        import collections

        self.place = place
        self._cache: "collections.OrderedDict[tuple, _RunPlan]" = collections.OrderedDict()
        # keyed (prog.id, param-identity tuple); at most one live entry per
        # program — growing a program evicts its stale state
        self._opt_states: Dict[tuple, Any] = {}
        # (prog.id, version) -> feed names actually consumed by the ops
        self._feed_use: Dict[tuple, set] = {}
        # weakrefs to device-handle Tensors returned while donation is on;
        # a donated run sweeps these and poisons the ones it consumed
        self._fetch_watch: list = []

    def run(self, program: Optional[Program] = None, feed: Optional[Dict[str, Any]] = None,
            fetch_list: Optional[List] = None, return_numpy: bool = True):
        from ..analysis import sanitizer as _sanitizer
        from ..framework.flags import flag as _flag
        from ..observability import span as _span
        from ..profiler import counter_inc

        counter_inc("executor.runs")
        prog = program if program is not None else _default_main
        if isinstance(prog, CompiledProgram):
            prog = prog._program
        feed = feed or {}
        fetch_list = fetch_list or []
        fetch_names = []
        passthrough: Dict[int, Tensor] = {}
        for i, f in enumerate(fetch_list):
            if isinstance(f, Tensor) and is_symbolic(f._value):
                fetch_names.append(f._value.name)
            elif isinstance(f, str):
                fetch_names.append(f)
            elif isinstance(f, Tensor):
                passthrough[i] = f  # concrete (e.g. a parameter): return as-is
                fetch_names.append(None)
            else:
                raise TypeError(f"fetch item {f!r} is not a Variable or name")

        if not prog.ops:  # startup-program case: params already initialized
            symbolic_fetches = [n for n in fetch_names if n is not None]
            if symbolic_fetches:
                raise ValueError(
                    f"cannot fetch {symbolic_fetches} from a program with no ops "
                    "(did you mean to run the main program?)")
            return [np.asarray(passthrough[i]._value) for i in range(len(fetch_list))]

        feed_arrays = {k: jnp.asarray(unwrap(v)) for k, v in feed.items()}
        if "__rng_key__" in prog.feeds:  # per-run dropout/rng seed (never user-fed)
            self._run_counter = getattr(self, "_run_counter", 0) + 1
            feed_arrays["__rng_key__"] = jnp.uint32(self._run_counter)
        if "__train_flag__" in prog.feeds:  # clone(for_test=True) flips to 0
            feed_arrays["__train_flag__"] = jnp.uint32(0 if getattr(prog, "for_test", False) else 1)
        missing = set(prog.feeds) - set(feed_arrays)
        if missing:
            use_key = (prog.id, prog.version)
            used_feeds = self._feed_use.get(use_key)
            if used_feeds is None:  # computed once per program version
                used_feeds = {n for op in prog.ops for kind, ref in op.inputs
                              if kind == "sym" for n in [ref.name] if n in prog.feeds}
                self._feed_use[use_key] = used_feeds  # noqa: PTA305 (keyed by (program, fetch) — bounded by program count, not request count)
            if missing & used_feeds:
                raise ValueError(f"missing feeds: {sorted(missing & used_feeds)}")

        train = prog.optimizer is not None or bool(prog.grad_vars)
        opt = prog.optimizer
        donate = (bool(_flag("FLAGS_executor_donate")) and train
                  and opt is not None and prog.loss_var is not None)
        check = bool(_flag("FLAGS_check_nan_inf"))

        with _span("executor.plan_lookup"):
            feed_sig = tuple(sorted((k, v.shape, str(v.dtype)) for k, v in feed_arrays.items()))
            key = (prog.id, prog.version, feed_sig, tuple(fetch_names), train, donate, check)
            plan = self._cache.get(key)
            if plan is None:
                counter_inc("executor.cache_misses")
                # recompile-churn sentinel: the callsite is the logical
                # (program, fetch) pair — feed shapes churning per run at a
                # fixed callsite is the pay-a-compile-every-step bug
                _sanitizer.note_compile(
                    "executor",
                    f"prog{prog.id}.v{prog.version}"
                    f"/{','.join(n or '_' for n in fetch_names)}",
                    feed_sig)
                if _flag("FLAGS_static_check"):
                    # pre-flight the program once per compiled specialization:
                    # warnings surface through the warnings module, error-severity
                    # diagnostics (e.g. a baked dynamic dim) abort before compile
                    self._static_check(prog, [n for n in fetch_names if n])
                refs = prog.tensor_refs()
                if train and prog.grad_vars:
                    # append_backward already applied parameter_list/no_grad_set
                    params = [t for t in refs if id(t) in prog.grad_vars]
                elif train:
                    params = [t for t in refs if not t.stop_gradient]
                else:
                    params = []
                param_ids = {id(t) for t in params}
                others = [t for t in refs if id(t) not in param_ids]
                fn = self._build(prog, tuple(sorted(feed_arrays)), fetch_names,
                                 params, others, train, donate, check=check)
                label = (f"prog{prog.id}.v{prog.version}"
                         + ("/train" if train else "/infer")
                         + ("/donated" if donate else "")
                         + "/" + ",".join(f"{k}{list(s)}" for k, s, _ in feed_sig))
                plan = self._cache[key] = _RunPlan(fn, tuple(params), tuple(others),
                                                  train, donate, label=label,
                                                  check=check)
                while len(self._cache) > self._CACHE_CAPACITY:
                    self._cache.popitem(last=False)  # LRU eviction
            else:
                counter_inc("executor.cache_hits")
                self._cache.move_to_end(key)
        params = plan.params

        # keyed by param identity too: appending ops/params to the program
        # after a trained run must rebuild the state, not pair the stale
        # pytree with a different params list
        opt_key = (prog.id, tuple(id(p) for p in params))
        if train and opt is not None and opt_key not in self._opt_states:
            for stale in [k for k in self._opt_states if k[0] == prog.id]:
                del self._opt_states[stale]
            ptree = {i: p._value for i, p in enumerate(params)}
            self._opt_states[opt_key] = {"opt": opt.core.init(ptree),
                                         "step": jnp.zeros((), jnp.int32)}
        state = self._opt_states.get(opt_key) if train and opt is not None else None

        param_vals = tuple(p._value for p in params)
        other_vals = tuple(t._value for t in plan.others)
        donated_ids = None
        if donate:
            donated_ids = {id(v) for v in param_vals}
            donated_ids.update(id(l) for l in jax.tree_util.tree_leaves(state))
        run_args = (feed_arrays, param_vals, other_vals, state)
        if plan.cost is None:
            # first run of this specialization: compile through the AOT path
            # so the XLA Compiled handle (the only source of cost_analysis/
            # memory_analysis) is retained for run-log + explain(); one XLA
            # compile either way — the jit cache is simply never populated
            from ..observability import introspect as _introspect
            from ..observability import runlog as _runlog

            with _span("executor.compile"):
                # FLAGS_compile_cache_dir: executables round-trip through
                # the on-disk AOT store (keyed on lowered program text) so a
                # restarted Executor with the same program loads instead of
                # compiling — the warm-restart time_to_first_step lever
                plan.compiled, plan.cost = _introspect.aot_compile(
                    plan.fn, run_args, cache_scope="executor")
            if plan.cost.get("from_disk_cache"):
                counter_inc("executor.aot_cache_hits")
            else:
                counter_inc("executor.compiles")
                if plan.cost.get("aot_cache_stored"):
                    counter_inc("executor.aot_cache_stores")
            _runlog.emit("compile", component="executor", label=plan.label,
                         seconds=plan.cost.get("compile_seconds"),
                         cached=bool(plan.cost.get("from_disk_cache")),
                         flops=plan.cost.get("flops"),
                         bytes_accessed=plan.cost.get("bytes_accessed"),
                         peak_bytes=plan.cost.get("peak_bytes"))
            if plan.compiled is not None and _flag("FLAGS_shard_check"):
                # SPMD pre-flight (PTA2xx) over the lowered program, once
                # per specialization like FLAGS_static_check: reshard/
                # collective findings warn, an HBM-budget overrun raises
                # before the first dispatch (and on every later run — the
                # plan stays poisoned, not half-checked)
                from ..analysis import ProgramAnalysisError as _PAErr
                from ..analysis import spmd as _spmd

                try:
                    plan.spmd = _spmd.shard_check(
                        plan.compiled, component="executor",
                        label=plan.label, kind="executor").summary()
                except _PAErr as e:
                    plan.shard_error = e
                    raise
        if plan.shard_error is not None:
            raise plan.shard_error
        if _sanitizer.enabled() and donate:
            # donated params/opt-state reused after a prior donating run
            # raise a structured StaleStateError here, not an opaque XLA
            # deleted-buffer crash mid-dispatch
            _sanitizer.check_state("executor", (param_vals, state),
                                   label=plan.label)
        with _span("executor.dispatch"):
            try:
                with _sanitizer.transfer_scope(f"executor.{plan.label}"):
                    fetched, buf_updates, new_params, new_state, finite = (
                        plan.compiled if plan.compiled is not None else plan.fn)(*run_args)
            except (TypeError, ValueError):
                if plan.compiled is None:
                    raise
                # AOT executables validate input avals strictly; on drift
                # (weak types, device placement) fall back to the jit path
                # permanently for this plan
                plan.compiled = None
                with _sanitizer.transfer_scope(f"executor.{plan.label}"):
                    fetched, buf_updates, new_params, new_state, finite = plan.fn(*run_args)
        if plan.check and finite:
            # FLAGS_check_nan_inf: the all-finite flags were computed inside
            # the compiled program; this host sync reads len(finite) booleans
            ordered = ([n for n in fetch_names if n in finite]
                       + sorted(set(finite) - {n for n in fetch_names if n}))
            for name in ordered:
                if not bool(finite[name]):
                    from ..observability import runlog as _runlog_nf

                    _runlog_nf.emit("bad_step", component="executor", var=name)
                    raise NonFiniteError(
                        name, kind="gradient" if name.endswith("@GRAD") else "fetch")
        if train and opt is not None:
            for p, v in zip(params, new_params):
                p._value = v
            self._opt_states[opt_key] = new_state
        for buf, sym in prog.buffer_writes:  # commit running-stat updates
            if sym.name in buf_updates:
                buf._value = buf_updates[sym.name]
        if donate:
            counter_inc("executor.donated_runs")
            self._sweep_stale(donated_ids)

        # publish results into the active Scope (reference: the executor's
        # variables live in global_scope; find_var(...).get_tensor() works)
        # — through the plan's cached Variable slots, not per-run gs.var()
        from ..framework.scope import global_scope as _gs

        with _span("executor.fetch"):
            plan.bind_scope(_gs(), fetch_names)
            for p, var in plan.param_vars:
                var._value = p._value
            out = []
            track = bool(_flag("FLAGS_executor_donate")) and not return_numpy
            for i in range(len(fetch_list)):
                if i in passthrough:
                    v = passthrough[i]._value
                else:
                    v = fetched[fetch_names[i]]
                    if fetch_names[i]:
                        plan.fetch_vars[fetch_names[i]]._value = v
                if return_numpy:
                    out.append(np.asarray(v))  # host transfer = device sync
                else:
                    t = _wrap_value(v)  # device handle, no sync
                    if track:
                        import weakref

                        self._fetch_watch.append(weakref.ref(t))
                    out.append(t)
        return out

    def explain(self, analyze: bool = False) -> List[dict]:
        """Per-specialization cost table for every cached compiled program:
        one row per :class:`_RunPlan` with the XLA ``cost_analysis``/
        ``memory_analysis`` captured at its compile (flops, bytes accessed,
        peak device memory, compile seconds). Render with
        ``paddle_tpu.observability.format_cost_table``.

        ``analyze=True`` attaches the SPMD sharding analyzer's verdict
        (PTA2xx: collective counts, reshard bytes, schedule fingerprint)
        under each row's ``"spmd"`` key — reusing the ``FLAGS_shard_check``
        result when the run already produced one, analyzing the retained
        executable lazily otherwise."""
        rows = []
        for plan in self._cache.values():
            row = {"label": plan.label, "train": plan.train,
                   "donate": plan.donate}
            row.update(plan.cost or {})
            if analyze:
                if plan.spmd is not None:
                    row["spmd"] = plan.spmd
                elif plan.compiled is not None:
                    from ..analysis import spmd as _spmd

                    row["spmd"] = _spmd.analyze_compiled(
                        plan.compiled, label=plan.label,
                        kind="executor").summary()
            rows.append(row)
        return rows

    def _sweep_stale(self, donated_ids):
        """Poison previously returned device handles whose buffer the donated
        run just consumed, so reuse raises StaleHandleError (clear story)
        instead of XLA's deleted-buffer error."""
        msg = ("this handle's device buffer was donated back to the compiled "
               "program by a later Executor.run (FLAGS_executor_donate); "
               "fetch it again, or copy it out (np.asarray / .numpy()) "
               "before the next run")
        alive = []
        for ref in self._fetch_watch:
            t = ref()
            if t is None:
                continue
            if id(t._value) in donated_ids:
                t._value = _StaleArray(msg)
            else:
                alive.append(ref)
        self._fetch_watch = alive

    def _static_check(self, prog: Program, fetch_names):
        """FLAGS_static_check body: analyze, warn, raise on errors."""
        import warnings as _warnings

        from ..analysis import ProgramAnalysisError

        diags = prog.analyze(fetch_names or None)
        errors = [d for d in diags if d.severity == "error"]
        for d in diags:
            if d.severity != "error":
                _warnings.warn(f"FLAGS_static_check: {d}", stacklevel=3)
        if errors:
            raise ProgramAnalysisError(errors)

    def _build(self, prog: Program, feed_names, fetch_names, params, others, train,
               donate=False, check=False):
        opt = prog.optimizer
        param_ids = [id(p) for p in params]
        other_ids = [id(t) for t in others]
        grad_names = {id_: sv.name for id_, sv in prog.grad_vars.items()}

        def _is_float(v):
            return hasattr(v, "dtype") and (
                jnp.issubdtype(v.dtype, jnp.floating) or v.dtype == jnp.bfloat16)

        def run_fn(feed_arrays, param_vals, other_vals, state):
            tensor_vals = dict(zip(other_ids, other_vals))

            def forward(pvals):
                tv = dict(tensor_vals)
                tv.update(zip(param_ids, pvals))
                env = dict(feed_arrays)
                return prog.interpret(env, tv)

            new_params, new_state = param_vals, state
            if train and prog.loss_var is not None:
                def loss_of(pvals):
                    env = forward(pvals)
                    loss = env[prog.loss_var.name]
                    return loss, env

                (loss, env), grads = jax.value_and_grad(loss_of, has_aux=True)(param_vals)
                for pid, g in zip(param_ids, grads):
                    if pid in grad_names:
                        env[grad_names[pid]] = g
                if opt is not None:
                    gtree = {i: g for i, g in enumerate(grads)}
                    ptree = {i: v for i, v in enumerate(param_vals)}
                    np_tree, new_opt, _lr = opt._traced_update(
                        gtree, state["opt"], ptree, state["step"])
                    new_params = tuple(np_tree[i] for i in range(len(param_vals)))
                    new_state = {"opt": new_opt, "step": state["step"] + 1}
            else:
                env = forward(param_vals)
            fetched = {n: env[n] for n in fetch_names if n is not None}
            buf_updates = {sym.name: env[sym.name] for _, sym in prog.buffer_writes
                           if sym.name in env}
            finite = {}
            if check:
                # FLAGS_check_nan_inf, fused: one all-finite reduction per
                # float fetch + per gradient, inside this same program (the
                # eager path's per-op host-sync check has no jit analog)
                for n, v in fetched.items():
                    if _is_float(v):
                        finite[n] = jnp.all(jnp.isfinite(v.astype(jnp.float32)))
                for pid in param_ids:
                    gname = grad_names.get(pid)
                    if gname is not None and gname in env and gname not in finite \
                            and _is_float(env[gname]):
                        finite[gname] = jnp.all(jnp.isfinite(
                            env[gname].astype(jnp.float32)))
            return fetched, buf_updates, new_params, new_state, finite

        if donate:
            # donate param_vals + opt state (the two pytrees the update
            # rewrites): XLA reuses their buffers for the new values, so
            # param memory stays flat across training runs. The consumed
            # jax.Arrays are dead after the call — run() rebinds p._value
            # and sweeps previously fetched handles (StaleHandleError).
            return jax.jit(run_fn, donate_argnums=(1, 3))
        return jax.jit(run_fn)


# --------------------------------------------------------- inference format
def save_inference_model(path_prefix: str, feed_vars: List[Tensor], fetch_vars: List[Tensor],
                         executor: Optional[Executor] = None, program: Optional[Program] = None,
                         **kwargs) -> None:
    """Serialize the inference graph as StableHLO + metadata.

    Reference paddle.static.save_inference_model prunes the program to the
    feed→fetch subgraph and writes ProgramDesc+params; here jax.export lowers
    the same subgraph (params embedded as constants) to portable StableHLO —
    ``{prefix}.pdmodel`` holds the serialized artifact, ``{prefix}.pdiparams``
    the metadata (feed/fetch names and shapes).
    """
    prog = program if program is not None else _default_main
    if isinstance(prog, CompiledProgram):
        prog = prog._program
    feed_names = [v._value.name if is_symbolic(v._value) else v.name for v in feed_vars]
    fetch_names = [v._value.name for v in fetch_vars]
    refs = prog.tensor_refs()
    ref_vals = tuple(t._value for t in refs)
    ref_ids = [id(t) for t in refs]

    def infer_fn(*feeds):
        env = dict(zip(feed_names, feeds))
        if "__rng_key__" in prog.feeds and "__rng_key__" not in env:
            env["__rng_key__"] = jnp.uint32(0)
        if "__train_flag__" in prog.feeds and "__train_flag__" not in env:
            # export is inference: recorded rng ops (dropout) become identity
            env["__train_flag__"] = jnp.uint32(0)
        env = prog.interpret(env, dict(zip(ref_ids, ref_vals)))
        return tuple(env[n] for n in fetch_names)

    # dynamic (-1/None) dims export as jax symbolic dimensions so the loaded
    # artifact accepts any batch size (reference programs are shape-dynamic)
    scope = jax.export.SymbolicScope()
    specs = []
    for i, v in enumerate(feed_vars):
        shape = tuple(v._value.shape)
        if any(d < 0 for d in shape):
            spec_str = ",".join(f"d{i}_{j}" if d < 0 else str(d) for j, d in enumerate(shape))
            shape = jax.export.symbolic_shape(spec_str, scope=scope)
        specs.append(jax.ShapeDtypeStruct(shape, v._value.dtype))
    exported = jax.export.export(jax.jit(infer_fn))(*specs)
    path = Path(path_prefix)
    path.parent.mkdir(parents=True, exist_ok=True)
    Path(str(path) + ".pdmodel").write_bytes(exported.serialize())
    meta = {
        "feed_names": feed_names,
        "fetch_names": fetch_names,
        # symbolic (dynamic) dims serialize as -1
        "feed_shapes": [[int(d) if isinstance(d, int) else -1 for d in s.shape] for s in specs],
        "feed_dtypes": [str(s.dtype) for s in specs],
        # artifact provenance: .pdmodel is serialized StableHLO (jax.export);
        # this pickle sidecar is the legacy metadata format
        "format": "stablehlo",
        "producer": f"paddle_tpu/jax {jax.__version__}",
    }
    Path(str(path) + ".pdiparams").write_bytes(pickle.dumps(meta))


def load_inference_model(path_prefix: str, executor: Optional[Executor] = None):
    """Returns (callable_program, feed_names, fetch_names); the callable maps
    feed arrays → list of fetch arrays (reference returns a ProgramDesc — the
    StableHLO artifact plays that role here)."""
    exported = jax.export.deserialize(Path(str(path_prefix) + ".pdmodel").read_bytes())
    meta = pickle.loads(Path(str(path_prefix) + ".pdiparams").read_bytes())

    def run(*feeds):
        arrays = [jnp.asarray(unwrap(f)) for f in feeds]
        return list(exported.call(*arrays))

    run.meta = meta
    return run, meta["feed_names"], meta["fetch_names"]


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Embed a host (numpy) function in the program (reference:
    python/paddle/fluid/layers/nn.py py_func / py_func_op.cc). The op lowers
    to a ``jax.pure_callback`` node — the Executor's compiled program calls
    back to the host for this op — with ``backward_func`` attached via
    ``jax.custom_vjp`` so append_backward/minimize differentiate through it.

    ``out`` supplies the output spec(s): Tensor(s)/placeholder(s) whose
    shape+dtype describe the result (their values are not read). Returns the
    result Tensor (or list, mirroring ``out``'s structure).

    ``backward_func(*inputs, *outputs, *out_grads)`` returns one grad per
    input; ``skip_vars_in_backward_input`` drops the given forward
    inputs/outputs from its argument list (reference semantics).
    """
    from ..utils.custom_op import make_callback_op

    xs = list(x) if isinstance(x, (list, tuple)) else [x]
    outs_spec = list(out) if isinstance(out, (list, tuple)) else [out]
    multi_out = isinstance(out, (list, tuple))

    specs = [jax.ShapeDtypeStruct(tuple(int(d) for d in o.shape), to_jax_dtype(o.dtype)) for o in outs_spec]

    def infer_spec(*_):
        return specs[0] if not multi_out else tuple(specs)

    skipped = set()
    if skip_vars_in_backward_input:
        sk = skip_vars_in_backward_input if isinstance(skip_vars_in_backward_input, (list, tuple)) else [skip_vars_in_backward_input]
        skipped = {id(v) for v in sk}
    # positions (within inputs+outputs) passed to backward_func
    keep_in = [i for i, v in enumerate(xs) if id(v) not in skipped]
    keep_out = [i for i, v in enumerate(outs_spec) if id(v) not in skipped]

    bwd = None
    if backward_func is not None:
        def bwd(*args):
            ins = args[:len(xs)]
            outs = args[len(xs):len(xs) + len(specs)]
            gouts = args[len(xs) + len(specs):]
            picked = [ins[i] for i in keep_in] + [outs[i] for i in keep_out] + list(gouts)
            g = backward_func(*picked)
            return tuple(g) if isinstance(g, (list, tuple)) else g

    raw = make_callback_op(func, bwd, infer_spec, name=getattr(func, "__name__", "py_func"))
    from ..tensor._helpers import ensure_tensor, op as _op

    result = _op(raw, *[ensure_tensor(t) for t in xs], _name="py_func")
    return list(result) if multi_out and isinstance(result, (tuple, list)) else result


# ------------------------------------------------------------- static.nn
class _StaticNN:
    """reference paddle.static.nn: LayerHelper-style builders. Each call
    creates fresh parameters (eagerly, = startup init) and records ops."""

    @staticmethod
    def fc(x, size, num_flatten_dims=1, activation=None, name=None):
        from .. import nn

        in_features = int(np.prod(x._value.shape[num_flatten_dims:]))
        layer = nn.Linear(in_features, size)
        if num_flatten_dims != 1 or len(x._value.shape) > 2:
            from ..tensor.manipulation import reshape

            x = reshape(x, [-1, in_features] if num_flatten_dims == 1 else
                        list(x._value.shape[:num_flatten_dims]) + [in_features])
        out = layer(x)
        if activation:
            import paddle_tpu.nn.functional as F

            out = getattr(F, activation)(out)
        return out

    @staticmethod
    def batch_norm(x, **kwargs):
        from .. import nn

        return nn.BatchNorm(x._value.shape[1])(x)

    @staticmethod
    def embedding(input, size, **kwargs):
        from .. import nn

        return nn.Embedding(size[0], size[1])(input)

    # -- control flow (reference fluid/layers/control_flow.py) -------------
    # The reference builds ConditionalBlock / While sub-blocks in the
    # ProgramDesc; the XLA-native forms are lax.cond / lax.while_loop /
    # lax.switch. These work identically in eager, @to_static and recorded
    # static programs — and are the documented bridge for Python `if`/`while`
    # over traced values (which cannot compile; see jit.to_static docs).
    # In static capture, branch/body closures are traced into SUB-programs
    # (the ConditionalBlock analog): their outer-variable reads become the
    # recorded cond/while op's inputs, and the sub-program interprets inside
    # lax.cond / lax.while_loop at Executor time.

    @staticmethod
    def _trace_subblock(fn, *placeholder_specs):
        """Run ``fn`` (with fresh symbolic placeholders for
        ``placeholder_specs``) inside a nested Program. Returns
        (subprogram, out_tensors, placeholders, outer_sym_deps, tensor_deps).
        """
        from ..framework.core import Tensor, _wrap_value
        from ..framework.static_trace import Program, pop_program, push_program, SymbolicValue

        sub = Program()
        push_program(sub)
        try:
            phs = [
                _wrap_value(SymbolicValue(tuple(s.shape), s.dtype, sub.fresh_name("loopvar")), stop_gradient=True)
                for s in placeholder_specs
            ]
            out = fn(*phs) if phs else fn()
        finally:
            pop_program()
        outs = list(out) if isinstance(out, (tuple, list)) else [out]
        produced = {sv.name for op_ in sub.ops for sv in op_.outputs}
        produced |= {t._value.name for t in phs}
        sym_deps, tensor_deps = {}, {}
        for op_ in sub.ops:
            for kind, ref in op_.inputs:
                if kind == "sym" and ref.name not in produced:
                    sym_deps[ref.name] = ref
                elif kind == "tensor":
                    tensor_deps[id(ref)] = ref
        return sub, outs, phs, sym_deps, tensor_deps

    @staticmethod
    def _branch_closure(branches):
        """Trace each branch into a sub-block; build a picker that evaluates
        branch ``i`` from positional values. Dependencies — outer symbolic
        reads, captured concrete tensors, and outer values RETURNED
        unchanged — all become positional inputs of the recorded op, so
        gradients flow through closure-captured parameters (the eager tape /
        jax.vjp sees them as real inputs) and identity branches resolve."""
        from ..framework.core import Tensor
        from ..framework.static_trace import is_symbolic

        traced = [_StaticNN._trace_subblock(fn) for fn in branches]
        n_out = len(traced[0][1])
        if any(len(t[1]) != n_out for t in traced):
            raise ValueError("all branches must return the same number of outputs")
        sym_deps, tensor_deps = {}, {}
        for sub, outs, _, deps, tens in traced:
            sym_deps.update(deps)
            tensor_deps.update(tens)
            for o in outs:  # identity-returned outer values are deps too
                if isinstance(o, Tensor):
                    if is_symbolic(o._value):
                        produced = {sv.name for op_ in sub.ops for sv in op_.outputs}
                        if o._value.name not in produced:
                            sym_deps[o._value.name] = o._value
                    else:
                        tensor_deps.setdefault(id(o), o)
        names = sorted(sym_deps)
        tensors = [tensor_deps[k] for k in sorted(tensor_deps)]
        tpos = {id(t): i for i, t in enumerate(tensors)}

        def make_runner(vals):
            env0 = dict(zip(names, vals[:len(names)]))
            tvals = dict(zip([id(t) for t in tensors], vals[len(names):]))

            def runner(i):
                sub, outs, _, _, _ = traced[i]

                def go(_):
                    env = sub.interpret(dict(env0), tvals)
                    res = []
                    for o in outs:
                        if is_symbolic(o._value):
                            res.append(env[o._value.name])
                        elif id(o) in tpos:  # identity-returned captured tensor
                            res.append(vals[len(names) + tpos[id(o)]])
                        else:  # true constant
                            res.append(o._value)
                    return tuple(res)

                return go

            return runner

        inputs = [sym_deps[n] for n in names] + tensors
        return make_runner, inputs, n_out

    @staticmethod
    def cond(pred, true_fn=None, false_fn=None, name=None):
        from ..tensor._helpers import ensure_tensor, op

        if true_fn is None or false_fn is None:
            raise ValueError("static.nn.cond requires both true_fn and false_fn")
        make_runner, inputs, n_out = _StaticNN._branch_closure([true_fn, false_fn])

        def fn(p, *vals):
            import jax

            runner = make_runner(vals)
            out = jax.lax.cond(jnp.all(p), runner(0), runner(1), 0)
            return out if n_out > 1 else out[0]

        return op(fn, ensure_tensor(pred), *inputs, _name="cond")

    @staticmethod
    def while_loop(cond, body, loop_vars, is_test=False, name=None):
        """lax.while_loop bridge. Reverse-mode through an unbounded while is
        impossible under XLA (unknown trip count), so differentiable loop
        vars are rejected up front — detach() them, or express bounded
        recurrences with RNN layers / lax.scan-based ops."""
        import jax

        from ..framework.core import Tensor
        from ..tensor._helpers import ensure_tensor, op

        loop_vars = [ensure_tensor(v) for v in loop_vars]
        for v in loop_vars:
            if isinstance(v, Tensor) and not v.stop_gradient:
                raise ValueError(
                    "static.nn.while_loop cannot backprop (XLA has no "
                    "reverse-mode for unbounded while); pass detached loop "
                    "vars or use a bounded scan (nn RNN layers)")
        n_loop = len(loop_vars)
        specs = [jax.ShapeDtypeStruct(tuple(v._value.shape), v._value.dtype) for v in loop_vars]

        make_c, in_c, nc = _StaticNN._branch_closure_with_args([cond], specs)
        make_b, in_b, nb = _StaticNN._branch_closure_with_args([body], specs)
        if nb != n_loop:
            raise ValueError(f"while_loop body returned {nb} values for {n_loop} loop vars")

        def fn(*vals):
            lv = vals[:n_loop]
            cv = vals[n_loop:n_loop + len(in_c)]
            bv = vals[n_loop + len(in_c):]

            def c(vs):
                return jnp.all(make_c(cv)(0, vs)(0)[0])

            def b(vs):
                return make_b(bv)(0, vs)(0)

            return jax.lax.while_loop(c, b, tuple(lv))

        out = op(fn, *loop_vars, *in_c, *in_b, _name="while_loop")
        return list(out) if isinstance(out, tuple) else [out]

    @staticmethod
    def switch_case(branch_index, branch_fns, default=None, name=None):
        from ..tensor._helpers import ensure_tensor, op

        if isinstance(branch_fns, dict):
            items = sorted(branch_fns.items())
        else:
            items = list(enumerate(branch_fns)) if callable(branch_fns[0]) else [tuple(kv) for kv in branch_fns]
        keys = [k for k, _ in items]
        fns = [f for _, f in items]
        if default is None:
            default = fns[-1]
        make_runner, inputs, n_out = _StaticNN._branch_closure(fns + [default])

        def fn(idx, *vals):
            import jax

            runner = make_runner(vals)
            branches = [runner(i) for i in range(len(fns) + 1)]
            # map sparse keys onto dense branch slots; unmatched -> default
            slot = jnp.full((), len(fns), jnp.int32)
            for i, k in enumerate(keys):
                slot = jnp.where(idx == k, jnp.int32(i), slot)
            out = jax.lax.switch(slot, branches, 0)
            return out if n_out > 1 else out[0]

        return op(fn, ensure_tensor(branch_index), *inputs, _name="switch_case")

    @staticmethod
    def _branch_closure_with_args(fns, arg_specs):
        """_branch_closure variant for callables taking loop-var arguments:
        traces fn(*placeholders) and returns a runner factory whose runners
        are called as runner(vals)(i, loop_vals) -> go."""
        from ..framework.static_trace import is_symbolic

        traced = [_StaticNN._trace_subblock(fn, *arg_specs) for fn in fns]
        n_out = len(traced[0][1])
        sym_deps, tensor_deps = {}, {}
        ph_names = [[p._value.name for p in t[2]] for t in traced]
        for (sub, outs, phs, deps, tens), names_i in zip(traced, ph_names):
            sym_deps.update({k: v for k, v in deps.items()})
            tensor_deps.update(tens)
        names = sorted(sym_deps)
        tensors = [tensor_deps[k] for k in sorted(tensor_deps)]

        def make_runner(vals):
            env0 = dict(zip(names, vals[:len(names)]))
            tvals = dict(zip([id(t) for t in tensors], vals[len(names):]))

            def at(i, loop_vals):
                sub, outs, phs, _, _ = traced[i]

                def go(_):
                    env = dict(env0)
                    env.update({p._value.name: v for p, v in zip(phs, loop_vals)})
                    env = sub.interpret(env, tvals)
                    res = []
                    for o in outs:
                        if not hasattr(o, "_value"):  # raw python constant
                            res.append(jnp.asarray(o))  # (e.g. a bool flag)
                        elif is_symbolic(o._value):
                            if o._value.name in env:
                                res.append(env[o._value.name])
                            else:  # identity-returned placeholder
                                res.append(loop_vals[[p._value.name for p in phs].index(o._value.name)])
                        else:
                            res.append(o._value)
                    return tuple(res)

                return go

            return at

        inputs = [sym_deps[n] for n in names] + tensors
        return make_runner, inputs, n_out


# sequence ops live on static.nn in the reference (fluid/layers/sequence_lod.py).
# Only the trace-safe ones (built on the op() chokepoint) are aliased; pad/
# unpad/expand are host-side (data-dependent shapes) and raise a pointer to
# their eager form instead of failing deep inside np.asarray.
from ..nn.functional import sequence  # noqa: E402

for _sn in ("sequence_mask", "sequence_pool", "sequence_softmax"):
    setattr(_StaticNN, _sn, staticmethod(getattr(sequence, _sn)))


def _host_side_sequence_op(name):
    def raiser(*a, **k):
        raise NotImplementedError(
            f"static.nn.{name} has data-dependent output shapes and cannot be "
            f"recorded in a static program; call paddle.nn.functional.{name} "
            f"on concrete data (e.g. at ingest, like a DataLoader collate)")

    return staticmethod(raiser)


for _sn in ("sequence_pad", "sequence_unpad", "sequence_expand"):
    setattr(_StaticNN, _sn, _host_side_sequence_op(_sn))

nn = _StaticNN()
# appended to paddle_tpu/static/__init__.py after the host-side sequence raisers


def _attach_static_nn_tail():
    """static.nn wrapper tail (reference python/paddle/static/nn/__init__.py):
    the static forms delegate to the same traced functionals the dygraph API
    uses — under this design a static program records them through the op()
    chokepoint identically."""
    import paddle_tpu.nn.functional as F
    from ..nn.functional import extension_ops as _ext
    from ..tensor import linalg as _linalg  # noqa: F401

    def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
               groups=1, param_attr=None, bias_attr=None, act=None, name=None, data_format="NCHW"):
        from .. import nn

        layer = nn.Conv2D(int(input.shape[1]), num_filters, filter_size, stride,
                          padding, dilation, groups, weight_attr=param_attr,
                          bias_attr=bias_attr, data_format=data_format)
        out = layer(input)
        return getattr(F, act)(out) if act else out

    def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
               groups=1, param_attr=None, bias_attr=None, act=None, name=None, data_format="NCDHW"):
        from .. import nn

        layer = nn.Conv3D(int(input.shape[1]), num_filters, filter_size, stride,
                          padding, dilation, groups, weight_attr=param_attr, bias_attr=bias_attr)
        out = layer(input)
        return getattr(F, act)(out) if act else out

    def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                         padding=0, stride=1, dilation=1, groups=1, param_attr=None,
                         bias_attr=None, act=None, name=None, data_format="NCHW"):
        from .. import nn

        layer = nn.Conv2DTranspose(int(input.shape[1]), num_filters, filter_size,
                                   stride, padding, weight_attr=param_attr, bias_attr=bias_attr)
        out = layer(input)
        return getattr(F, act)(out) if act else out

    def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,
                         padding=0, stride=1, dilation=1, groups=1, param_attr=None,
                         bias_attr=None, act=None, name=None, data_format="NCDHW"):
        from .. import nn

        layer = nn.Conv3DTranspose(int(input.shape[1]), num_filters, filter_size,
                                   stride, padding, weight_attr=param_attr, bias_attr=bias_attr)
        out = layer(input)
        return getattr(F, act)(out) if act else out

    def layer_norm(input, scale=True, shift=True, begin_norm_axis=1, epsilon=1e-5,
                   param_attr=None, bias_attr=None, act=None, name=None):
        shape = [int(d) for d in input.shape[begin_norm_axis:]]
        from .. import nn

        layer = nn.LayerNorm(shape, epsilon=epsilon,
                             weight_attr=None if scale else False,
                             bias_attr=None if shift else False)
        out = layer(input)
        return getattr(F, act)(out) if act else out

    def group_norm(input, groups, epsilon=1e-5, param_attr=None, bias_attr=None,
                   act=None, data_layout="NCHW", name=None):
        from .. import nn

        layer = nn.GroupNorm(groups, int(input.shape[1]), epsilon=epsilon)
        out = layer(input)
        return getattr(F, act)(out) if act else out

    def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None, name=None):
        from .. import nn

        return nn.InstanceNorm2D(int(input.shape[1]), epsilon=epsilon)(input)

    def data_norm(input, act=None, epsilon=1e-5, param_attr=None, data_layout="NCHW",
                  in_place=False, name=None, moving_mean_name=None, moving_variance_name=None,
                  do_model_average_for_mean_and_var=True, slot_dim=-1, sync_stats=False,
                  summary_decay_rate=0.9999999, enable_scale_and_shift=False):
        """Per-feature running standardization (reference data_norm_op):
        batch statistics without the affine, the CTR-model normalizer."""
        from ..tensor._helpers import ensure_tensor, op
        import jax.numpy as jnp

        x = ensure_tensor(input)

        def fn(v):
            mean = jnp.mean(v, axis=0, keepdims=True)
            var = jnp.mean(jnp.square(v - mean), axis=0, keepdims=True)
            return (v - mean) / jnp.sqrt(var + epsilon)

        out = op(fn, x, _name="data_norm")
        return getattr(F, act)(out) if act else out

    def prelu(x, mode="all", param_attr=None, data_format="NCHW", name=None):
        from .. import nn

        num = 1 if mode == "all" else int(x.shape[1])
        return nn.PReLU(num_parameters=num, weight_attr=param_attr)(x)

    def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
        from .. import nn

        return nn.SpectralNorm(tuple(int(d) for d in weight.shape), dim=dim,
                               power_iters=power_iters, eps=eps)(weight)

    def bilinear_tensor_product(x, y, size, act=None, name=None, param_attr=None, bias_attr=None):
        from .. import nn

        layer = nn.Bilinear(int(x.shape[1]), int(y.shape[1]), size,
                            weight_attr=param_attr, bias_attr=bias_attr)
        out = layer(x, y)
        return getattr(F, act)(out) if act else out

    def deform_conv2d(x, offset, mask, num_filters, filter_size, stride=1, padding=0,
                      dilation=1, groups=1, deformable_groups=1, im2col_step=1,
                      param_attr=None, bias_attr=None, name=None):
        from ..vision.ops import DeformConv2D

        layer = DeformConv2D(int(x.shape[1]), num_filters, filter_size, stride,
                             padding, dilation, deformable_groups, groups)
        return layer(x, offset, mask)

    def row_conv(input, future_context_size, param_attr=None, act=None):
        """Lookahead row convolution (reference row_conv_op, DeepSpeech2):
        y[t] = sum_{k=0..K} x[t+k] * w[k]."""
        import jax.numpy as jnp

        from ..framework.core import _wrap_value
        from ..framework.random import split_key
        from ..tensor._helpers import ensure_tensor, op
        import jax

        x = ensure_tensor(input)  # [B, T, D]
        D = int(x.shape[-1])
        K = int(future_context_size)
        w = _wrap_value(jax.random.normal(split_key(), (K + 1, D), jnp.float32) * 0.02,
                        stop_gradient=False)

        def fn(v, wv):
            outs = 0
            for k in range(K + 1):
                shifted = jnp.concatenate([v[:, k:], jnp.zeros_like(v[:, :k])], axis=1)
                outs = outs + shifted * wv[k]
            return outs

        out = op(fn, x, w, _name="row_conv")
        return getattr(F, act)(out) if act else out

    def nce(input, label, num_total_classes, sample_weight=None, param_attr=None,
            bias_attr=None, num_neg_samples=None, name=None, sampler="uniform",
            custom_dist=None, seed=0, is_sparse=False):
        """Noise-contrastive estimation loss (reference nce_op): sampled
        softmax against uniformly drawn negatives."""
        import jax
        import jax.numpy as jnp

        from ..framework.core import _wrap_value
        from ..framework.random import split_key
        from ..tensor._helpers import ensure_tensor, op

        x, y = ensure_tensor(input), ensure_tensor(label)
        D = int(x.shape[-1])
        k = int(num_neg_samples or 10)
        w = _wrap_value(jax.random.normal(split_key(), (num_total_classes, D), jnp.float32) * 0.02,
                        stop_gradient=False)
        b = _wrap_value(jnp.zeros((num_total_classes,), jnp.float32), stop_gradient=False)
        neg = jax.random.randint(split_key(), (k,), 0, num_total_classes)

        def fn(xv, yv, wv, bv):
            yv = yv.reshape(-1)
            pos_logit = jnp.sum(xv * wv[yv], -1) + bv[yv]
            neg_logit = xv @ wv[neg].T + bv[neg]
            pos_loss = jax.nn.log_sigmoid(pos_logit)
            neg_loss = jax.nn.log_sigmoid(-neg_logit).sum(-1)
            return -(pos_loss + neg_loss)[:, None]

        return op(fn, x, y, w, b, _name="nce")

    def crf_decoding(input, param_attr=None, label=None, length=None, transition=None):
        """Viterbi decode (reference crf_decoding_op) via the text module's
        decoder. The reference passes the transition matrix through
        ``param_attr``; a direct ``transition`` tensor is also accepted."""
        from ..text import viterbi_decode

        if transition is None:
            transition = param_attr
        if transition is None:
            raise ValueError("pass the transition matrix (param_attr= or transition=)")
        return viterbi_decode(input, transition, length)

    def sparse_embedding(input, size, padding_idx=None, is_test=False, entry=None,
                         table_class="MemorySparseTable", param_attr=None, dtype="float32"):
        """PS-era sparse table lookup -> dense Embedding(sparse=True)
        (framework SelectedRows lazy-row contract)."""
        from .. import nn

        return nn.Embedding(size[0], size[1], padding_idx=padding_idx, sparse=True,
                            weight_attr=param_attr)(input)

    def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                       min_ratio=None, max_ratio=None, **kwargs):
        raise NotImplementedError(
            "multi_box_head (SSD prior boxes) is out of scope; compose "
            "vision.ops.yolo_box / nms pipelines instead")

    def case(pred_fn_pairs, default=None, name=None):
        """First-match conditional chain (reference layers.case): nested
        static.nn.cond."""
        if not pred_fn_pairs:
            raise ValueError("case needs at least one (pred, fn) pair")

        def build(pairs):
            (pred, fn) = pairs[0]
            rest = pairs[1:]
            if not rest:
                if default is None:
                    return fn()
                return _StaticNN.cond(pred, fn, default)
            return _StaticNN.cond(pred, fn, lambda: build(rest))

        return build(list(pred_fn_pairs))

    def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
        from . import py_func as _pf

        return _pf(func, x, out, backward_func, skip_vars_in_backward_input)

    # sequence static forms over the padded+lengths pair
    from ..nn.functional import sequence as _seq

    def sequence_concat(input, name=None):
        from ..tensor.manipulation import concat

        return concat(input, axis=1)

    def sequence_first_step(input, lengths=None):
        if lengths is None:
            raise ValueError("pass lengths (padded+lengths is the LoD here)")
        return _seq.sequence_pool(input, lengths, "first")

    def sequence_last_step(input, lengths=None):
        if lengths is None:
            raise ValueError("pass lengths (padded+lengths is the LoD here)")
        return _seq.sequence_pool(input, lengths, "last")

    def sequence_reverse(x, lengths=None, name=None):
        """Reverse each sequence's valid prefix (reference
        sequence_reverse_op)."""
        import jax.numpy as jnp

        from ..tensor._helpers import ensure_tensor, op

        xt = ensure_tensor(x)
        if lengths is None:
            return op(lambda v: v[:, ::-1], xt, _name="sequence_reverse")
        lt = ensure_tensor(lengths)

        def fn(v, ln):
            t = v.shape[1]
            idx = jnp.arange(t)[None, :]
            rev = jnp.where(idx < ln[:, None], ln[:, None] - 1 - idx, idx)
            return jnp.take_along_axis(v, rev.reshape(rev.shape + (1,) * (v.ndim - 2)), axis=1)

        return op(fn, xt, lt, _name="sequence_reverse")

    def sequence_expand_as(x, y, name=None):
        from ..tensor.manipulation import expand_as

        return expand_as(x, y)

    def _host_only(name):
        def raiser(*a, **k):
            raise NotImplementedError(
                f"static.nn.{name}: LoD-shape-changing op; express it over "
                f"the (padded, lengths) pair with nn.functional.sequence_*")

        raiser.__name__ = name
        return raiser

    sequence_enumerate = _host_only("sequence_enumerate")
    sequence_reshape = _host_only("sequence_reshape")
    sequence_scatter = _host_only("sequence_scatter")
    sequence_slice = _host_only("sequence_slice")
    sequence_conv = _host_only("sequence_conv")

    for name, fn in list(locals().items()):
        if callable(fn) and not name.startswith("_"):
            setattr(_StaticNN, name, staticmethod(fn))


_attach_static_nn_tail()
