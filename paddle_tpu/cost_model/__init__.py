"""Cost model (parity: python/paddle/cost_model/cost_model.py).

The reference profiles a static Program per-op through the C++ profiler and
serves op time/memory tables to auto-parallel planners. TPU-first: the
whole-program cost comes from the XLA compiler itself —
``Compiled.cost_analysis()`` (flops, bytes accessed, estimated time) plus
``memory_analysis()`` (argument/output/temp allocation) computed on the
lowered executable, no measurement run needed. ``static_cost_data`` serves
the same role as the reference's static_op_benchmark.json: per-op
analytical costs extracted from the compiled module.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional

import jax
import numpy as np

__all__ = ["CostModel", "HardwareSpec", "hardware_spec", "predict_step_time"]


@dataclass(frozen=True)
class HardwareSpec:
    """Roofline constants for one device class: peak matmul throughput,
    HBM bandwidth, and interconnect (ICI/host) bandwidth. Deliberately
    coarse — the auto-parallel planner only needs costs that *rank*
    candidate plans correctly, not cycle-accurate latencies; the dominant
    signal (reshard bytes vs compute) survives a 2x constant error."""

    name: str
    flops_per_sec: float
    hbm_bytes_per_sec: float
    ici_bytes_per_sec: float


#: per-backend defaults (order-of-magnitude; override via hardware_spec(hw=..))
_KNOWN_HARDWARE = {
    # TPU v5e-class chip: ~200 TFLOP/s bf16, ~800 GB/s HBM, ~100 GB/s ICI
    "tpu": HardwareSpec("tpu", 2.0e14, 8.0e11, 1.0e11),
    "gpu": HardwareSpec("gpu", 1.0e14, 2.0e12, 5.0e10),
    # host CPU: the constants only matter relative to each other — comms
    # (loopback "collectives") are priced well below compute bandwidth so a
    # reshard-heavy plan still ranks worse than a clean one
    "cpu": HardwareSpec("cpu", 5.0e10, 3.0e10, 1.0e10),
}


def hardware_spec(backend: Optional[str] = None) -> HardwareSpec:
    """The roofline constants for ``backend`` (default: the active jax
    backend; the axon tunnel registers TPU devices under its own name)."""
    if backend is None:
        backend = jax.default_backend()
    if backend == "axon":
        backend = "tpu"
    return _KNOWN_HARDWARE.get(backend, _KNOWN_HARDWARE["cpu"])


def predict_step_time(flops: Optional[float], bytes_accessed: Optional[float],
                      comm_bytes: float = 0.0,
                      hw: Optional[HardwareSpec] = None) -> Dict[str, float]:
    """Analytical step-time estimate from compiled-program stats.

    Classic roofline: compute and HBM traffic overlap (the slower one
    bounds the kernel), collectives are serialized on top (XLA's
    latency-hiding scheduler overlaps some of it, so this is a pessimistic
    bound — fine for *ranking* plans, which is all the planner needs).
    Returns the component seconds plus ``total_s``.
    """
    if hw is None:
        hw = hardware_spec()
    compute_s = float(flops or 0.0) / hw.flops_per_sec
    memory_s = float(bytes_accessed or 0.0) / hw.hbm_bytes_per_sec
    comm_s = float(comm_bytes or 0.0) / hw.ici_bytes_per_sec
    return {
        "compute_s": compute_s,
        "memory_s": memory_s,
        "comm_s": comm_s,
        "total_s": max(compute_s, memory_s) + comm_s,
    }


class CostModel:
    def __init__(self):
        self._last = None

    def profile_measure(self, main_program=None, startup_program=None, device="tpu", fetch_cost_list=("time",), *, feed=None, fetch_list=None, fn=None, args=None):
        """Cost-estimate a program (reference profile_measure runs it under
        the profiler; here XLA's analytical model prices the compiled HLO).

        Either pass a recorded static ``main_program`` (+ example ``feed`` /
        ``fetch_list``) or a raw callable ``fn`` + example ``args``.
        """
        if fn is not None:
            lowered = jax.jit(fn).lower(*args)
        else:
            if main_program is None:
                raise ValueError("pass main_program= or fn=/args=")
            import jax.numpy as jnp

            from ..framework.core import unwrap
            from ..static import Executor

            exe = Executor()
            if startup_program is not None:
                exe.run(startup_program)
            prog = main_program
            feed_arrays = {k: jnp.asarray(unwrap(v)) for k, v in (feed or {}).items()}
            if "__rng_key__" in prog.feeds:
                feed_arrays["__rng_key__"] = jnp.uint32(1)
            if "__train_flag__" in prog.feeds:
                feed_arrays["__train_flag__"] = jnp.uint32(1)
            from ..framework.core import Tensor as _T
            from ..framework.static_trace import is_symbolic

            fetch_names = [f._value.name if isinstance(f, _T) and is_symbolic(f._value) else f
                           for f in (fetch_list or [])]
            train = prog.optimizer is not None or bool(prog.grad_vars)
            refs = prog.tensor_refs()
            if train and prog.grad_vars:
                params = [t for t in refs if id(t) in prog.grad_vars]
            elif train:
                params = [t for t in refs if not t.stop_gradient]
            else:
                params = []
            pids = {id(t) for t in params}
            others = [t for t in refs if id(t) not in pids]
            jit_fn = exe._build(prog, tuple(sorted(feed_arrays)), fetch_names, params, others, train)
            state = None
            if train and prog.optimizer is not None:
                ptree = {i: p._value for i, p in enumerate(params)}
                state = {"opt": prog.optimizer.core.init(ptree), "step": jnp.zeros((), jnp.int32)}
            lowered = jit_fn.lower(feed_arrays, tuple(p._value for p in params),
                                   tuple(t._value for t in others), state)
        compiled = lowered.compile()
        from ..framework.jax_compat import (
            compiled_cost_analysis,
            compiled_memory_analysis,
        )

        cost = compiled_cost_analysis(compiled)
        mem = compiled_memory_analysis(compiled)
        out = {
            "flops": float(cost.get("flops", 0.0)),
            "bytes_accessed": float(cost.get("bytes accessed", 0.0)),
            "utilization": {k: float(v) for k, v in cost.items() if "utilization" in k},
            "temp_bytes": getattr(mem, "temp_size_in_bytes", None),
            "output_bytes": getattr(mem, "output_size_in_bytes", None),
            "argument_bytes": getattr(mem, "argument_size_in_bytes", None),
            "raw": {k: float(v) for k, v in cost.items()},
        }
        self._last = out
        return out

    def static_cost_data(self) -> Optional[Dict]:
        """The last analysis (reference reads static_op_benchmark.json)."""
        return self._last

    def get_static_op_time(self, op_name: str, forward=True, dtype="float32"):
        """Per-op static costs are folded into whole-program XLA analysis on
        TPU; expose the aggregate instead of a per-op table."""
        if self._last is None:
            raise RuntimeError("run profile_measure first")
        return self._last["raw"]
