"""paddle.text.datasets parity: the NLP dataset classes.

Parity: python/paddle/text/datasets/{imdb,imikolov,conll05,movielens,
uci_housing,wmt14,wmt16}.py — map-style ``paddle.io.Dataset`` subclasses
whose constructors take a ``data_file``/``mode`` and whose ``__getitem__``
yields numpy records.

This environment has zero egress, so the reference's auto-download
(``_check_exists_and_download``) becomes an explicit local-path contract:
pass ``data_file=`` pointing at the same archive/plain-text formats the
reference downloads; ``download=True`` without a local file raises with the
canonical URL so users know what to fetch. Parsing of locally supplied
files matches the reference record schemas (token-id sequences for
IMDB/Imikolov, (features, price) rows for UCIHousing, ...).
"""
from __future__ import annotations

import os
import re
import tarfile
from typing import List, Optional

import numpy as np

from ..io import Dataset

__all__ = ["Imdb", "Imikolov", "UCIHousing", "Conll05st", "Movielens", "WMT14", "WMT16"]


def _require(data_file, url, name):
    if data_file is None or not os.path.exists(data_file):
        raise FileNotFoundError(
            f"{name}: no network egress in this environment — download the "
            f"dataset archive yourself ({url}) and pass data_file=<path>")
    return data_file


class _TokenizedCorpus(Dataset):
    """Shared machinery: build a frequency-cutoff word dict from text, map
    documents to id sequences."""

    def _build_dict(self, texts: List[str], cutoff: int = 0):
        freq = {}
        for t in texts:
            for w in t.split():
                freq[w] = freq.get(w, 0) + 1
        words = sorted([w for w, c in freq.items() if c > cutoff],
                       key=lambda w: (-freq[w], w))
        return {w: i for i, w in enumerate(words)}


class Imdb(_TokenizedCorpus):
    """IMDB sentiment (reference imdb.py): records = (token-ids, label)."""

    URL = "https://dataset.bj.bcebos.com/imdb%2FaclImdb_v1.tar.gz"

    def __init__(self, data_file=None, mode="train", cutoff=150, download=True):
        assert mode in ("train", "test")
        path = _require(data_file, self.URL, "Imdb")
        pat = re.compile(rf"aclImdb/{mode}/(pos|neg)/.*\.txt$")
        texts, labels = [], []
        with tarfile.open(path) as tf:
            for m in tf.getmembers():
                mm = pat.match(m.name)
                if mm:
                    texts.append(tf.extractfile(m).read().decode("utf-8", "ignore").lower())
                    labels.append(0 if mm.group(1) == "pos" else 1)
        self.word_idx = self._build_dict(texts, cutoff)
        unk = len(self.word_idx)
        self.docs = [np.array([self.word_idx.get(w, unk) for w in t.split()], np.int64) for t in texts]
        self.labels = np.array(labels, np.int64)

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]

    def __len__(self):
        return len(self.docs)


class Imikolov(_TokenizedCorpus):
    """PTB n-gram LM dataset (reference imikolov.py): records = n-gram tuple."""

    URL = "https://dataset.bj.bcebos.com/imikolov%2Fsimple-examples.tgz"

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5, mode="train", min_word_freq=50, download=True):
        assert data_type in ("NGRAM", "SEQ")
        path = _require(data_file, self.URL, "Imikolov")
        name = f"simple-examples/data/ptb.{'train' if mode == 'train' else 'valid'}.txt"
        with tarfile.open(path) as tf:
            lines = tf.extractfile(name).read().decode().strip().split("\n")
        self.word_idx = self._build_dict(lines, min_word_freq)
        for tok in ("<s>", "<e>", "<unk>"):
            self.word_idx.setdefault(tok, len(self.word_idx))
        unk = self.word_idx["<unk>"]
        self.data = []
        for line in lines:
            ids = [self.word_idx["<s>"]] + [self.word_idx.get(w, unk) for w in line.split()] + [self.word_idx["<e>"]]
            if data_type == "NGRAM":
                for i in range(window_size, len(ids) + 1):
                    self.data.append(np.array(ids[i - window_size:i], np.int64))
            else:
                self.data.append((np.array(ids[:-1], np.int64), np.array(ids[1:], np.int64)))

    def __getitem__(self, idx):
        return self.data[idx]

    def __len__(self):
        return len(self.data)


class UCIHousing(Dataset):
    """Boston housing regression (reference uci_housing.py): records =
    (13 normalized features f32, price f32). 80/20 train/test split."""

    URL = "http://paddlemodels.bj.bcebos.com/uci_housing/housing.data"

    def __init__(self, data_file=None, mode="train", download=True):
        assert mode in ("train", "test")
        path = _require(data_file, self.URL, "UCIHousing")
        raw = np.loadtxt(path).astype(np.float32)
        feats, prices = raw[:, :-1], raw[:, -1:]
        mn, mx, avg = feats.min(0), feats.max(0), feats.mean(0)
        feats = (feats - avg) / np.maximum(mx - mn, 1e-6)
        split = int(len(raw) * 0.8)
        sl = slice(0, split) if mode == "train" else slice(split, None)
        self.data = feats[sl]
        self.label = prices[sl]

    def __getitem__(self, idx):
        return self.data[idx], self.label[idx]

    def __len__(self):
        return len(self.data)


class _NotDownloadable(Dataset):
    URL = ""
    NAME = ""

    def __init__(self, data_file=None, **kwargs):
        _require(data_file, self.URL, self.NAME)
        raise NotImplementedError(
            f"{self.NAME}: archive parsing not implemented in this build; "
            "the record schema matches the reference — contributions via "
            "paddle_tpu.text.datasets")


class Conll05st(_NotDownloadable):
    URL = "https://dataset.bj.bcebos.com/conll05st/conll05st-tests.tar.gz"
    NAME = "Conll05st"


class Movielens(_NotDownloadable):
    URL = "https://dataset.bj.bcebos.com/movielens%2Fml-1m.zip"
    NAME = "Movielens"


class WMT14(_NotDownloadable):
    URL = "http://paddlemodels.bj.bcebos.com/wmt/wmt14.tgz"
    NAME = "WMT14"


class WMT16(_NotDownloadable):
    URL = "http://paddlepaddle.bj.bcebos.com/dataset/wmt_16.tar.gz"
    NAME = "WMT16"
