"""paddle.text parity: viterbi decoding + the dataset classes.

Parity: python/paddle/text/viterbi_decode.py (ViterbiDecoder over the
viterbi_decode CRF op, paddle/fluid/operators/viterbi_decode_op.*) and
python/paddle/text/datasets/* (IMDB, Imikolov, Conll05, MovieLens,
UCIHousing, WMT14/16 download-backed map-style datasets).

TPU-first: the decode DP is a ``lax.scan`` over time steps (argmax
backpointers carried as int32), one XLA computation for the whole batch —
no per-step host loop. Datasets read from a local ``data_file`` (this
environment has no egress; the reference's auto-download becomes an
explicit file argument with the same record schema).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..nn.layer.base import Layer
from ..tensor._helpers import ensure_tensor, op
from . import datasets  # noqa: F401

__all__ = ["viterbi_decode", "ViterbiDecoder", "datasets"]


def _viterbi_raw(pot, trans, lengths, include_bos_eos_tag):
    """pot [b, T, n] f32; trans [n, n]; lengths [b] int — (scores, paths)."""
    b, T, n = pot.shape
    lengths = lengths.astype(jnp.int32)

    if include_bos_eos_tag:
        # last tag = BOS (start), second-to-last = EOS (stop): sequences start
        # from BOS-transitions and end with EOS-transitions (reference op attr)
        bos, eos = n - 1, n - 2
        alpha0 = pot[:, 0] + trans[bos][None, :]
    else:
        alpha0 = pot[:, 0]

    def step(carry, t):
        alpha, hist_dummy = carry
        # scores[b, i, j] = alpha[b, i] + trans[i, j] + pot[b, t, j]
        scores = alpha[:, :, None] + trans[None]
        best_prev = jnp.argmax(scores, axis=1).astype(jnp.int32)  # [b, n]
        best_score = jnp.max(scores, axis=1) + pot[:, t]
        # positions past a sequence's length keep its alpha frozen
        active = (t < lengths)[:, None]
        alpha_new = jnp.where(active, best_score, alpha)
        bp = jnp.where(active, best_prev, jnp.arange(n, dtype=jnp.int32)[None])
        return (alpha_new, hist_dummy), bp

    (alpha, _), bps = jax.lax.scan(step, (alpha0, jnp.int32(0)), jnp.arange(1, T))
    # bps: [T-1, b, n]
    if include_bos_eos_tag:
        alpha = alpha + trans[:, eos][None, :]
    scores = jnp.max(alpha, axis=-1)
    last_tag = jnp.argmax(alpha, axis=-1).astype(jnp.int32)  # [b]

    def back(carry, bp):
        tag = carry
        prev = jnp.take_along_axis(bp, tag[:, None], axis=1)[:, 0]
        return prev, tag

    first_tag, path_rev = jax.lax.scan(back, last_tag, bps, reverse=True)
    # reverse scan yields tag_t for t=1..T-1 in forward order; the final
    # carry is tag_0
    paths = jnp.concatenate([first_tag[None], path_rev], axis=0).T  # [b, T]
    # mask out positions beyond each length (reference emits only length
    # tokens; static shapes here, so the tail repeats the last valid tag)
    idx = jnp.arange(T, dtype=jnp.int32)[None]
    paths = jnp.where(idx < lengths[:, None], paths, 0)
    return scores, paths.astype(jnp.int64)


def viterbi_decode(potentials, transition_params, lengths, include_bos_eos_tag=True, name=None):
    """Highest-scoring tag sequence under unary ``potentials`` + CRF
    ``transition_params``. Returns (scores [b], paths [b, T] int64)."""
    return op(
        lambda p, t, l: _viterbi_raw(p, t, l, include_bos_eos_tag),
        ensure_tensor(potentials), ensure_tensor(transition_params), ensure_tensor(lengths),
        _name="viterbi_decode")


class ViterbiDecoder(Layer):
    """Layer form (reference text/viterbi_decode.py ViterbiDecoder)."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        super().__init__()
        self.transitions = ensure_tensor(transitions)
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths, self.include_bos_eos_tag)
