"""Secondary benchmark suite for the BASELINE.md north-star configs.

``bench.py`` stays the driver's single-line flagship metric; this suite
measures the other configs on demand:

    python bench_suite.py mnist            # LeNet eager + jit steps/sec
    python bench_suite.py resnet50 [batch] # jit train step images/sec (AMP O2)
    python bench_suite.py bert             # BERT-base MLM tokens/sec (AMP O2)
    python bench_suite.py decode [batch]   # GPT-medium generate() tokens/sec

Each subcommand prints one JSON line. Reference analog: the external
benchmark suite cloned by tools/ci_model_benchmark.sh:50.
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np


def _time_steps(fn, warmup=3, iters=20, sync=None):
    for _ in range(warmup):
        out = fn()
    if sync:
        sync(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn()
    if sync:
        sync(out)
    return iters / (time.perf_counter() - t0)


def bench_mnist():
    import paddle_tpu as paddle
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models.lenet import LeNet

    paddle.seed(0)
    m = LeNet()
    opt = paddle.optimizer.Momentum(learning_rate=0.01, parameters=m.parameters())
    loss_fn = paddle.nn.CrossEntropyLoss()
    x = paddle.to_tensor(np.random.default_rng(0).normal(size=(64, 1, 28, 28)).astype("float32"))
    y = paddle.to_tensor(np.random.default_rng(1).integers(0, 10, (64,)).astype("int64"))

    def eager_step():
        loss = loss_fn(m(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    eager_sps = _time_steps(eager_step, warmup=3, iters=20, sync=float)
    step = TrainStep(m, opt, loss_fn)
    jit_sps = _time_steps(lambda: step(x, y), warmup=3, iters=200, sync=lambda o: float(o["loss"]))
    return {"metric": "mnist_lenet_steps_per_sec", "eager": round(eager_sps, 2), "jit": round(jit_sps, 1), "batch": 64}


def bench_resnet50(batch=128):
    import paddle_tpu as paddle
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.vision.models import resnet50

    paddle.seed(0)
    m = resnet50(num_classes=1000)
    opt = paddle.optimizer.Momentum(learning_rate=0.1, parameters=m.parameters())
    loss_fn = paddle.nn.CrossEntropyLoss()
    x = paddle.to_tensor(np.random.default_rng(0).normal(size=(batch, 3, 224, 224)).astype("float32"))
    y = paddle.to_tensor(np.random.default_rng(1).integers(0, 1000, (batch,)).astype("int64"))
    step = TrainStep(m, opt, loss_fn, amp_level="O2")
    sps = _time_steps(lambda: step(x, y), warmup=3, iters=20, sync=lambda o: float(o["loss"]))
    return {"metric": "resnet50_images_per_sec", "value": round(batch * sps, 1), "batch": batch, "amp": "O2"}


def bench_bert():
    import paddle_tpu as paddle
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models.bert import BertConfig, BertForPretraining, BertPretrainingCriterion

    paddle.seed(0)
    cfg = BertConfig()
    m = BertForPretraining(cfg)
    crit = BertPretrainingCriterion()
    opt = paddle.optimizer.AdamW(learning_rate=1e-4, parameters=m.parameters())
    b, s = 16, 512

    def loss_fn(outs, mlm_labels, nsp_labels):
        mlm, nsp = outs
        return crit(mlm, nsp, mlm_labels, nsp_labels)

    ids = paddle.to_tensor(np.random.default_rng(0).integers(0, cfg.vocab_size, (b, s)).astype("int32"))
    labels = np.full((b, s), -100, "int32")
    labels[:, :64] = np.random.default_rng(1).integers(0, cfg.vocab_size, (b, 64))
    mlm_y = paddle.to_tensor(labels)
    nsp_y = paddle.to_tensor(np.random.default_rng(2).integers(0, 2, (b,)).astype("int64"))
    step = TrainStep(m, opt, loss_fn, amp_level="O2")
    sps = _time_steps(lambda: step(ids, (mlm_y, nsp_y)), warmup=3, iters=15, sync=lambda o: float(o["loss"]))
    return {"metric": "bert_base_mlm_tokens_per_sec", "value": round(b * s * sps), "batch": b, "seq": s, "amp": "O2"}


def bench_decode(batch=8):
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import GPTConfig, GPTForPretraining

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=50304, hidden_size=1024, num_layers=16, num_heads=16, max_seq_len=1024)
    m = GPTForPretraining(cfg)
    m.eval()
    for _, p in m.named_parameters():
        p._value = p._value.astype(jnp.bfloat16)
    prompt = paddle.to_tensor(np.random.default_rng(0).integers(0, cfg.vocab_size, (batch, 128)).astype("int32"))
    new = 384
    _ = m.generate(prompt, max_new_tokens=new).numpy()
    t0 = time.perf_counter()
    for _ in range(3):
        out = m.generate(prompt, max_new_tokens=new)
    _ = out.numpy()
    dt = (time.perf_counter() - t0) / 3
    return {"metric": "gpt_decode_tokens_per_sec", "value": round(batch * new / dt), "batch": batch, "prompt": 128, "new_tokens": new, "dtype": "bf16"}


def main():
    which = sys.argv[1] if len(sys.argv) > 1 else "mnist"
    arg = int(sys.argv[2]) if len(sys.argv) > 2 else None
    if which == "mnist":
        out = bench_mnist()
    elif which == "resnet50":
        out = bench_resnet50(arg or 128)
    elif which == "bert":
        out = bench_bert()
    elif which == "decode":
        out = bench_decode(arg or 8)
    else:
        raise SystemExit(f"unknown benchmark {which!r}")
    print(json.dumps(out))


if __name__ == "__main__":
    main()
