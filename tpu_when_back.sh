#!/bin/bash
# Fire the full on-chip verification + measurement sequence the moment the
# tunnel answers. Serial (one TPU process at a time); everything logs to
# tpu_results.log for BASELINE.md transcription.
set -u
cd /root/repo
LOG=tpu_results.log
run() {
  echo "=== $* === $(date -u +%H:%M:%S)" | tee -a $LOG
  timeout "${T:-900}" "$@" 2>&1 | grep -v xla_bridge | tee -a $LOG
}
echo "==== session $(date -u) ====" | tee -a $LOG
T=600  run python tpu_runbook.py flat      # kernel parity (incl. new hg shapes)
T=700  run python tpu_runbook.py step      # flagship A/B: flag off vs on
T=1500 run python tpu_runbook.py sweep     # block-size tune (persists cache)
T=700  run python tpu_runbook.py step      # re-A/B with tuned blocks
T=700  run python tpu_runbook.py decode    # decode throughput row
T=2400 run python bench_1p3b.py tpu        # BASELINE row 4
T=1200 run python bench_1p3b.py tpu-ernie  # BASELINE row 5
T=1500 run python bench.py                 # headline (self-selecting)
echo "==== done $(date -u) ====" | tee -a $LOG
