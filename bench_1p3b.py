"""Flagship-config proof runs (BASELINE.md rows 4 and 5).

Modes:
  python bench_1p3b.py cpu-mesh   — full GPT-3 1.3B hybrid (dp2 x mp2 x pp2,
      ZeRO stage-2 over sdp where factored) ONE step on the 8-device virtual
      CPU mesh at full layer/hidden dims, tiny batch: proves the sharded
      compile + memory plan without TPU hardware.
  python bench_1p3b.py tpu        — single real chip: 1.3B with selective
      remat + grad accumulation + bf16 AMP O2, measured tokens/sec/chip.
  python bench_1p3b.py tpu-ernie  — ERNIE-3.0-style hybrid config #5 proxy on
      one chip (same trunk machinery; mp/pp degrees are mesh-bound, so the
      single-chip number is the per-chip throughput of the dp slice).

Each mode prints one JSON line.
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np


def _cpu_mesh_step():
    import os

    import jax

    jax.config.update("jax_platforms", "cpu")
    os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"

    import paddle_tpu as paddle
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.strategy import DistributedStrategy
    from paddle_tpu.models.gpt import GPTConfig, GPTForPretraining, GPTPretrainingCriterion

    strat = DistributedStrategy()
    strat.hybrid_configs = {"dp_degree": 1, "sharding_degree": 2, "mp_degree": 2, "pp_degree": 2}
    strat.sharding = True
    strat.sharding_configs = {"sharding_stage": 2}
    strat.pipeline_configs = {"accumulate_steps": 2, "schedule": "1f1b"}
    fleet.init(is_collective=True, strategy=strat)

    paddle.seed(0)
    cfg = GPTConfig.gpt3_1p3b(max_seq_len=256)  # full width/depth, short seq
    model = GPTForPretraining(cfg)
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    opt = paddle.optimizer.AdamW(learning_rate=1e-4, parameters=model.parameters())
    step = fleet.distributed_step(model, opt, GPTPretrainingCriterion())
    ids = fleet.shard_batch(paddle.to_tensor(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (4, 256)).astype("int32")))
    t0 = time.time()
    loss = float(step(ids, ids)["loss"])
    print(json.dumps({
        "metric": "gpt3_1p3b_hybrid_cpu_mesh_step", "params": n_params,
        "mesh": "sdp2xmp2xpp2+zero2", "loss": round(loss, 4),
        "step_wall_s": round(time.time() - t0, 1), "ok": bool(np.isfinite(loss)),
    }))


def _tpu_run(ernie=False):
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models.gpt import GPTConfig, GPTForPretraining, GPTPretrainingCriterion

    paddle.seed(0)
    rng = np.random.default_rng(0)
    if ernie:
        # the REAL ERNIE family (models/ernie.py): 3.0-xbase shape, MLM+SOP
        from paddle_tpu.models.ernie import (
            ErnieConfig,
            ErnieForPretraining,
            ErniePretrainingCriterion,
        )

        cfg = ErnieConfig.ernie3_xbase(vocab_size=40000)
        model = ErnieForPretraining(cfg)

        class Crit(paddle.nn.Layer):
            def __init__(self):
                super().__init__()
                self.c = ErniePretrainingCriterion()

            def forward(self, outs, mlm_labels, sop_labels):
                return self.c(outs[0], outs[1], mlm_labels, sop_labels)

        crit = Crit()
        batch, seq, accum, iters = 16, 512, 1, 8
        name, config = "ernie3_xbase_throughput", f"b16xs512 bf16-O2 MLM+SOP"
        ids = rng.integers(0, cfg.vocab_size, (batch, seq)).astype("int32")
        mlm = ids.copy()
        mlm[:, ::2] = -100  # odd positions are the masked targets
        labels = (paddle.to_tensor(mlm.astype("int64")),
                  paddle.to_tensor(rng.integers(0, 2, (batch,)).astype("int64")))
    else:
        cfg = GPTConfig.gpt3_1p3b(recompute=True, recompute_granularity="selective")
        model = GPTForPretraining(cfg)
        crit = GPTPretrainingCriterion()
        batch, seq, accum, iters = 4, 2048, 2, 6
        name = "gpt3_1p3b_throughput"
        config = f"b{batch}xs{seq} accum{accum} bf16-O2 remat=selective"
        ids = rng.integers(0, cfg.vocab_size, (batch, seq)).astype("int32")
        labels = paddle.to_tensor(ids)

    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    opt = paddle.optimizer.AdamW(learning_rate=1e-4, parameters=model.parameters())
    step = TrainStep(model, opt, crit, amp_level="O2", accumulate_steps=accum)
    t = paddle.to_tensor(ids)
    for _ in range(2):
        out = step(t, labels)
    float(out["loss"])
    t0 = time.perf_counter()
    for _ in range(iters):
        out = step(t, labels)
    float(out["loss"])
    dt = time.perf_counter() - t0
    print(json.dumps({
        "metric": name, "params": n_params,
        "value": round(batch * seq * iters / dt, 1), "unit": "tokens/sec/chip",
        "config": config,
    }))


if __name__ == "__main__":
    mode = sys.argv[1] if len(sys.argv) > 1 else "cpu-mesh"
    if mode == "cpu-mesh":
        _cpu_mesh_step()
    elif mode == "tpu":
        _tpu_run(False)
    elif mode == "tpu-ernie":
        _tpu_run(True)
    else:
        raise SystemExit(f"unknown mode {mode}")
