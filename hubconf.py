"""paddle.hub entry for this repo: `paddle.hub.load('/path/to/repo', 'resnet50', source='local')`."""


def _vision(name):
    def factory(pretrained=False, **kwargs):
        import paddle_tpu as paddle

        return getattr(paddle.vision.models, name)(**kwargs)

    factory.__name__ = name
    factory.__doc__ = f"paddle_tpu.vision.models.{name}"
    return factory


lenet = _vision("LeNet")
resnet18 = _vision("resnet18")
resnet50 = _vision("resnet50")
vgg16 = _vision("vgg16")
mobilenet_v2 = _vision("mobilenet_v2")


def gpt_tiny(**kwargs):
    """Tiny GPT for smoke tests (models/gpt.py GPTConfig.tiny)."""
    from paddle_tpu.models.gpt import GPTConfig, GPTForPretraining

    return GPTForPretraining(GPTConfig.tiny())
