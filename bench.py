"""Flagship benchmark: GPT pretraining tokens/sec/chip on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
The reference publishes no in-tree numbers (BASELINE.md), so vs_baseline is
measured against this repo's own recorded prior (bench_baseline.json, written
on first run) — a regression gate in the spirit of tools/ci_op_benchmark.sh.
"""
from __future__ import annotations

import json
import os
import time

import numpy as np


def main():
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models.gpt import GPTConfig, GPTForPretraining, GPTPretrainingCriterion

    d0 = jax.devices()[0]
    # the axon tunnel reports platform 'axon' with device_kind 'TPU v5 lite'
    on_tpu = d0.platform in ("tpu", "axon") or "TPU" in getattr(d0, "device_kind", "")
    # sized to fit+stress one chip; tiny fallback for CPU smoke runs
    if on_tpu:
        cfg = GPTConfig(vocab_size=50304, hidden_size=1024, num_layers=16, num_heads=16, max_seq_len=1024)
        batch, seq, iters = 8, 1024, 20
    else:
        cfg = GPTConfig.tiny()
        batch, seq, iters = 8, 64, 5

    paddle.seed(0)
    model = GPTForPretraining(cfg)
    crit = GPTPretrainingCriterion()
    opt = paddle.optimizer.AdamW(learning_rate=1e-4, parameters=model.parameters())
    # bf16 compute with f32 master weights (TPU-native AMP O2) + Pallas flash
    # attention (fwd+bwd); measured 52.2k tok/s/chip vs 30.5k f32 on v5lite
    amp_level = "O2" if on_tpu else None
    step = TrainStep(model, opt, crit, amp_level=amp_level)

    ids = np.random.default_rng(0).integers(0, cfg.vocab_size, (batch, seq)).astype("int32")
    t = paddle.to_tensor(ids)

    # warmup (compile) + 3 steps; float() is a host transfer = hard sync
    # (block_until_ready on a dict does not wait under the axon tunnel)
    for _ in range(3):
        out = step(t, t)
    float(out["loss"])

    t0 = time.perf_counter()
    for _ in range(iters):
        out = step(t, t)
    float(out["loss"])  # last loss depends on the whole state chain
    dt = time.perf_counter() - t0

    tokens_per_sec = batch * seq * iters / dt
    config_key = f"{d0.device_kind or d0.platform}/h{cfg.hidden_size}L{cfg.num_layers}b{batch}s{seq}/amp={amp_level}"
    base_path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "bench_baseline.json")
    vs = 1.0
    if os.path.exists(base_path):
        try:
            prior = json.load(open(base_path))
            # only compare like-for-like (same device kind + model config)
            if prior.get("config") == config_key and prior.get("value"):
                vs = tokens_per_sec / prior["value"]
        except Exception:
            pass
    else:
        json.dump({"metric": "gpt_pretrain_throughput", "value": tokens_per_sec, "unit": "tokens/sec/chip", "config": config_key}, open(base_path, "w"))

    print(json.dumps({
        "metric": "gpt_pretrain_throughput",
        "value": round(tokens_per_sec, 2),
        "unit": "tokens/sec/chip",
        "vs_baseline": round(vs, 4),
    }))


if __name__ == "__main__":
    main()
