"""Flagship benchmark: GPT pretraining tokens/sec/chip on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
The reference publishes no in-tree numbers (BASELINE.md), so vs_baseline is
measured against this repo's own recorded prior (bench_baseline.json, written
on first run) — a regression gate in the spirit of tools/ci_op_benchmark.sh.

On TPU the flagship step is measured on both attention-kernel paths — the
classic [b,h,s,d] pair and the flat-lane zero-relayout kernels
(FLAGS_flash_flat) — and the faster one is reported. The flat measurement
runs in a subprocess with a timeout so a pathological compile can never hang
the benchmark.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np


def _measure(flash_flat: bool):
    t_measure_start = time.perf_counter()
    import jax

    if os.environ.get("BENCH_FORCE_CPU"):
        # graceful TPU-absent fallback: the parent observed an unreachable
        # accelerator, so this child flips to the CPU platform BEFORE any
        # backend initializes (env vars alone are too late — sitecustomize
        # may have pre-registered the TPU platform)
        jax.config.update("jax_platforms", "cpu")

    import paddle_tpu as paddle
    from paddle_tpu.framework.flags import _REGISTRY
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models.gpt import GPTConfig, GPTForPretraining, GPTPretrainingCriterion

    _REGISTRY["FLAGS_flash_flat"] = flash_flat
    if flash_flat:
        # apply block sizes tuned by `tpu_runbook.py sweep` (no-op if absent)
        from paddle_tpu.incubate import autotune

        autotune.load_tuned(shape=(8, 1024, 16, 64),
                            cache_path=os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                                    ".autotune_cache.json"))
    d0 = jax.devices()[0]
    # the axon tunnel reports platform 'axon' with device_kind 'TPU v5 lite'
    on_tpu = d0.platform in ("tpu", "axon") or "TPU" in getattr(d0, "device_kind", "")
    # sized to fit+stress one chip; tiny fallback for CPU smoke runs
    if on_tpu:
        cfg = GPTConfig(vocab_size=50304, hidden_size=1024, num_layers=16, num_heads=16, max_seq_len=1024)
        batch, seq, iters = 8, 1024, 20
    else:
        cfg = GPTConfig.tiny()
        batch, seq, iters = 8, 64, 5

    paddle.seed(0)
    model = GPTForPretraining(cfg)
    crit = GPTPretrainingCriterion()
    opt = paddle.optimizer.AdamW(learning_rate=1e-4, parameters=model.parameters())
    # bf16 compute with f32 master weights (TPU-native AMP O2) + Pallas flash
    # attention (fwd+bwd)
    amp_level = "O2" if on_tpu else None
    step = TrainStep(model, opt, crit, amp_level=amp_level)

    ids = np.random.default_rng(0).integers(0, cfg.vocab_size, (batch, seq)).astype("int32")
    t = paddle.to_tensor(ids)

    # warmup (compile) + 3 steps; float() is a host transfer = hard sync
    # (block_until_ready on a dict does not wait under the axon tunnel)
    time_to_first_step = None
    for i in range(3):
        out = step(t, t)
        if i == 0:
            # restart-latency metric: import + build + trace + compile +
            # first dispatch, synced — what an elastic event or rollback
            # actually pays before training resumes
            float(out["loss"])
            time_to_first_step = time.perf_counter() - t_measure_start
    float(out["loss"])

    t0 = time.perf_counter()
    for _ in range(iters):
        out = step(t, t)
    float(out["loss"])  # last loss depends on the whole state chain
    dt = time.perf_counter() - t0

    tokens_per_sec = batch * seq * iters / dt
    steps_per_sec = iters / dt

    # dispatch-amortized multi-step path: K fused steps per Python dispatch
    # (lax.scan over the step body, state donated) — same model/state
    from paddle_tpu import profiler

    K = 8
    stacked = (np.stack([ids] * K), np.stack([ids] * K))
    out = step.run_steps(stacked, k=K)  # warmup compile
    float(np.asarray(out["loss"]._value)[-1])
    profiler.reset_counters("train_step.")
    groups = max(1, iters // K)
    t0 = time.perf_counter()
    for _ in range(groups):
        out = step.run_steps(stacked, k=K)
    float(np.asarray(out["loss"]._value)[-1])
    dt_fused = time.perf_counter() - t0
    counts = profiler.counters("train_step.")
    extras = {
        "steps_per_sec": round(steps_per_sec, 3),
        "steps_per_sec_fused": round(groups * K / dt_fused, 3),
        "dispatches_per_step": round(
            counts["train_step.dispatches"] / counts["train_step.steps"], 4),
        "time_to_first_step": round(time_to_first_step, 3),
    }
    if not on_tpu:
        # training-health guard overhead on the fused tiny-GPT microbench
        # (CPU smoke path; the in-graph finite checks + where-selects must
        # stay <2% of fused steps/sec — tracked via BENCH_* history).
        # Measured SYMMETRICALLY: both sides warm, interleaved repeats of
        # the same K-step dispatch, best-of taken per side — a single
        # dispatch timing is ±10% noise on CPU.
        paddle.seed(0)
        model_g = GPTForPretraining(cfg)
        opt_g = paddle.optimizer.AdamW(learning_rate=1e-4, parameters=model_g.parameters())
        step_g = TrainStep(model_g, opt_g, crit, amp_level=amp_level, guard=True)
        out = step_g.run_steps(stacked, k=K)  # warmup compile
        float(np.asarray(out["loss"]._value)[-1])

        def _time_fused(s, reps=8):
            t0 = time.perf_counter()
            for _ in range(reps):
                o = s.run_steps(stacked, k=K)
            float(np.asarray(o["loss"]._value)[-1])
            return (time.perf_counter() - t0) / reps

        base_dt, guard_dt = [], []
        for _ in range(4):  # interleave so drift hits both sides equally
            base_dt.append(_time_fused(step))
            guard_dt.append(_time_fused(step_g))
        base_sps = K / min(base_dt)
        guarded_sps = K / min(guard_dt)
        extras["steps_per_sec_fused_guarded"] = round(guarded_sps, 3)
        extras["guard_overhead_pct"] = round(
            100.0 * (1.0 - guarded_sps / base_sps), 2)
        # dispatch-sanitizer overhead (FLAGS_sanitize runtime guards:
        # transfer_guard scope + recompile-churn sentinel + donated-state
        # sweep) on the same fused microbench, same symmetric interleaved
        # best-of protocol as the guard arm; budget is <2% of fused sps.
        # host_transfers_per_step must be 0.0 — the hot path never syncs.
        from paddle_tpu.analysis import sanitizer as _sanitizer
        from paddle_tpu.observability.metrics import counters as _san_counters

        paddle.seed(0)
        model_s = GPTForPretraining(cfg)
        opt_s = paddle.optimizer.AdamW(
            learning_rate=1e-4, parameters=model_s.parameters())
        step_s = TrainStep(model_s, opt_s, crit, amp_level=amp_level)
        _sanitizer.reset()
        prev_san = _REGISTRY.get("FLAGS_sanitize", False)
        try:
            _REGISTRY["FLAGS_sanitize"] = True
            out = step_s.run_steps(stacked, k=K)  # warmup compile
            float(np.asarray(out["loss"]._value)[-1])
            _REGISTRY["FLAGS_sanitize"] = False
            base2_dt, san_dt = [], []
            ht0 = _san_counters().get("sanitizer.host_transfers", 0)
            for _ in range(4):  # interleave: drift hits both sides equally
                base2_dt.append(_time_fused(step))
                _REGISTRY["FLAGS_sanitize"] = True
                san_dt.append(_time_fused(step_s))
                _REGISTRY["FLAGS_sanitize"] = False
            san_steps = 4 * 8 * K  # rounds * reps * fused K
            extras["host_transfers_per_step"] = round(
                (_san_counters().get("sanitizer.host_transfers", 0) - ht0)
                / san_steps, 4)
            san_sps = K / min(san_dt)
            extras["steps_per_sec_fused_sanitized"] = round(san_sps, 3)
            extras["sanitize_overhead_pct"] = round(
                100.0 * (1.0 - san_sps / (K / min(base2_dt))), 2)
        finally:
            _REGISTRY["FLAGS_sanitize"] = prev_san
    from paddle_tpu.observability.metrics import counters as _counters

    stab = _counters()
    extras["skipped_steps"] = stab.get("train_step.skipped", 0) + stab.get(
        "amp.skipped_steps", 0)
    extras["rollbacks"] = stab.get("stability.rollbacks", 0)
    # auto-parallel planner: search the (single-chip here) plan space from
    # shapes alone and compare its roofline step-time prediction against
    # the measured fused step — the calibration record for the
    # cost-model-driven search (distributed/planner.py)
    try:
        from paddle_tpu.distributed import planner as _planner

        t_plan = time.perf_counter()
        plans = _planner.search(
            model, len(jax.devices()), loss=crit,
            optimizer=paddle.optimizer.AdamW(
                learning_rate=1e-4, parameters=model.parameters()),
            inputs_spec=jax.ShapeDtypeStruct((batch, seq), np.int32),
            cache=False)
        best = next((p for p in plans if p.feasible), None)
        if best is not None:
            extras["plan"] = {
                "search_ms": round((time.perf_counter() - t_plan) * 1e3, 1),
                "candidates": len(plans),
                "chosen": best.label,
                "predicted_step_ms": best.predicted_step_ms,
                "measured_step_ms": round(1e3 * dt_fused / (groups * K), 3),
                "comm_bytes": best.comm_bytes,
                "peak_bytes": best.peak_bytes,
            }
    except Exception as exc:  # the planner must never sink the benchmark
        extras["plan"] = {"error": f"{type(exc).__name__}: {exc}"}
    # warm-restart time_to_first_step: with FLAGS_compile_cache_dir set the
    # compiled step executable round-trips through the AOT training cache,
    # so a rebuilt TrainStep (the restart path) skips straight to dispatch
    try:
        import tempfile as _tempfile

        from paddle_tpu.framework.flags import flag as _flag2

        cache_was = _flag2("FLAGS_compile_cache_dir")
        cache_dir = cache_was or _tempfile.mkdtemp(prefix="bench_aot_")
        paddle.set_flags({"FLAGS_compile_cache_dir": cache_dir})
        paddle.seed(0)
        model_w = GPTForPretraining(cfg)
        opt_w = paddle.optimizer.AdamW(learning_rate=1e-4, parameters=model_w.parameters())
        TrainStep(model_w, opt_w, crit, amp_level=amp_level)(t, t)  # store
        # drop the in-process executable memo so the timed rebuild loads
        # from DISK — what a real process restart pays
        from paddle_tpu.observability.introspect import _EXEC_MEMO

        _EXEC_MEMO.clear()
        t_warm = time.perf_counter()
        paddle.seed(0)
        model_w2 = GPTForPretraining(cfg)
        opt_w2 = paddle.optimizer.AdamW(learning_rate=1e-4, parameters=model_w2.parameters())
        step_w = TrainStep(model_w2, opt_w2, crit, amp_level=amp_level)
        float(step_w(t, t)["loss"])
        extras["time_to_first_step_warm"] = round(time.perf_counter() - t_warm, 3)
        extras["warm_restart_aot_hits"] = _counters().get(
            "train_step.aot_cache_hits", 0)
        paddle.set_flags({"FLAGS_compile_cache_dir": cache_was})
    except Exception as exc:
        extras["time_to_first_step_warm"] = None
        extras.setdefault("plan", {})["warm_error"] = f"{type(exc).__name__}"
    # observability snapshot: dispatch counters + span-histogram summaries
    # (p50/p90/p99 step/compile timings), plus the per-specialization XLA
    # cost rows behind TrainStep.explain()
    from paddle_tpu import observability

    snap = observability.metrics.snapshot()
    extras["metrics"] = {
        "counters": {k: v for k, v in snap["counters"].items() if v},
        "histograms": snap["histograms"],
    }
    cost_rows = step.explain(analyze=True)
    if cost_rows:
        extras["cost"] = {k: cost_rows[0].get(k) for k in
                          ("flops", "bytes_accessed", "peak_bytes",
                           "compile_seconds")}
        # SPMD analyzer verdict for the first training specialization
        # (collective counts by kind, est. reshard bytes per dispatch, peak
        # per-device memory estimate) — the planner-facing summary
        spmd = cost_rows[0].get("spmd")
        if spmd:
            extras["spmd"] = {k: spmd.get(k) for k in
                              ("collectives", "reshard_bytes", "peak_bytes",
                               "codes")}
        # stdout carries only the JSON result line; the table is operator aid
        print(observability.format_cost_table(cost_rows), file=sys.stderr)
    config_key = f"{d0.device_kind or d0.platform}/h{cfg.hidden_size}L{cfg.num_layers}b{batch}s{seq}/amp={amp_level}"
    return tokens_per_sec, config_key, on_tpu, extras


def _measure_moe(_flat_unused=False):
    """GPT-MoE training throughput on BOTH ``moe`` kernel paths: the fused
    sort-based Pallas dispatch/combine (interpret mode on CPU) vs the dense
    one-hot/einsum composite, forced per run via FLAGS_kernel_overrides and
    exercised inside the donated ``run_steps`` scan. Reports
    ``moe_tokens_per_sec`` (fused) / ``moe_tokens_per_sec_dense`` and the
    registry-selection pin (``kernels.moe.picked`` == compile count)."""
    import jax

    if os.environ.get("BENCH_FORCE_CPU"):
        jax.config.update("jax_platforms", "cpu")

    import paddle_tpu as paddle
    from paddle_tpu.framework.flags import _REGISTRY
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models.gpt import GPTConfig, GPTForPretraining, GPTPretrainingCriterion
    from paddle_tpu.observability import metrics as _metrics
    from paddle_tpu.ops import moe_pallas

    d0 = jax.devices()[0]
    on_tpu = d0.platform in ("tpu", "axon") or "TPU" in getattr(d0, "device_kind", "")
    # capacity factor 2.0 = GShard's canonical top-2 train setting (each
    # token may dispatch to both experts without forced drops)
    if on_tpu:
        cfg = dict(vocab_size=50304, hidden_size=1024, num_layers=8, num_heads=16,
                   max_seq_len=1024, moe=8, moe_every=2, moe_capacity_factor=2.0)
        batch, seq, K, reps = 8, 1024, 4, 4
    else:
        moe_pallas.set_interpret(True)  # CPU: interpret-mode kernel lowering
        cfg = dict(vocab_size=512, hidden_size=128, num_layers=2, num_heads=4,
                   max_seq_len=256, moe=64, moe_every=1, ffn_hidden_size=1024,
                   moe_capacity_factor=2.0)
        batch, seq, K, reps = 8, 256, 2, 8

    ids = np.random.default_rng(0).integers(0, cfg["vocab_size"], (batch, seq)).astype("int32")
    stacked = (np.stack([ids] * K), np.stack([ids] * K))
    crit = GPTPretrainingCriterion()

    steps = {}
    for path in ("dense", "pallas_sorted"):
        _REGISTRY["FLAGS_kernel_overrides"] = f"moe={path}"
        paddle.seed(0)
        model = GPTForPretraining(GPTConfig(**cfg))
        opt = paddle.optimizer.AdamW(learning_rate=1e-4, parameters=model.parameters())
        step = TrainStep(model, opt, crit)
        out = step.run_steps(stacked, k=K)  # warmup: compile with the override live
        float(np.asarray(out["loss"]._value)[-1])
        steps[path] = step

    def _time_fused(s):
        t0 = time.perf_counter()
        o = s.run_steps(stacked, k=K)
        float(np.asarray(o["loss"]._value)[-1])
        return time.perf_counter() - t0

    best = {"dense": math_inf, "pallas_sorted": math_inf}
    order = list(steps)
    for i in range(reps):  # interleave (alternating order) so drift and
        for path in (order if i % 2 == 0 else order[::-1]):  # cache effects
            best[path] = min(best[path], _time_fused(steps[path]))  # hit both

    tok = batch * seq * K
    counters = _metrics.counters("kernels.moe.")
    compiles = _metrics.counters("train_step.").get("train_step.compiles", 0)
    extras = {
        "moe_tokens_per_sec": round(tok / best["pallas_sorted"], 2),
        "moe_tokens_per_sec_dense": round(tok / best["dense"], 2),
        "moe_kernel": {
            "picked": counters.get("kernels.moe.picked", 0),
            "fallback": counters.get("kernels.moe.fallback", 0),
            "train_step_compiles": compiles,
            "interpret": not on_tpu,
        },
    }
    config_key = f"{d0.device_kind or d0.platform}/moe{cfg['moe']}h{cfg['hidden_size']}L{cfg['num_layers']}b{batch}s{seq}"
    return extras["moe_tokens_per_sec"], config_key, on_tpu, extras


math_inf = float("inf")


def _measure_flash_micro(_flat_unused=False):
    """Flat-lane vs classic flash kernel microbench (the FLAGS_flash_flat
    verdict): interleaved best-of fwd+bwd timings of the same packed-qkv
    causal attention on both kernel families — Pallas interpreter on CPU,
    the real kernels on TPU."""
    import jax
    import jax.numpy as jnp

    if os.environ.get("BENCH_FORCE_CPU"):
        jax.config.update("jax_platforms", "cpu")

    from paddle_tpu.framework.flags import _REGISTRY
    from paddle_tpu.ops import flash_attention as fa
    from paddle_tpu.ops import flash_attention_flat as flat

    d0 = jax.devices()[0]
    on_tpu = d0.platform in ("tpu", "axon") or "TPU" in getattr(d0, "device_kind", "")
    _REGISTRY["FLAGS_use_flash_attention"] = True
    if on_tpu:
        b, s, h, d = 8, 1024, 16, 64
        dtype, reps = jnp.bfloat16, 8
    else:
        fa.set_interpret(True)
        flat.set_interpret(True)
        b, s, h, d = 1, 256, 2, 64
        dtype, reps = jnp.float32, 3

    qkv = jax.random.normal(jax.random.key(0), (b, s, 3, h, d), dtype)

    def classic(x):
        return jnp.sum(fa._flash(x[:, :, 0], x[:, :, 1], x[:, :, 2], True))

    def flat_packed(x):
        return jnp.sum(flat.flash_packed(x, causal=True))

    fns = {"classic": jax.jit(jax.value_and_grad(classic)),
           "flat": jax.jit(jax.value_and_grad(flat_packed))}
    for fn in fns.values():  # compile + numeric sanity
        val, g = fn(qkv)
        jax.block_until_ready((val, g))

    best = {name: math_inf for name in fns}
    for _ in range(reps):  # interleaved best-of: drift hits both sides
        for name, fn in fns.items():
            t0 = time.perf_counter()
            jax.block_until_ready(fn(qkv))
            best[name] = min(best[name], time.perf_counter() - t0)

    micro = {
        "classic_ms": round(best["classic"] * 1e3, 3),
        "flat_ms": round(best["flat"] * 1e3, 3),
        "flat_speedup": round(best["classic"] / best["flat"], 3),
        "mode": "tpu" if on_tpu else "cpu_interpret",
        "shape": [b, s, h, d],
        "what": "fwd+bwd packed-qkv causal attention, interleaved best-of",
    }
    config_key = f"{d0.device_kind or d0.platform}/flash_micro b{b}s{s}h{h}d{d}"
    return micro["flat_speedup"], config_key, on_tpu, {"flash_flat_micro": micro}


def _measure_in_subprocess(which: str, timeout: float, force_cpu: bool = False):
    """One measurement per process: TPU runtimes hold per-process device
    locks, so the parent must not initialize a backend before its children.
    Per-phase budgets (compile dominates; steps take seconds) keep probe +
    classic + flat + the CPU fallback well inside the driver's window.
    ``force_cpu`` flips the child to the CPU platform before backend init
    (the graceful TPU-absent fallback)."""
    env = dict(os.environ, BENCH_ONE=which)
    if force_cpu:
        env["BENCH_FORCE_CPU"] = "1"
        env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run([sys.executable, os.path.abspath(__file__)], env=env,
                       capture_output=True, text=True, timeout=timeout)
    line = [l for l in r.stdout.splitlines() if l.startswith("{")][-1]
    d = json.loads(line)
    return d["value"], d["config"], d["on_tpu"], d.get("extras", {})


# Per-phase wall budgets (seconds), env-overridable. The sum bounds the
# worst case; every expiry degrades to a smaller phase or a partial JSON
# line — bench.py itself NEVER runs into the driver's kill timeout
# (BENCH_r04 rc=124) and never exits non-zero.
PHASE_BUDGETS = {
    "probe": float(os.environ.get("BENCH_BUDGET_PROBE", 75)),
    "classic": float(os.environ.get("BENCH_BUDGET_CLASSIC", 480)),
    "flat": float(os.environ.get("BENCH_BUDGET_FLAT", 200)),
    "cpu_fallback": float(os.environ.get("BENCH_BUDGET_CPU", 240)),
    "moe": float(os.environ.get("BENCH_BUDGET_MOE", 300)),
    "flash_micro": float(os.environ.get("BENCH_BUDGET_FLASH_MICRO", 180)),
}


def main():
    if os.environ.get("BENCH_ONE"):
        which = os.environ["BENCH_ONE"]
        measure = {"moe": _measure_moe, "flash_micro": _measure_flash_micro}.get(which)
        if measure is not None:
            tps, config_key, on_tpu, extras = measure()
        else:
            tps, config_key, on_tpu, extras = _measure(which == "flat")
        print(json.dumps({"value": tps, "config": config_key, "on_tpu": on_tpu,
                          "extras": extras}))
        return

    from __graft_entry__ import _probe_default_backend

    phases = {}

    def _phase(name, fn, *args, **kwargs):
        """Run one budgeted phase; record outcome + wall seconds. Returns
        (ok, value) — a timeout/crash is a recorded partial result, not an
        exit."""
        t0 = time.perf_counter()
        try:
            out = fn(*args, **kwargs)
            phases[name] = {"status": "ok", "seconds": round(time.perf_counter() - t0, 1)}
            return True, out
        except subprocess.TimeoutExpired:
            phases[name] = {"status": "timeout", "seconds": round(time.perf_counter() - t0, 1),
                            "budget": PHASE_BUDGETS.get(name)}
        except Exception as exc:
            phases[name] = {"status": "error", "seconds": round(time.perf_counter() - t0, 1),
                            "error": f"{type(exc).__name__}"}
        return False, None

    tokens_per_sec = config_key = None
    on_tpu = False
    extras = {}
    chosen = "classic"
    fallback_reason = None

    verdict = _probe_default_backend(timeout=PHASE_BUDGETS["probe"])
    phases["probe"] = {"status": {True: "ok", False: "tpu_unreachable", None: "no_verdict"}[verdict]}

    if verdict is None:
        # could not spawn a probe child — subprocess machinery unavailable,
        # so measure once in-process (a hang here is unavoidable but this
        # path only exists where fork/exec fails, e.g. sandboxed CPU runs)
        ok, out = _phase("classic", _measure, False)
        if ok:
            tokens_per_sec, config_key, on_tpu, extras = out
            on_tpu = False  # device now locked by this process: skip the flat run
    elif verdict is True:
        ok, out = _phase("classic", _measure_in_subprocess, "classic",
                         timeout=PHASE_BUDGETS["classic"])
        if ok:
            tokens_per_sec, config_key, on_tpu, extras = out
        else:
            fallback_reason = "classic_" + phases["classic"]["status"]
    else:
        fallback_reason = "tpu_unreachable"

    if tokens_per_sec is None and verdict is not None:
        # graceful degradation: the TPU is absent/hung or the accelerator
        # run blew its budget — fall back to the CPU microbench so the run
        # still emits a real (if smaller) perf signal instead of rc=124
        ok, out = _phase("cpu_fallback", _measure_in_subprocess, "classic",
                         timeout=PHASE_BUDGETS["cpu_fallback"], force_cpu=True)
        if ok:
            tokens_per_sec, config_key, on_tpu, extras = out
            on_tpu = False

    if tokens_per_sec is None:
        # every phase failed: still ONE parseable line, rc 0
        print(json.dumps({"metric": "gpt_pretrain_throughput", "value": None,
                          "unit": "tokens/sec/chip", "vs_baseline": None,
                          "steps_per_sec": None, "steps_per_sec_fused": None,
                          "dispatches_per_step": None, "skipped_steps": None,
                          "rollbacks": None, "time_to_first_step": None,
                          "error": fallback_reason or "bench_error",
                          "phases": phases}))
        return

    if on_tpu:
        ok, out = _phase("flat", _measure_in_subprocess, "flat",
                         timeout=PHASE_BUDGETS["flat"])
        if ok:
            flat_tps, flat_cfg, _, flat_extras = out
            if flat_cfg == config_key and flat_tps > tokens_per_sec:
                tokens_per_sec, chosen, extras = flat_tps, "flash_flat", flat_extras

    # kernel-tier phases (own subprocesses, own budgets): GPT-MoE throughput
    # on the fused Pallas path vs the dense composite, and the
    # FLAGS_flash_flat flat-vs-classic microbench verdict. Skipped only
    # when subprocess machinery is unavailable (verdict is None).
    moe_extras, micro_extras = {}, {}
    if verdict is not None:
        ok, out = _phase("moe", _measure_in_subprocess, "moe",
                         timeout=PHASE_BUDGETS["moe"], force_cpu=not on_tpu)
        if ok:
            moe_extras = out[3]
        ok, out = _phase("flash_micro", _measure_in_subprocess, "flash_micro",
                         timeout=PHASE_BUDGETS["flash_micro"], force_cpu=not on_tpu)
        if ok:
            micro_extras = out[3]

    base_path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "bench_baseline.json")
    vs = 1.0
    if os.path.exists(base_path):
        try:
            prior = json.load(open(base_path))
            # only compare like-for-like (same device kind + model config)
            if prior.get("config") == config_key and prior.get("value"):
                vs = tokens_per_sec / prior["value"]
        except Exception:
            pass
    else:
        json.dump({"metric": "gpt_pretrain_throughput", "value": tokens_per_sec, "unit": "tokens/sec/chip", "config": config_key}, open(base_path, "w"))

    print(json.dumps({
        "metric": "gpt_pretrain_throughput",
        "value": round(tokens_per_sec, 2),
        "unit": "tokens/sec/chip",
        "vs_baseline": round(vs, 4),
        "attention_path": chosen,
        # dispatch-amortization telemetry (run_steps, lax.scan over K=8):
        # steps/sec for the per-step loop vs the fused multi-step path, and
        # dispatches-per-step measured by the train_step.* counters (1/8
        # when every step rides a fused dispatch)
        "steps_per_sec": extras.get("steps_per_sec"),
        "steps_per_sec_fused": extras.get("steps_per_sec_fused"),
        "dispatches_per_step": extras.get("dispatches_per_step"),
        # restart latency: import + build + trace + compile + first synced
        # step — the cost every elastic event / rollback / fresh deploy pays
        "time_to_first_step": extras.get("time_to_first_step"),
        # warm-restart path: same first step with the AOT training-
        # executable cache primed (FLAGS_compile_cache_dir) — build + trace
        # + DISK load + dispatch, no XLA compile
        "time_to_first_step_warm": extras.get("time_to_first_step_warm"),
        "warm_restart_aot_hits": extras.get("warm_restart_aot_hits"),
        # auto-parallel planner: plan-search time, the chosen plan, and the
        # roofline's predicted step time vs the measured fused step
        "plan": extras.get("plan"),
        # training-health guard telemetry: fused guarded steps/sec + overhead
        # vs unguarded (CPU microbench), and the run's skip/rollback counts
        "steps_per_sec_fused_guarded": extras.get("steps_per_sec_fused_guarded"),
        "guard_overhead_pct": extras.get("guard_overhead_pct"),
        "skipped_steps": extras.get("skipped_steps"),
        "rollbacks": extras.get("rollbacks"),
        # MoE kernel tier: GPT-MoE tokens/sec through the fused sort-based
        # Pallas dispatch/combine vs the dense one-hot/einsum composite
        # (interpret mode on CPU), plus the registry-selection pin
        # (kernels.moe.picked == compile count)
        "moe_tokens_per_sec": moe_extras.get("moe_tokens_per_sec"),
        "moe_tokens_per_sec_dense": moe_extras.get("moe_tokens_per_sec_dense"),
        "moe_kernel": moe_extras.get("moe_kernel"),
        # FLAGS_flash_flat verdict: flat-lane vs classic kernel pair,
        # fwd+bwd interleaved best-of (cpu_interpret or tpu mode)
        "flash_flat_micro": micro_extras.get("flash_flat_micro"),
        # observability snapshot (counters + span-histogram summaries) and
        # the compiled-specialization cost captured at TrainStep compile
        "metrics": extras.get("metrics"),
        "cost": extras.get("cost"),
        # SPMD sharding-analyzer summary for the first training
        # specialization (collective counts, est. reshard bytes/dispatch,
        # peak per-device memory estimate)
        "spmd": extras.get("spmd"),
        # graceful-degradation record: which phases ran, which fell back
        "fallback": fallback_reason,
        "phases": phases,
    }))


if __name__ == "__main__":
    try:
        main()
    except Exception as exc:  # any unplanned failure still emits one line
        print(json.dumps({"metric": "gpt_pretrain_throughput", "value": None,
                          "unit": "tokens/sec/chip", "vs_baseline": None,
                          "error": f"{type(exc).__name__}: {exc}"}))
    sys.exit(0)
