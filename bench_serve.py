"""Serving benchmark: continuous-batching GPT decode on one chip.

Prints ONE JSON line on the bench.py schema: {"metric", "value", "unit",
"vs_baseline", ...}. Three measurements:

1. **decode tokens/sec** through the static-KV-cache DecodeEngine (exactly
   two compiled programs: bucketed prefill + the decode step, donated cache
   buffers) vs the legacy growing-concat eager cache decode
   (``GPTBlock(cache=gen_cache(...))``) — ``decode_speedup`` is the
   engine-vs-concat ratio the serving tentpole is gated on (≥3x on CPU);
2. **requests/sec + latency p50/p99 + TTFT** from a continuous-batching run:
   R requests with mixed prompt lengths admitted into B slots in flight;
3. **time_to_first_token** cold: build + 2 compiles + first prefill.

Like bench.py, the process NEVER hangs into the driver's timeout and never
exits non-zero: the default backend is probed in a throwaway child first and
the run falls back to the CPU platform when the TPU is unreachable.
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def _percentile(sorted_vals, q):
    if not sorted_vals:
        return None
    idx = (q / 100.0) * (len(sorted_vals) - 1)
    lo = int(idx)
    hi = min(lo + 1, len(sorted_vals) - 1)
    return sorted_vals[lo] + (sorted_vals[hi] - sorted_vals[lo]) * (idx - lo)


def _measure():
    import jax

    if os.environ.get("BENCH_FORCE_CPU"):
        jax.config.update("jax_platforms", "cpu")

    import paddle_tpu as paddle
    from paddle_tpu import profiler
    from paddle_tpu.inference import ContinuousBatchingScheduler, DecodeEngine
    from paddle_tpu.models.gpt import GPTConfig, GPTForPretraining

    t_start = time.perf_counter()
    d0 = jax.devices()[0]
    on_tpu = d0.platform in ("tpu", "axon") or "TPU" in getattr(d0, "device_kind", "")
    if on_tpu:
        cfg = GPTConfig(vocab_size=50304, hidden_size=1024, num_layers=16,
                        num_heads=16, max_seq_len=1024)
        slots, max_seq, max_new, n_requests, decode_tokens = 8, 1024, 64, 32, 128
        buckets = (64, 128, 256, 512)
    else:
        cfg = GPTConfig.tiny()
        slots, max_seq, max_new, n_requests, decode_tokens = 4, 128, 12, 12, 48
        buckets = (8, 16, 32)

    paddle.seed(0)
    model = GPTForPretraining(cfg)
    model.eval()
    rng = np.random.default_rng(0)

    # --- engine decode throughput (and the 2-compile pin + TTFT cold) ----
    profiler.reset_counters("infer.")
    engine = DecodeEngine(model, max_batch_slots=slots, max_seq_len=max_seq,
                          prefill_buckets=buckets)
    prompt = rng.integers(0, cfg.vocab_size, (slots, buckets[0] // 2)).astype("int32")
    t0 = time.perf_counter()
    engine.generate(prompt, max_new_tokens=2)  # compiles prefill + step
    ttft_cold = time.perf_counter() - t_start
    compiles = int(profiler.counters("infer.").get("infer.compiles", 0))
    # warm decode: one prefill per slot then decode_tokens fused steps
    engine.generate(prompt, max_new_tokens=2)  # warm both programs
    t0 = time.perf_counter()
    out = engine.generate(prompt, max_new_tokens=decode_tokens)
    dt_engine = time.perf_counter() - t0
    engine_tps = slots * decode_tokens / dt_engine
    assert out.shape == (slots, prompt.shape[1] + decode_tokens)

    # --- growing-concat baseline (the legacy eager cache= decode path) ---
    from paddle_tpu.models.gpt import GPTBlock

    concat_tokens = max(8, decode_tokens // 4)  # eager is slow; scale count
    blocks = [GPTBlock(cfg) for _ in range(cfg.num_layers)]
    for b in blocks:
        b.eval()
    emb = model.gpt.embeddings
    x = paddle.to_tensor(prompt[:, :1])

    def concat_decode(n_tokens):
        caches = [b.gen_cache(emb(x)) for b in blocks]
        h = emb(x)
        for _ in range(n_tokens):
            for i, b in enumerate(blocks):
                h, caches[i] = b(h, cache=caches[i])
            h = h[:, -1:].detach()
        return h

    concat_decode(2)  # warm eager dispatch paths
    t0 = time.perf_counter()
    concat_decode(concat_tokens)
    dt_concat = time.perf_counter() - t0
    concat_tps = slots * concat_tokens / dt_concat
    speedup = engine_tps / concat_tps if concat_tps > 0 else None

    # --- continuous batching: requests/sec + latency percentiles ---------
    engine2 = DecodeEngine(model, max_batch_slots=slots, max_seq_len=max_seq,
                           prefill_buckets=buckets)
    # warm every prefill bucket + the decode step BEFORE any request's
    # latency clock starts — the serving numbers measure dispatch, not
    # compile (compile cost is reported separately as TTFT cold)
    for blen in buckets:
        engine2.generate(rng.integers(0, cfg.vocab_size, (1, blen)).astype("int32"),
                         max_new_tokens=2)
    sched = ContinuousBatchingScheduler(engine2)
    lens = rng.integers(buckets[0] // 2, buckets[-1] // 2, n_requests)
    for n in lens:
        sched.submit(rng.integers(0, cfg.vocab_size, (int(n),)).astype("int32"),
                     max_new_tokens=max_new)
    t0 = time.perf_counter()
    done = sched.run()
    dt_serve = time.perf_counter() - t0
    lat = sorted(r.total_seconds for r in done.values())
    ttft = sorted(r.ttft_seconds for r in done.values())
    requests_per_sec = len(done) / dt_serve if dt_serve > 0 else None

    config_key = (f"{d0.device_kind or d0.platform}/h{cfg.hidden_size}"
                  f"L{cfg.num_layers}b{slots}s{max_seq}")
    return {
        "value": round(requests_per_sec, 3),
        "config": config_key,
        "on_tpu": on_tpu,
        "requests_per_sec": round(requests_per_sec, 3),
        "latency_p50_ms": round(_percentile(lat, 50) * 1e3, 2),
        "latency_p99_ms": round(_percentile(lat, 99) * 1e3, 2),
        "ttft_p50_ms": round(_percentile(ttft, 50) * 1e3, 2),
        "requests": len(done),
        "tokens_generated": int(sum(len(r.tokens) for r in done.values())),
        "decode_tokens_per_sec": round(engine_tps, 1),
        "decode_tokens_per_sec_concat": round(concat_tps, 1),
        "decode_speedup": round(speedup, 2) if speedup else None,
        "decode_compiles": compiles,
        "time_to_first_token_cold": round(ttft_cold, 3),
    }


def main():
    if os.environ.get("BENCH_ONE"):
        print(json.dumps(_measure()))
        return

    from __graft_entry__ import _probe_default_backend

    budget = float(os.environ.get("BENCH_BUDGET_SERVE", 420))
    verdict = _probe_default_backend(timeout=75.0)
    extras = None
    error = None
    fallback = None
    if verdict is None:
        try:  # no subprocess machinery: measure in-process (CPU sandboxes)
            extras = _measure()
        except Exception as exc:
            error = f"{type(exc).__name__}: {exc}"
    else:
        import subprocess

        def _child(force_cpu):
            env = dict(os.environ, BENCH_ONE="serve")
            if force_cpu:
                env["BENCH_FORCE_CPU"] = "1"
                env["JAX_PLATFORMS"] = "cpu"
            r = subprocess.run([sys.executable, os.path.abspath(__file__)], env=env,
                               capture_output=True, text=True, timeout=budget)
            line = [l for l in r.stdout.splitlines() if l.startswith("{")][-1]
            return json.loads(line)

        if verdict is True:
            try:
                extras = _child(force_cpu=False)
            except Exception:
                fallback = "serve_bench_failed"
        else:
            fallback = "tpu_unreachable"
        if extras is None:
            try:  # graceful CPU fallback: still a real serving signal
                extras = _child(force_cpu=True)
            except Exception as exc:
                error = fallback or f"{type(exc).__name__}"

    if extras is None:
        print(json.dumps({"metric": "gpt_serving_throughput", "value": None,
                          "unit": "requests/sec", "vs_baseline": None,
                          "requests_per_sec": None, "latency_p50_ms": None,
                          "latency_p99_ms": None, "error": error or "bench_error"}))
        return

    base_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "bench_serve_baseline.json")
    vs = 1.0
    if os.path.exists(base_path):
        try:
            prior = json.load(open(base_path))
            if prior.get("config") == extras.get("config") and prior.get("value"):
                vs = extras["value"] / prior["value"]
        except Exception:
            pass
    else:
        try:
            json.dump({"metric": "gpt_serving_throughput", "value": extras["value"],
                       "unit": "requests/sec", "config": extras.get("config")},
                      open(base_path, "w"))
        except OSError:
            pass

    out = {"metric": "gpt_serving_throughput", "value": extras["value"],
           "unit": "requests/sec", "vs_baseline": round(vs, 4)}
    out.update({k: v for k, v in extras.items() if k not in ("value",)})
    if fallback:
        out["fallback"] = fallback
    print(json.dumps(out))


if __name__ == "__main__":
    try:
        main()
    except Exception as exc:  # any unplanned failure still emits one line
        print(json.dumps({"metric": "gpt_serving_throughput", "value": None,
                          "unit": "requests/sec", "vs_baseline": None,
                          "error": f"{type(exc).__name__}: {exc}"}))
    sys.exit(0)
