"""Serving benchmark: continuous-batching GPT decode on one chip.

Prints ONE JSON line on the bench.py schema: {"metric", "value", "unit",
"vs_baseline", ...}. Measurements:

1. **decode tokens/sec** through the static-KV-cache DecodeEngine at the
   round-2 hot path (chunked prefill + fused multi-token decode, donated
   cache buffers) vs the same engine unfused and vs the legacy
   growing-concat eager cache decode — ``decode_speedup`` is the
   engine-vs-concat ratio, ``fuse_speedup`` the fused-vs-unfused ratio,
   and ``decode_dispatches_per_token`` the dispatch amortization the fused
   scan buys (≈1/D);
2. **requests/sec + latency p50/p99 + TTFT + prefill stall** from a
   continuous-batching run: R requests with mixed prompt lengths sharing a
   system-prompt prefix, admitted into B slots in flight, served twice —
   once on the PR-6 path (bucketed prefill, per-token decode) and once on
   the round-2 path (chunked prefill, prefix-cache reuse, fused decode) —
   so the ``*_prev`` fields and ratios are measured in the same process;
3. **time_to_first_token** cold (build + compile family + first prefill)
   and **restart_ttft**: the same engine spec rebuilt against a warm
   ``FLAGS_compile_cache_dir`` AOT executable cache, where the compile
   family loads from disk instead of recompiling;
4. **fleet phase** (own ``BENCH_BUDGET_FLEET`` budget, own subprocess, same
   graceful-degradation contract): a ≥2-replica ServingFleet serving the
   shared-prefix request set — aggregate ``requests_per_sec`` fault-free,
   ``p99_under_kill_ms`` with ``FLAGS_chaos_replica_kill_at`` firing
   mid-stream (every request still finishes exactly once, bitwise — the
   run asserts it), ``scaleout_ttft_ms``: time-to-first-token on a
   replica scaled out against the warm AOT cache (``compiles == 0``),
   and ``trace_overhead_pct``: the same warm fleet run timed with
   ``FLAGS_trace`` off vs the full tracing plane writing span events to
   a run-log dir (< 2% budget) — the on-arm's merged chrome trace is
   written next to the run logs and reported as ``trace_artifact``;
5. **procfleet phase** (own ``BENCH_BUDGET_PROCFLEET`` budget, own
   subprocess): the cross-process ProcServingFleet — subprocess replicas
   behind the store-RPC transport — vs the in-process fleet on the same
   request set (``requests_per_sec`` / ``requests_per_sec_inproc`` /
   ``transport_overhead_pct``), ``p99_under_sigkill_ms`` with
   ``FLAGS_chaos_replica_sigkill_at`` delivering a real ``kill -9`` to one
   replica mid-stream (bitwise exactly-once asserted), streaming
   ``stream_ttft_p50_ms`` (first token chunk across the process boundary),
   and ``child_compiles`` pinning the warm AOT boot (0 == no recompiles);
6. **spec phase** (own ``BENCH_BUDGET_SPEC`` budget, own subprocess): the
   round-3 raw-speed pair — speculative decoding
   (``spec_decode_tokens_per_sec`` at the oracle-draft acceptance ceiling
   and with a genuinely small draft, ``spec_acceptance_rate``,
   ``decode_dispatches_per_token``; both arms assert bitwise parity with
   the plain engine) and the int8 KV cache (``kv_bytes_per_slot`` int8 vs
   f32, the shrink ratio, and ``max_concurrent_slots`` under a notional
   64 MiB KV budget — the concurrency the quantization buys);
7. **alerts phase** (own ``BENCH_BUDGET_ALERTS`` budget, own subprocess):
   the observability round-3 alerting arm — a TTFT SLO with sub-second
   burn windows over a live fleet, a chaos latency spike
   (``FLAGS_chaos_replica_slow_ms``), and the judgment layer's reaction
   time: ``alert_detection_ms`` (chaos start → page alert firing, within
   the fast window), ``alert_firing_ms`` (page → cleared once the spike
   ages out of the windows under recovery traffic), and
   ``slo_eval_overhead_pct`` — the monitor's evaluation cost over the
   serving run's wall time at a 50ms cadence (< 2% budget);
8. **ingress phase** (own ``BENCH_BUDGET_INGRESS`` budget, own
   subprocess): the round-4 HTTP front door + socket fast path —
   ``ingress_requests_per_sec`` through ``ServingIngress`` vs the same
   fleet driven in-process (``requests_per_sec_inproc``),
   ``socket_vs_store_overhead_pct``: the socket-transport fleet's wall
   time vs the identical workload on the store-poll transport
   (negative == the fast path is faster), ``stream_ttft_p50_ms`` over
   HTTP chunked streaming, ``disconnect_cancel_ms`` (client socket
   dropped mid-stream → mid-decode cancel observed),
   ``drain_under_load_ms`` (SIGTERM-style drain with requests in flight:
   rc 0, every accepted request finished), and the end-to-end chaos pin:
   replica ``kill -9`` mid-decode UNDER the ingress with streams open —
   every HTTP stream completes bitwise-identical to the unkilled
   reference (``exactly_once_under_sigkill``).

Like bench.py, the process NEVER hangs into the driver's timeout and never
exits non-zero: the default backend is probed in a throwaway child first and
the run falls back to the CPU platform when the TPU is unreachable.
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def _percentile(sorted_vals, q):
    if not sorted_vals:
        return None
    idx = (q / 100.0) * (len(sorted_vals) - 1)
    lo = int(idx)
    hi = min(lo + 1, len(sorted_vals) - 1)
    return sorted_vals[lo] + (sorted_vals[hi] - sorted_vals[lo]) * (idx - lo)


def _measure():
    import jax

    if os.environ.get("BENCH_FORCE_CPU"):
        jax.config.update("jax_platforms", "cpu")

    import paddle_tpu as paddle
    from paddle_tpu import profiler
    from paddle_tpu.inference import ContinuousBatchingScheduler, DecodeEngine
    from paddle_tpu.models.gpt import GPTConfig, GPTForPretraining

    t_start = time.perf_counter()
    d0 = jax.devices()[0]
    on_tpu = d0.platform in ("tpu", "axon") or "TPU" in getattr(d0, "device_kind", "")
    if on_tpu:
        cfg = GPTConfig(vocab_size=50304, hidden_size=1024, num_layers=16,
                        num_heads=16, max_seq_len=1024)
        slots, max_seq, max_new, n_requests, decode_tokens = 8, 1024, 64, 32, 128
        buckets = (64, 128, 256, 512)
        fuse, chunk, prefix_mb = 8, 128, 512.0
    else:
        cfg = GPTConfig.tiny()
        slots, max_seq, max_new, n_requests, decode_tokens = 4, 128, 12, 12, 48
        buckets = (8, 16, 32, 64)
        fuse, chunk, prefix_mb = 4, 16, 16.0

    paddle.seed(0)
    model = GPTForPretraining(cfg)
    model.eval()
    rng = np.random.default_rng(0)

    # --- engine decode throughput (round-2: chunked prefill + fused scan) --
    profiler.reset_counters("infer.")
    engine = DecodeEngine(model, max_batch_slots=slots, max_seq_len=max_seq,
                          prefill_chunk=chunk, fuse=fuse)
    prompt = rng.integers(0, cfg.vocab_size, (slots, chunk // 2)).astype("int32")
    engine.generate(prompt, max_new_tokens=2)  # compiles prefill-final + fused decode
    ttft_cold = time.perf_counter() - t_start
    compiles = int(profiler.counters("infer.").get("infer.compiles", 0))
    engine.generate(prompt, max_new_tokens=2)            # warm the fused path
    engine.generate(prompt, max_new_tokens=2, fuse=1)    # warm the unfused program
    profiler.reset_counters("infer.")
    t0 = time.perf_counter()
    out = engine.generate(prompt, max_new_tokens=decode_tokens)
    dt_engine = time.perf_counter() - t0
    engine_tps = slots * decode_tokens / dt_engine
    c = profiler.counters("infer.")
    decode_dispatches = int(c.get("infer.decode_dispatches", 0))
    dispatches_per_token = decode_dispatches / max(1, slots * decode_tokens)
    assert out.shape == (slots, prompt.shape[1] + decode_tokens)
    t0 = time.perf_counter()
    engine.generate(prompt, max_new_tokens=decode_tokens, fuse=1)
    dt_unfused = time.perf_counter() - t0
    unfused_tps = slots * decode_tokens / dt_unfused

    # --- growing-concat baseline (the legacy eager cache= decode path) ---
    from paddle_tpu.models.gpt import GPTBlock

    concat_tokens = max(8, decode_tokens // 4)  # eager is slow; scale count
    blocks = [GPTBlock(cfg) for _ in range(cfg.num_layers)]
    for b in blocks:
        b.eval()
    emb = model.gpt.embeddings
    x = paddle.to_tensor(prompt[:, :1])

    def concat_decode(n_tokens):
        caches = [b.gen_cache(emb(x)) for b in blocks]
        h = emb(x)
        for _ in range(n_tokens):
            for i, b in enumerate(blocks):
                h, caches[i] = b(h, cache=caches[i])
            h = h[:, -1:].detach()
        return h

    concat_decode(2)  # warm eager dispatch paths
    t0 = time.perf_counter()
    concat_decode(concat_tokens)
    dt_concat = time.perf_counter() - t0
    concat_tps = slots * concat_tokens / dt_concat
    speedup = engine_tps / concat_tps if concat_tps > 0 else None

    # --- continuous batching: PR-6 path vs round-2 path ------------------
    # same request set both rounds: mixed prompt lengths behind one shared
    # system-prompt prefix (2 chunks — what the prefix cache feeds on) with
    # duplicated queries, the serving-traffic shape prefix reuse exists for
    lens = rng.integers(max(1, chunk // 4), chunk, max(1, n_requests // 2))
    shared = rng.integers(0, cfg.vocab_size, (2 * chunk,)).astype("int32")
    tails = [rng.integers(0, cfg.vocab_size, (int(n),)).astype("int32") for n in lens]
    prompts = [np.concatenate([shared, tails[i % len(tails)]])
               for i in range(n_requests)]

    def serve_round(**engine_kwargs):
        eng = DecodeEngine(model, max_batch_slots=slots, max_seq_len=max_seq,
                           **engine_kwargs)
        # warm every program BEFORE any request's latency clock starts —
        # the serving numbers measure dispatch, not compile (compile cost
        # is reported separately as TTFT cold / restart)
        if engine_kwargs.get("prefill_chunk"):
            warm_lens = (engine_kwargs["prefill_chunk"] + 1,)
        else:
            warm_lens = engine_kwargs["prefill_buckets"]
        for blen in warm_lens:
            eng.generate(rng.integers(0, cfg.vocab_size, (1, blen)).astype("int32"),
                         max_new_tokens=2)
        best = None
        for _trial in range(3):  # best-of-3: host scheduling noise dominates
            sched = ContinuousBatchingScheduler(eng)
            for p in prompts:
                sched.submit(p, max_new_tokens=max_new)
            t0 = time.perf_counter()
            done = sched.run()
            dt = time.perf_counter() - t0
            lat = sorted(r.total_seconds for r in done.values())
            ttft = sorted(r.ttft_seconds for r in done.values())
            stalls = sorted(r.stall_seconds for r in done.values())
            trial = {
                "engine": eng,
                "requests": len(done),
                "requests_per_sec": len(done) / dt if dt > 0 else None,
                "latency_p50_ms": _percentile(lat, 50) * 1e3,
                "latency_p99_ms": _percentile(lat, 99) * 1e3,
                "ttft_p50_ms": _percentile(ttft, 50) * 1e3,
                "prefill_stall_ms_p99": _percentile(stalls, 99) * 1e3,
                "tokens_generated": int(sum(len(r.tokens) for r in done.values())),
            }
            if best is None or trial["requests_per_sec"] > best["requests_per_sec"]:
                best = trial
        return best

    prev = serve_round(prefill_buckets=buckets)          # the PR-6 serving path
    cur = serve_round(prefill_chunk=chunk, prefix_cache_mb=prefix_mb, fuse=fuse)
    pstats = cur["engine"].prefix_cache.stats()
    hit_rate = pstats["hits"] / max(1, pstats["hits"] + pstats["misses"])

    # --- restart TTFT: AOT executable cache under FLAGS_compile_cache_dir --
    restart_ttft = None
    aot_hits = 0
    try:
        import tempfile

        cache_dir = tempfile.mkdtemp(prefix="bench_serve_aot_")
        paddle.set_flags({"FLAGS_compile_cache_dir": cache_dir})
        spec = dict(max_batch_slots=slots, max_seq_len=max_seq,
                    prefill_chunk=chunk, fuse=fuse)
        warm = DecodeEngine(model, **spec)
        warm.generate(prompt[:1], max_new_tokens=2)  # compile + serialize family
        profiler.reset_counters("infer.")
        t0 = time.perf_counter()
        cold = DecodeEngine(model, **spec)           # "restarted" engine
        job = cold.begin_prefill(prompt[0], slot=0, max_new_tokens=2)
        while not cold.prefill_step(job):
            pass
        restart_ttft = time.perf_counter() - t0      # first token, no compiles
        aot_hits = int(profiler.counters("infer.").get("infer.aot_cache_hits", 0))
    except Exception:
        pass
    finally:
        try:
            paddle.set_flags({"FLAGS_compile_cache_dir": ""})
        except Exception:
            pass

    config_key = (f"{d0.device_kind or d0.platform}/h{cfg.hidden_size}"
                  f"L{cfg.num_layers}b{slots}s{max_seq}")
    out = {
        "value": round(cur["requests_per_sec"], 3),
        "config": config_key,
        "on_tpu": on_tpu,
        "requests_per_sec": round(cur["requests_per_sec"], 3),
        "latency_p50_ms": round(cur["latency_p50_ms"], 2),
        "latency_p99_ms": round(cur["latency_p99_ms"], 2),
        "ttft_p50_ms": round(cur["ttft_p50_ms"], 2),
        "ttft_p50_ms_prev": round(prev["ttft_p50_ms"], 2),
        "prefill_stall_ms_p99": round(cur["prefill_stall_ms_p99"], 3),
        "requests": cur["requests"],
        "tokens_generated": cur["tokens_generated"],
        "requests_per_sec_prev": round(prev["requests_per_sec"], 3),
        "latency_p50_ms_prev": round(prev["latency_p50_ms"], 2),
        "decode_tokens_per_sec": round(engine_tps, 1),
        "decode_tokens_per_sec_unfused": round(unfused_tps, 1),
        "decode_tokens_per_sec_concat": round(concat_tps, 1),
        "decode_speedup": round(speedup, 2) if speedup else None,
        "fuse_speedup": round(engine_tps / unfused_tps, 2) if unfused_tps else None,
        "fuse": fuse,
        "prefill_chunk": chunk,
        "decode_dispatches_per_token": round(dispatches_per_token, 4),
        "prefix_cache_hit_rate": round(hit_rate, 3),
        "prefix_tokens_reused": int(profiler.counters("serving.").get(
            "serving.prefix_tokens_reused", 0)),
        "decode_compiles": compiles,
        "time_to_first_token_cold": round(ttft_cold, 3),
        "restart_ttft": round(restart_ttft, 3) if restart_ttft is not None else None,
        "restart_aot_cache_hits": aot_hits,
    }
    return out


def _measure_fleet():
    """The serving-fleet phase: throughput, p99 under a mid-stream replica
    kill, and scale-out TTFT against the warm AOT cache. Asserts the kill
    run's completions are exactly-once and bitwise-equal to the fault-free
    run — the bench doubles as the fleet's integration check."""
    import jax

    if os.environ.get("BENCH_FORCE_CPU"):
        jax.config.update("jax_platforms", "cpu")

    import paddle_tpu as paddle
    from paddle_tpu import profiler
    from paddle_tpu.inference import ServingFleet
    from paddle_tpu.models.gpt import GPTConfig, GPTForPretraining
    from paddle_tpu.testing import chaos

    d0 = jax.devices()[0]
    on_tpu = d0.platform in ("tpu", "axon") or "TPU" in getattr(d0, "device_kind", "")
    if on_tpu:
        cfg = GPTConfig(vocab_size=50304, hidden_size=1024, num_layers=16,
                        num_heads=16, max_seq_len=1024)
        slots, max_seq, max_new, n_requests = 8, 1024, 32, 24
        chunk, fuse, prefix_mb, n_replicas = 128, 8, 256.0, 2
    else:
        cfg = GPTConfig.tiny()
        slots, max_seq, max_new, n_requests = 2, 128, 8, 10
        chunk, fuse, prefix_mb, n_replicas = 16, 2, 16.0, 2

    paddle.seed(0)
    model = GPTForPretraining(cfg)
    model.eval()
    rng = np.random.default_rng(0)
    kw = dict(max_batch_slots=slots, max_seq_len=max_seq, prefill_chunk=chunk,
              fuse=fuse, prefix_cache_mb=prefix_mb)
    shared = rng.integers(0, cfg.vocab_size, (2 * chunk,)).astype("int32")
    prompts = [np.concatenate([shared, rng.integers(
        0, cfg.vocab_size, (int(n),)).astype("int32")])
        for n in rng.integers(max(1, chunk // 4), chunk, n_requests)]

    import tempfile

    cache_dir = tempfile.mkdtemp(prefix="bench_fleet_aot_")
    paddle.set_flags({"FLAGS_compile_cache_dir": cache_dir})
    try:
        # --- fault-free throughput (programs warm via the AOT store) ------
        fleet = ServingFleet(model, replicas=n_replicas, **kw)
        fids = [fleet.submit(p, max_new_tokens=max_new, seed=i)
                for i, p in enumerate(prompts)]
        fleet.run()  # warm run: compiles + serializes the family
        want = {i: list(fleet.requests[f].tokens) for i, f in enumerate(fids)}
        fleet = ServingFleet(model, replicas=n_replicas, **kw)
        fids = [fleet.submit(p, max_new_tokens=max_new, seed=i)
                for i, p in enumerate(prompts)]
        t0 = time.perf_counter()
        done = fleet.run()
        dt = time.perf_counter() - t0
        rps = len(done) / dt if dt > 0 else None

        # --- p99 latency with a replica killed mid-stream -----------------
        with chaos.inject(FLAGS_chaos_replica_kill_at=f"{n_replicas - 1}:2"):
            fleet_k = ServingFleet(model, replicas=n_replicas, **kw)
            fids_k = [fleet_k.submit(p, max_new_tokens=max_new, seed=i)
                      for i, p in enumerate(prompts)]
            done_k = fleet_k.run()
        assert len(done_k) == len(prompts), "kill run lost completions"
        for i, f in enumerate(fids_k):
            assert list(done_k[f].tokens) == want[i], \
                f"kill run diverged on request {i}"
        lat = sorted(r.total_seconds for r in done_k.values())
        p99_kill = _percentile(lat, 99)
        stats_k = fleet_k.stats()

        # --- scale-out TTFT at compiles == 0 ------------------------------
        profiler.reset_counters("infer.")
        t0 = time.perf_counter()
        new = fleet.scale_out(1)
        fid = fleet.submit(prompts[0], max_new_tokens=2, seed=0,
                           replica=new[0])
        while fleet.requests[fid].status != "finished":
            fleet.step()
        scaleout_ttft = fleet.requests[fid].first_token_ts - t0
        scaleout_compiles = int(profiler.counters("infer.").get("infer.compiles", 0))

        # --- tracing overhead, measured in-band ---------------------------
        # same warm fleet spec with the run-log disk mirror held constant
        # in BOTH arms (that's pre-existing monitor cost, not tracing
        # cost): FLAGS_trace off (no ids, no span events) vs the full
        # tracing plane.  Arms interleave and each takes min-of-3 so host
        # scheduling noise cancels.  The on-arm's merged chrome trace is
        # kept as the bench artifact.  PR-14 budget: < 2% throughput cost.
        off_dir = tempfile.mkdtemp(prefix="bench_fleet_notrace_")
        trace_dir = tempfile.mkdtemp(prefix="bench_fleet_trace_")
        prev_flags = paddle.get_flags(["FLAGS_trace", "FLAGS_run_log_dir"])

        def _timed_run(trace_on):
            paddle.set_flags({"FLAGS_trace": trace_on,
                              "FLAGS_run_log_dir":
                                  trace_dir if trace_on else off_dir})
            fl = ServingFleet(model, replicas=n_replicas, **kw)
            for i, p in enumerate(prompts):
                fl.submit(p, max_new_tokens=max_new, seed=i)
            t0 = time.perf_counter()
            fl.run()
            return time.perf_counter() - t0

        trace_overhead_pct = None
        trace_artifact = None
        trace_events = 0
        try:
            _timed_run(True)  # warm both log files + the trace-id streams
            _timed_run(False)
            t_off, t_on = [], []
            for _ in range(5):  # interleaved min-of-5: host noise on the
                t_off.append(_timed_run(False))  # tiny CPU config is far
                t_on.append(_timed_run(True))    # larger than the signal
            t_off, t_on = min(t_off), min(t_on)
            trace_overhead_pct = (t_on - t_off) / t_off * 100.0 if t_off else None

            from paddle_tpu.observability.__main__ import chrome_trace_doc

            doc = chrome_trace_doc(trace_dir)
            trace_events = len(doc.get("traceEvents", []))
            trace_artifact = os.path.join(trace_dir, "trace.json")
            with open(trace_artifact, "w") as f:
                json.dump(doc, f)
        except Exception:
            trace_artifact = None
        finally:
            paddle.set_flags(prev_flags)
    finally:
        try:
            paddle.set_flags({"FLAGS_compile_cache_dir": ""})
        except Exception:
            pass

    return {
        "replicas": n_replicas,
        "requests": len(done),
        "requests_per_sec": round(rps, 3) if rps else None,
        "p99_under_kill_ms": round(p99_kill * 1e3, 2),
        "requeues_under_kill": stats_k["requeues"],
        "replica_deaths": len(stats_k["dead"]),
        "scaleout_ttft_ms": round(scaleout_ttft * 1e3, 2),
        "scaleout_compiles": scaleout_compiles,
        "trace_overhead_pct": (round(trace_overhead_pct, 2)
                               if trace_overhead_pct is not None else None),
        "trace_artifact": trace_artifact,
        "trace_events": trace_events,
    }


def _measure_procfleet():
    """The cross-process fleet phase: subprocess replicas behind the
    store-RPC transport vs the in-process fleet on the same request set
    (``*_inproc`` fields → transport overhead), p99 latency with one
    replica killed by a real SIGKILL mid-stream, and streaming TTFT (time
    to the first token CHUNK delivered across the process boundary). Both
    procfleet arms assert exactly-once bitwise completions — the bench
    doubles as the kill -9 integration check."""
    import jax

    if os.environ.get("BENCH_FORCE_CPU"):
        jax.config.update("jax_platforms", "cpu")

    import paddle_tpu as paddle
    from paddle_tpu.inference import ProcServingFleet, ServingFleet
    from paddle_tpu.models.gpt import GPTConfig, GPTForPretraining
    from paddle_tpu.testing import chaos

    d0 = jax.devices()[0]
    on_tpu = d0.platform in ("tpu", "axon") or "TPU" in getattr(d0, "device_kind", "")
    if on_tpu:
        cfg = GPTConfig(vocab_size=50304, hidden_size=1024, num_layers=16,
                        num_heads=16, max_seq_len=1024)
        slots, max_seq, max_new, n_requests = 8, 1024, 32, 24
        chunk, fuse, n_replicas = 128, 8, 2
    else:
        cfg = GPTConfig.tiny()
        slots, max_seq, max_new, n_requests = 2, 128, 8, 10
        chunk, fuse, n_replicas = 16, 2, 2

    paddle.seed(0)
    model = GPTForPretraining(cfg)
    model.eval()
    rng = np.random.default_rng(0)
    kw = dict(max_batch_slots=slots, max_seq_len=max_seq, prefill_chunk=chunk,
              fuse=fuse)
    prompts = [rng.integers(0, cfg.vocab_size, (int(n),)).astype("int32")
               for n in rng.integers(max(1, chunk // 4), chunk, n_requests)]

    import tempfile

    cache_dir = tempfile.mkdtemp(prefix="bench_procfleet_aot_")
    paddle.set_flags({"FLAGS_compile_cache_dir": cache_dir})
    try:
        # --- in-process arm: warm the AOT store, pin the reference tokens,
        # then a timed fault-free run — the transport-overhead baseline ----
        warm = ServingFleet(model, replicas=n_replicas, **kw)
        fids = [warm.submit(p, max_new_tokens=max_new, seed=i)
                for i, p in enumerate(prompts)]
        warm.run()  # compiles + serializes the program family
        want = [list(warm.requests[f].tokens) for f in fids]
        fl = ServingFleet(model, replicas=n_replicas, **kw)
        fids = [fl.submit(p, max_new_tokens=max_new, seed=i)
                for i, p in enumerate(prompts)]
        t0 = time.perf_counter()
        done = fl.run()
        dt_in = time.perf_counter() - t0
        rps_in = len(done) / dt_in if dt_in > 0 else None
        ttft_in = sorted(r.ttft_seconds for r in done.values())
        lat_in = sorted(r.total_seconds for r in done.values())

        # --- cross-process arm, fault-free: boot cost, throughput, and
        # streaming TTFT (first chunk across the process boundary) --------
        t0 = time.perf_counter()
        pf = ProcServingFleet(cfg, replicas=n_replicas,
                              heartbeat_timeout=120.0, **kw)
        boot_s = time.perf_counter() - t0
        try:
            stream = pf.submit(prompts[0], max_new_tokens=max_new, seed=0,
                               stream=True)
            fids = [stream.fid] + [pf.submit(p, max_new_tokens=max_new, seed=i)
                                   for i, p in enumerate(prompts) if i > 0]
            t0 = time.perf_counter()
            chunks = list(stream)
            done_p = pf.run(timeout_s=600)
            dt_p = time.perf_counter() - t0
            assert len(done_p) == len(prompts), "procfleet lost completions"
            got = [list(pf.requests[f].tokens) for f in fids]
            assert got == want, "procfleet diverged from the in-process run"
            assert [t for c in chunks for t in c] == want[0], "stream diverged"
            rps_p = len(done_p) / dt_p if dt_p > 0 else None
            ttft_p = sorted(r.ttft_seconds for r in done_p.values())
            counters = pf.child_counters()
            child_compiles = sum(c.get("compiles", 0) for c in counters.values())
        finally:
            pf.shutdown()

        # --- p99 with one subprocess killed by a real SIGKILL mid-stream --
        with chaos.inject(
                FLAGS_chaos_replica_sigkill_at=f"{n_replicas - 1}:2"):
            pf_k = ProcServingFleet(cfg, replicas=n_replicas,
                                    heartbeat_timeout=120.0, **kw)
            try:
                fids_k = [pf_k.submit(p, max_new_tokens=max_new, seed=i)
                          for i, p in enumerate(prompts)]
                done_k = pf_k.run(timeout_s=600)
                assert len(done_k) == len(prompts), "sigkill run lost completions"
                for i, f in enumerate(fids_k):
                    assert list(done_k[f].tokens) == want[i], \
                        f"sigkill run diverged on request {i}"
                lat_k = sorted(r.total_seconds for r in done_k.values())
                stats_k = pf_k.stats()
            finally:
                pf_k.shutdown()
    finally:
        try:
            paddle.set_flags({"FLAGS_compile_cache_dir": ""})
        except Exception:
            pass

    overhead = ((rps_in / rps_p - 1.0) * 100.0
                if rps_in and rps_p else None)
    return {
        "replicas": n_replicas,
        "requests": len(done_p),
        "requests_per_sec": round(rps_p, 3) if rps_p else None,
        "requests_per_sec_inproc": round(rps_in, 3) if rps_in else None,
        "transport_overhead_pct": round(overhead, 2) if overhead is not None else None,
        "p99_under_sigkill_ms": round(_percentile(lat_k, 99) * 1e3, 2),
        "latency_p99_ms_inproc": round(_percentile(lat_in, 99) * 1e3, 2),
        "stream_ttft_p50_ms": round(_percentile(ttft_p, 50) * 1e3, 2),
        "ttft_p50_ms_inproc": round(_percentile(ttft_in, 50) * 1e3, 2),
        "requeues_under_sigkill": stats_k["requeues"],
        "replica_deaths": len(stats_k["dead"]),
        "boot_seconds": round(boot_s, 3),
        "child_compiles": child_compiles,  # 0 == the warm-boot pin held
        "stream_chunks": len(chunks),
    }


def _measure_spec():
    """The round-3 raw-speed phase: speculative decoding (oracle self-draft
    — the acceptance-rate ceiling — plus a genuinely small draft) and the
    int8 KV cache. Reports ``spec_decode_tokens_per_sec`` vs the plain
    per-token engine, the measured ``spec_acceptance_rate`` and
    ``decode_dispatches_per_token`` amortization, and the KV-cache byte
    story: ``kv_bytes_per_slot`` int8 vs f32, the shrink ratio, and
    ``max_concurrent_slots`` — how many slots a notional 64 MiB KV budget
    admits under each representation (the capacity the quantization buys).
    The spec arms assert bitwise parity with the plain engine in-band."""
    import jax

    if os.environ.get("BENCH_FORCE_CPU"):
        jax.config.update("jax_platforms", "cpu")

    import paddle_tpu as paddle
    from paddle_tpu import profiler
    from paddle_tpu.inference import DecodeEngine
    from paddle_tpu.models.gpt import GPTConfig, GPTForPretraining

    d0 = jax.devices()[0]
    on_tpu = d0.platform in ("tpu", "axon") or "TPU" in getattr(d0, "device_kind", "")
    if on_tpu:
        cfg = GPTConfig(vocab_size=50304, hidden_size=1024, num_layers=16,
                        num_heads=16, max_seq_len=1024)
        dcfg = GPTConfig(vocab_size=50304, hidden_size=256, num_layers=2,
                         num_heads=4, max_seq_len=1024)
        slots, max_seq, decode_tokens, spec_k = 8, 1024, 128, 4
        buckets = (64,)
    else:
        cfg = GPTConfig.tiny()
        dcfg = GPTConfig(vocab_size=512, hidden_size=32, num_layers=1,
                         num_heads=2, max_seq_len=128)
        slots, max_seq, decode_tokens, spec_k = 4, 128, 48, 4
        buckets = (16,)

    paddle.seed(0)
    model = GPTForPretraining(cfg)
    model.eval()
    rng = np.random.default_rng(0)
    prompt = rng.integers(0, cfg.vocab_size, (slots, buckets[0] - 2)).astype("int32")
    kw = dict(max_batch_slots=slots, max_seq_len=max_seq, prefill_buckets=buckets)

    def timed_tps(eng):
        eng.generate(prompt, max_new_tokens=2)   # compile + warm
        t0 = time.perf_counter()
        out = eng.generate(prompt, max_new_tokens=decode_tokens)
        dt = time.perf_counter() - t0
        return slots * decode_tokens / dt, out

    plain = DecodeEngine(model, **kw)
    plain_tps, want = timed_tps(plain)

    # oracle self-draft: acceptance ~1.0 — the amortization ceiling (a real
    # deployment's distilled draft lands between this and the small-draft arm)
    profiler.reset_counters("infer.")
    oracle = DecodeEngine(model, draft=model, spec_k=spec_k, **kw)
    oracle_tps, got = timed_tps(oracle)
    assert np.array_equal(got, want), "oracle spec arm diverged from plain engine"
    c = profiler.counters("infer.")
    disp_per_tok = (int(c.get("infer.decode_dispatches", 0)) - 1) / max(
        1, int(c.get("infer.tokens", 0)) - slots)  # minus the warm-up generate
    oracle_acc = oracle.spec_stats()["acceptance_rate"]

    # small independent draft: real draft-forward cost at its (random-init,
    # near-zero) acceptance — the throughput floor of the mechanism
    small = DecodeEngine(model, draft=dcfg, spec_k=spec_k, draft_seed=1, **kw)
    small_tps, got = timed_tps(small)
    assert np.array_equal(got, want), "small-draft spec arm diverged from plain engine"
    small_acc = small.spec_stats()["acceptance_rate"]

    # --- int8 KV cache: per-slot bytes and the capacity they buy ----------
    i8 = DecodeEngine(model, kv_dtype="int8", **kw)
    i8.generate(prompt, max_new_tokens=2)
    f32_slot, i8_slot = plain.kv_bytes_per_slot(), i8.kv_bytes_per_slot()
    kv_budget = 64 * 1024 * 1024  # notional per-chip KV budget for capacity math
    return {
        "spec_k": spec_k,
        "decode_tokens_per_sec_plain": round(plain_tps, 1),
        "spec_decode_tokens_per_sec": round(oracle_tps, 1),
        "spec_decode_tokens_per_sec_small_draft": round(small_tps, 1),
        "spec_speedup_oracle": round(oracle_tps / plain_tps, 2) if plain_tps else None,
        "spec_acceptance_rate": round(oracle_acc, 4),
        "spec_acceptance_rate_small_draft": round(small_acc, 4),
        "decode_dispatches_per_token": round(disp_per_tok, 4),
        "kv_bytes_per_slot": i8_slot,
        "kv_bytes_per_slot_f32": f32_slot,
        "kv_shrink": round(f32_slot / i8_slot, 2) if i8_slot else None,
        "max_concurrent_slots": int(kv_budget // i8_slot) if i8_slot else None,
        "max_concurrent_slots_f32": int(kv_budget // f32_slot) if f32_slot else None,
    }


def _measure_alerts():
    """The round-3 alerting arm: the SLO engine watching a live fleet.

    Installs one TTFT SLO with sub-second burn windows (the production
    ~5min/1h windows shrunk so the bench finishes), injects a chaos
    latency spike (``FLAGS_chaos_replica_slow_ms``), and measures the
    judgment layer's reaction time: ``alert_detection_ms`` — wall time
    from the start of the degraded run to the page-severity alert firing
    — and ``alert_firing_ms`` — page until the alert cleared as the
    spike aged out of both windows. The spike size and
    objective threshold are machine-relative (multiples of the measured
    healthy TTFT) so the arm pages on the chaos and never on the host's
    own speed. ``slo_eval_overhead_pct`` is the monitor's cost while the
    healthy run was serving: Σ ``slo.eval_seconds`` over the run's wall
    time, evaluated every 50ms — budget < 2%."""
    import jax

    if os.environ.get("BENCH_FORCE_CPU"):
        jax.config.update("jax_platforms", "cpu")

    import paddle_tpu as paddle
    from paddle_tpu.inference import ServingFleet
    from paddle_tpu.models.gpt import GPTConfig, GPTForPretraining
    from paddle_tpu.observability import metrics, slo
    from paddle_tpu.testing import chaos

    d0 = jax.devices()[0]
    on_tpu = d0.platform in ("tpu", "axon") or "TPU" in getattr(d0, "device_kind", "")
    if on_tpu:
        cfg = GPTConfig(vocab_size=50304, hidden_size=1024, num_layers=16,
                        num_heads=16, max_seq_len=1024)
        slots, max_seq, max_new, n_requests = 8, 1024, 16, 12
        chunk, fuse, n_replicas = 128, 8, 2
    else:
        cfg = GPTConfig.tiny()
        slots, max_seq, max_new, n_requests = 2, 128, 6, 6
        chunk, fuse, n_replicas = 16, 2, 2

    paddle.seed(0)
    model = GPTForPretraining(cfg)
    model.eval()
    rng = np.random.default_rng(0)
    kw = dict(max_batch_slots=slots, max_seq_len=max_seq, prefill_chunk=chunk,
              fuse=fuse)
    prompts = [rng.integers(0, cfg.vocab_size, (int(n),)).astype("int32")
               for n in rng.integers(max(1, chunk // 4), chunk, n_requests)]

    import tempfile

    cache_dir = tempfile.mkdtemp(prefix="bench_alerts_aot_")
    log_dir = tempfile.mkdtemp(prefix="bench_alerts_log_")
    prev_flags = paddle.get_flags(["FLAGS_compile_cache_dir",
                                   "FLAGS_run_log_dir"])
    paddle.set_flags({"FLAGS_compile_cache_dir": cache_dir,
                      "FLAGS_run_log_dir": log_dir})

    def serve(tag):
        fl = ServingFleet(model, replicas=n_replicas, **kw)
        for i, p in enumerate(prompts):
            fl.submit(p, max_new_tokens=max_new, seed=i)
        t0 = time.perf_counter()
        fl.run()
        return time.perf_counter() - t0

    try:
        serve("warm")   # compile + serialize the program family
        serve("healthy")  # healthy TTFT sample, monitor not yet installed
        ttft_hist = metrics.histogram("serving.ttft_seconds")
        healthy_ms = (ttft_hist.percentile(50) or 0.01) * 1e3
        # objective + spike sized off the measured healthy TTFT so the arm
        # alerts on the chaos, not on the host's own speed
        threshold_ms = max(50.0, 2.0 * healthy_ms)
        slow_ms = int(min(2500.0, max(150.0, 4.0 * healthy_ms)))
        # the fast window must hold several chaos-slowed ticks: the first
        # degraded TTFT only exists a few ticks into the incident, so a
        # window shorter than that could never contain its own detection
        fast_w = max(2.0, 6.0 * slow_ms / 1e3)
        slow_w = 4.0 * fast_w
        spec = slo.SLO("serving.ttft_p50_ms", "percentile",
                       threshold=threshold_ms, histogram="serving.ttft_seconds",
                       q=50, scale=1e3, page_burn=1.2, warn_burn=1.0,
                       description="bench alerting arm: machine-relative TTFT")
        mon = slo.install([spec], with_regress=False, eval_every_s=0.05,
                          fast_window_s=fast_w, slow_window_s=slow_w)
        mon.evaluate()  # baseline snapshot before the overhead-metered run

        # --- monitor overhead while serving healthy traffic ---------------
        eval_sum0 = metrics.histogram("slo.eval_seconds").sum
        evals0 = metrics.counters("slo.")["slo.evaluations"]
        dt_healthy = serve("metered")
        eval_cost = metrics.histogram("slo.eval_seconds").sum - eval_sum0
        overhead_pct = eval_cost / dt_healthy * 100.0 if dt_healthy else None
        evaluations = int(metrics.counters("slo.")["slo.evaluations"] - evals0)
        paged_on_healthy = mon.states()[0]["severity"] is not None

        # --- chaos latency spike: page within the fast window -------------
        # quiesce one fast window first: otherwise the healthy run's TTFT
        # samples share the window with the first chaos samples and hold
        # the percentile down, inflating detection by ~the window length
        time.sleep(fast_w)
        mon.evaluate()
        t_chaos = time.time()
        with chaos.inject(FLAGS_chaos_replica_slow_ms=str(slow_ms)):
            serve("chaos")  # tick loops drive the monitor's 50ms cadence
        mon.evaluate()

        # --- recovery: healthy traffic, spike ages out of both windows ----
        serve("recovery")
        deadline = time.time() + 4 * slow_w
        while time.time() < deadline:
            mon.evaluate()
            if mon.states()[0]["severity"] is None:
                break
            time.sleep(0.1)

        # detection/clear come from the run-log alert events: with chaos
        # ticks longer than the fast window the alert can fire AND clear
        # inside the chaos run itself, so post-run monitor state alone
        # would under-report what the judgment layer actually did
        events = []
        for name in sorted(os.listdir(log_dir)):
            if not (name.startswith("run-") and name.endswith(".jsonl")):
                continue
            with open(os.path.join(log_dir, name)) as f:
                for line in f:
                    try:
                        ev = json.loads(line)
                    except ValueError:
                        continue
                    if (ev.get("event") == "alert"
                            and ev.get("slo") == spec.name
                            and ev.get("ts", 0) >= t_chaos):
                        events.append(ev)
        events.sort(key=lambda e: e.get("ts", 0))
        pages = [e for e in events if e.get("state") == "firing"
                 and e.get("severity") == "page"]
        severity = "page" if pages else (
            events[-1].get("severity") if events else None)
        detection_ms = ((pages[0]["since"] - t_chaos) * 1e3
                        if pages and pages[0].get("since") else None)
        cleared = [e for e in events if e.get("state") == "cleared"
                   and pages and e["ts"] >= pages[0]["ts"]]
        clear_ms = ((cleared[-1]["ts"] - pages[0]["since"]) * 1e3
                    if cleared and pages[0].get("since") else None)
        final_quiet = mon.states()[0]["severity"] is None
        return {
            "replicas": n_replicas,
            "healthy_ttft_p50_ms": round(healthy_ms, 2),
            "ttft_threshold_ms": round(threshold_ms, 2),
            "chaos_slow_ms": slow_ms,
            "fast_window_s": fast_w,
            "slow_window_s": slow_w,
            "alert_severity": severity,
            "alert_detection_ms": (round(detection_ms, 1)
                                   if detection_ms is not None else None),
            "detected_within_fast_window": (
                detection_ms is not None and detection_ms <= fast_w * 1e3),
            "alert_cleared": bool(cleared) and final_quiet,
            "alert_firing_ms": (round(clear_ms, 1)
                                if clear_ms is not None else None),
            "slo_evaluations": evaluations,
            "slo_eval_overhead_pct": (round(overhead_pct, 4)
                                      if overhead_pct is not None else None),
            "paged_on_healthy_traffic": paged_on_healthy,
            "page_alerts_fired": int(metrics.counters("alerts.")["alerts.page"]),
        }
    finally:
        slo.uninstall()
        try:
            paddle.set_flags(prev_flags)
        except Exception:
            pass


def _measure_ingress():
    """The round-4 front-door phase: HTTP ingress over the cross-process
    fleet on the socket fast path. Measures the HTTP hop against the same
    fleet driven in-process, the socket transport against the store-poll
    transport on an identical workload, streaming TTFT over chunked
    transfer, the disconnect→cancel reaction, a drain under load, and the
    headline chaos pin: ``kill -9`` of a replica mid-decode with HTTP
    streams open — every stream must complete bitwise-identical to the
    unkilled reference, exactly once, through the real socket path."""
    import http.client
    import threading

    import jax

    if os.environ.get("BENCH_FORCE_CPU"):
        jax.config.update("jax_platforms", "cpu")

    import paddle_tpu as paddle
    from paddle_tpu.inference import ProcServingFleet, ServingIngress
    from paddle_tpu.models.gpt import GPTConfig
    from paddle_tpu.testing import chaos

    d0 = jax.devices()[0]
    on_tpu = d0.platform in ("tpu", "axon") or "TPU" in getattr(d0, "device_kind", "")
    if on_tpu:
        cfg = GPTConfig(vocab_size=50304, hidden_size=1024, num_layers=16,
                        num_heads=16, max_seq_len=1024)
        slots, max_seq, max_new, n_requests = 8, 1024, 16, 16
        chunk, fuse, n_replicas = 128, 8, 2
    else:
        cfg = GPTConfig.tiny()
        slots, max_seq, max_new, n_requests = 2, 128, 8, 8
        chunk, fuse, n_replicas = 16, 2, 2

    rng = np.random.default_rng(0)
    kw = dict(max_batch_slots=slots, max_seq_len=max_seq, prefill_chunk=chunk,
              fuse=fuse, heartbeat_timeout=120.0)
    prompts = [rng.integers(0, cfg.vocab_size, (int(n),)).astype("int32")
               for n in rng.integers(max(1, chunk // 4), chunk, n_requests)]
    bodies = [{"prompt": [int(t) for t in p], "max_new_tokens": max_new,
               "seed": i} for i, p in enumerate(prompts)]

    def _post(port, body, stream=False, key=None, timeout=600):
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
        headers = {"Content-Type": "application/json"}
        if key:
            headers["Idempotency-Key"] = key
        conn.request("POST", "/v1/generate",
                     body=json.dumps(body).encode(), headers=headers)
        r = conn.getresponse()
        if not stream:
            doc = json.loads(r.read())
            conn.close()
            return r.status, doc, None
        toks, t_first, final = [], None, None
        while True:
            line = r.readline()
            if not line:
                break
            doc = json.loads(line)
            if t_first is None:
                t_first = time.perf_counter()
            if "tokens" in doc:
                toks.extend(doc["tokens"])
            else:
                final = doc
        conn.close()
        return r.status, {"tokens": toks, "final": final}, t_first

    import tempfile

    cache_dir = tempfile.mkdtemp(prefix="bench_ingress_aot_")
    paddle.set_flags({"FLAGS_compile_cache_dir": cache_dir})
    try:
        # --- socket-transport fleet: in-process reference, then HTTP -----
        pf = ProcServingFleet(cfg, replicas=n_replicas, **kw)
        try:
            # untimed warm-up: the children compile their program family on
            # first prefill — both transport arms are timed warm
            for i, p in enumerate(prompts):
                pf.submit(p, max_new_tokens=max_new, seed=500 + i)
            pf.run(timeout_s=600)
            fids = [pf.submit(p, max_new_tokens=max_new, seed=i)
                    for i, p in enumerate(prompts)]
            t0 = time.perf_counter()
            pf.run(timeout_s=600)
            dt_direct = time.perf_counter() - t0
            assert all(pf.requests[f].status == "finished" for f in fids), \
                "direct run lost completions"
            want = [list(pf.requests[f].tokens) for f in fids]
            rps_direct = len(fids) / dt_direct if dt_direct > 0 else None

            ing = ServingIngress(pf, port=0)
            results = [None] * n_requests
            nthreads = min(4, n_requests)

            def http_worker(idxs):
                for i in idxs:
                    st, doc, _ = _post(ing.port, bodies[i])
                    results[i] = (st, doc)

            t0 = time.perf_counter()
            ts = [threading.Thread(target=http_worker,
                                   args=(range(k, n_requests, nthreads),))
                  for k in range(nthreads)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            dt_http = time.perf_counter() - t0
            for i, (st, doc) in enumerate(results):
                assert st == 200 and doc["status"] == "finished", (st, doc)
                assert doc["tokens"] == want[i], "http run diverged"
            rps_http = n_requests / dt_http if dt_http > 0 else None

            # streaming TTFT over HTTP (sequential — isolates the hop)
            ttfts = []
            for i in range(min(4, n_requests)):
                t0 = time.perf_counter()
                st, doc, t_first = _post(ing.port, dict(bodies[i], stream=True),
                                         stream=True)
                assert st == 200 and doc["tokens"] == want[i], "stream diverged"
                ttfts.append(t_first - t0)
            ttfts.sort()

            # client disconnect mid-stream -> mid-decode cancel
            long_body = dict(bodies[0], max_new_tokens=max_new * 8,
                             stream=True)
            conn = http.client.HTTPConnection("127.0.0.1", ing.port,
                                              timeout=600)
            conn.request("POST", "/v1/generate",
                         body=json.dumps(long_body).encode(),
                         headers={"Idempotency-Key": "bench-disconnect"})
            r = conn.getresponse()
            r.readline()  # one chunk is flowing; the request is mid-decode
            fid = ing._idem["bench-disconnect"].fid
            t0 = time.perf_counter()
            conn.sock.close()
            conn.close()
            while (pf.requests[fid].status not in
                   ("finished", "cancelled", "deadline_exceeded")
                   and time.perf_counter() - t0 < 30):
                time.sleep(0.002)
            disconnect_ms = (time.perf_counter() - t0) * 1e3
            disconnect_status = pf.requests[fid].status

            # drain under load: requests in flight when the drain begins
            drain_docs = []

            def drain_worker(i):
                _, doc, _ = _post(ing.port, dict(bodies[i], seed=100 + i))
                drain_docs.append(doc)

            dts = [threading.Thread(target=drain_worker, args=(i,))
                   for i in range(3)]
            for t in dts:
                t.start()
            t0 = time.perf_counter()
            while len(ing._active) < 3 and time.perf_counter() - t0 < 30:
                time.sleep(0.002)
            t0 = time.perf_counter()
            ing.begin_drain()
            drain_rc = ing.drain(grace=300)
            drain_ms = (time.perf_counter() - t0) * 1e3
            for t in dts:
                t.join()
            drain_finished = sum(1 for d in drain_docs
                                 if d.get("status") == "finished")
        finally:
            pf.shutdown()

        # --- store-poll transport: identical workload, sockets off -------
        pf_s = ProcServingFleet(cfg, replicas=n_replicas, use_sockets=False,
                                **kw)
        try:
            for i, p in enumerate(prompts):  # warm, like the socket arm
                pf_s.submit(p, max_new_tokens=max_new, seed=500 + i)
            pf_s.run(timeout_s=600)
            fids = [pf_s.submit(p, max_new_tokens=max_new, seed=i)
                    for i, p in enumerate(prompts)]
            t0 = time.perf_counter()
            pf_s.run(timeout_s=600)
            dt_store = time.perf_counter() - t0
            assert all(pf_s.requests[f].status == "finished" for f in fids), \
                "store run lost completions"
            got = [list(pf_s.requests[f].tokens) for f in fids]
            assert got == want, "store transport diverged"
        finally:
            pf_s.shutdown()

        # --- kill -9 through the ingress: bitwise exactly-once -----------
        with chaos.inject(
                FLAGS_chaos_replica_sigkill_at=f"{n_replicas - 1}:2"):
            pf_k = ProcServingFleet(cfg, replicas=n_replicas, **kw)
            ing_k = ServingIngress(pf_k, port=0)
            try:
                kill_docs = [None] * 4

                def kill_worker(i):
                    st, doc, _ = _post(ing_k.port,
                                       dict(bodies[i], stream=True),
                                       stream=True)
                    kill_docs[i] = (st, doc)

                kts = [threading.Thread(target=kill_worker, args=(i,))
                       for i in range(4)]
                for t in kts:
                    t.start()
                for t in kts:
                    t.join()
                for i, (st, doc) in enumerate(kill_docs):
                    assert st == 200, f"kill arm http {st}"
                    assert doc["final"]["status"] == "finished", doc["final"]
                    assert doc["tokens"] == want[i], \
                        f"kill arm diverged on stream {i}"
                stats_k = pf_k.stats()
            finally:
                ing_k.stop()
                pf_k.shutdown()
    finally:
        try:
            paddle.set_flags({"FLAGS_compile_cache_dir": ""})
        except Exception:
            pass

    socket_vs_store = ((dt_direct / dt_store - 1.0) * 100.0
                       if dt_store > 0 else None)
    return {
        "replicas": n_replicas,
        "requests": n_requests,
        "ingress_requests_per_sec": round(rps_http, 3) if rps_http else None,
        "requests_per_sec_inproc": round(rps_direct, 3) if rps_direct else None,
        "http_overhead_pct": (round((dt_http / dt_direct - 1.0) * 100.0, 2)
                              if dt_direct > 0 else None),
        "socket_vs_store_overhead_pct": (round(socket_vs_store, 2)
                                         if socket_vs_store is not None
                                         else None),
        "stream_ttft_p50_ms": round(_percentile(ttfts, 50) * 1e3, 2),
        "disconnect_cancel_ms": round(disconnect_ms, 2),
        "disconnect_status": disconnect_status,
        "drain_under_load_ms": round(drain_ms, 2),
        "drain_rc": drain_rc,
        "drain_finished": drain_finished,
        "drain_inflight": len(drain_docs),
        "exactly_once_under_sigkill": True,  # asserted above, bitwise
        "requeues_under_sigkill": stats_k["requeues"],
        "replica_deaths": len(stats_k["dead"]),
    }


def main():
    if os.environ.get("BENCH_ONE") == "alerts":
        print(json.dumps(_measure_alerts()))
        return
    if os.environ.get("BENCH_ONE") == "spec":
        print(json.dumps(_measure_spec()))
        return
    if os.environ.get("BENCH_ONE") == "fleet":
        print(json.dumps(_measure_fleet()))
        return
    if os.environ.get("BENCH_ONE") == "procfleet":
        print(json.dumps(_measure_procfleet()))
        return
    if os.environ.get("BENCH_ONE") == "ingress":
        print(json.dumps(_measure_ingress()))
        return
    if os.environ.get("BENCH_ONE"):
        print(json.dumps(_measure()))
        return

    from __graft_entry__ import _probe_default_backend

    budget = float(os.environ.get("BENCH_BUDGET_SERVE", 420))
    budget_fleet = float(os.environ.get("BENCH_BUDGET_FLEET", 300))
    budget_procfleet = float(os.environ.get("BENCH_BUDGET_PROCFLEET", 300))
    budget_spec = float(os.environ.get("BENCH_BUDGET_SPEC", 300))
    budget_alerts = float(os.environ.get("BENCH_BUDGET_ALERTS", 240))
    budget_ingress = float(os.environ.get("BENCH_BUDGET_INGRESS", 420))
    verdict = _probe_default_backend(timeout=75.0)
    extras = None
    fleet_info = None
    procfleet_info = None
    spec_info = None
    alerts_info = None
    ingress_info = None
    error = None
    fallback = None
    if verdict is None:
        try:  # no subprocess machinery: measure in-process (CPU sandboxes)
            extras = _measure()
        except Exception as exc:
            error = f"{type(exc).__name__}: {exc}"
        try:
            spec_info = _measure_spec()
        except Exception as exc:
            spec_info = {"status": "error",
                         "error": f"{type(exc).__name__}: {exc}"}
        try:
            fleet_info = _measure_fleet()
        except Exception as exc:
            fleet_info = {"status": "error",
                          "error": f"{type(exc).__name__}: {exc}"}
        try:
            procfleet_info = _measure_procfleet()
        except Exception as exc:
            procfleet_info = {"status": "error",
                              "error": f"{type(exc).__name__}: {exc}"}
        try:
            alerts_info = _measure_alerts()
        except Exception as exc:
            alerts_info = {"status": "error",
                           "error": f"{type(exc).__name__}: {exc}"}
        try:
            ingress_info = _measure_ingress()
        except Exception as exc:
            ingress_info = {"status": "error",
                            "error": f"{type(exc).__name__}: {exc}"}
    else:
        import subprocess

        def _child(force_cpu, which="serve", timeout=None):
            env = dict(os.environ, BENCH_ONE=which)
            if force_cpu:
                env["BENCH_FORCE_CPU"] = "1"
                env["JAX_PLATFORMS"] = "cpu"
            r = subprocess.run([sys.executable, os.path.abspath(__file__)], env=env,
                               capture_output=True, text=True,
                               timeout=budget if timeout is None else timeout)
            line = [l for l in r.stdout.splitlines() if l.startswith("{")][-1]
            return json.loads(line)

        if verdict is True:
            try:
                extras = _child(force_cpu=False)
            except Exception:
                fallback = "serve_bench_failed"
        else:
            fallback = "tpu_unreachable"
        if extras is None:
            try:  # graceful CPU fallback: still a real serving signal
                extras = _child(force_cpu=True)
            except Exception as exc:
                error = fallback or f"{type(exc).__name__}"
        # spec-decode + int8-KV phase (round 3): own budget, own child
        try:
            spec_info = _child(force_cpu=(verdict is not True),
                               which="spec", timeout=budget_spec)
        except subprocess.TimeoutExpired:
            spec_info = {"status": "timeout", "budget_seconds": budget_spec}
        except Exception as exc:
            spec_info = {"status": "error", "error": f"{type(exc).__name__}"}
        # fleet phase: own budget, own child, graceful degradation — a
        # timeout or crash leaves a structured status in the JSON, rc 0
        try:
            fleet_info = _child(force_cpu=(verdict is not True),
                                which="fleet", timeout=budget_fleet)
        except subprocess.TimeoutExpired:
            fleet_info = {"status": "timeout", "budget_seconds": budget_fleet}
        except Exception as exc:
            fleet_info = {"status": "error", "error": f"{type(exc).__name__}"}
        # procfleet phase: subprocess replicas under real SIGKILL — its own
        # budget and child so a wedged transport can't eat the whole bench
        try:
            procfleet_info = _child(force_cpu=(verdict is not True),
                                    which="procfleet",
                                    timeout=budget_procfleet)
        except subprocess.TimeoutExpired:
            procfleet_info = {"status": "timeout",
                              "budget_seconds": budget_procfleet}
        except Exception as exc:
            procfleet_info = {"status": "error",
                              "error": f"{type(exc).__name__}"}
        # alerting arm (round 3): chaos spike -> page -> clear, plus the
        # monitor's eval overhead — own budget and child like the others
        try:
            alerts_info = _child(force_cpu=(verdict is not True),
                                 which="alerts", timeout=budget_alerts)
        except subprocess.TimeoutExpired:
            alerts_info = {"status": "timeout",
                           "budget_seconds": budget_alerts}
        except Exception as exc:
            alerts_info = {"status": "error",
                           "error": f"{type(exc).__name__}"}
        # ingress phase (round 4): HTTP front door + socket fast path under
        # real SIGKILL — own budget and child like the other fleet phases
        try:
            ingress_info = _child(force_cpu=(verdict is not True),
                                  which="ingress", timeout=budget_ingress)
        except subprocess.TimeoutExpired:
            ingress_info = {"status": "timeout",
                            "budget_seconds": budget_ingress}
        except Exception as exc:
            ingress_info = {"status": "error",
                            "error": f"{type(exc).__name__}"}

    if extras is None:
        print(json.dumps({"metric": "gpt_serving_throughput", "value": None,
                          "unit": "requests/sec", "vs_baseline": None,
                          "requests_per_sec": None, "latency_p50_ms": None,
                          "latency_p99_ms": None, "fleet": fleet_info,
                          "procfleet": procfleet_info, "spec": spec_info,
                          "alerts": alerts_info, "ingress": ingress_info,
                          "error": error or "bench_error"}))
        return

    base_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "bench_serve_baseline.json")
    vs = 1.0
    if os.path.exists(base_path):
        try:
            prior = json.load(open(base_path))
            if prior.get("config") == extras.get("config") and prior.get("value"):
                vs = extras["value"] / prior["value"]
                # round-2 acceptance ratios vs the committed baseline:
                # throughput-style fields improve UP, latency-style DOWN
                if prior.get("decode_tokens_per_sec"):
                    extras["decode_tokens_per_sec_vs_baseline"] = round(
                        extras["decode_tokens_per_sec"] / prior["decode_tokens_per_sec"], 4)
                if prior.get("ttft_p50_ms"):
                    extras["ttft_p50_ms_vs_baseline"] = round(
                        prior["ttft_p50_ms"] / extras["ttft_p50_ms"], 4)
        except Exception:
            pass
    else:
        try:
            json.dump({"metric": "gpt_serving_throughput", "value": extras["value"],
                       "unit": "requests/sec", "config": extras.get("config"),
                       "decode_tokens_per_sec": extras.get("decode_tokens_per_sec"),
                       "ttft_p50_ms": extras.get("ttft_p50_ms")},
                      open(base_path, "w"))
        except OSError:
            pass

    out = {"metric": "gpt_serving_throughput", "value": extras["value"],
           "unit": "requests/sec", "vs_baseline": round(vs, 4)}
    out.update({k: v for k, v in extras.items() if k not in ("value",)})
    if spec_info is not None:
        out["spec"] = spec_info
    if fleet_info is not None:
        out["fleet"] = fleet_info
    if procfleet_info is not None:
        out["procfleet"] = procfleet_info
    if alerts_info is not None:
        out["alerts"] = alerts_info
    if ingress_info is not None:
        out["ingress"] = ingress_info
    if fallback:
        out["fallback"] = fallback
    print(json.dumps(out))


if __name__ == "__main__":
    try:
        main()
    except Exception as exc:  # any unplanned failure still emits one line
        print(json.dumps({"metric": "gpt_serving_throughput", "value": None,
                          "unit": "requests/sec", "vs_baseline": None,
                          "error": f"{type(exc).__name__}: {exc}"}))
    sys.exit(0)
