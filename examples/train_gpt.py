"""Fleet hybrid GPT pretraining: the reference's
fleetrun + DistributedStrategy flow, TPU-native.

Run:  python examples/train_gpt.py          (8-dev virtual CPU mesh by default
                                             when no TPU is attached)
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
# must land before the first jax backend init: 8 virtual devices on CPU
os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
    " --xla_force_host_platform_device_count=8"

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.distributed import fleet
from paddle_tpu.models.gpt import GPTConfig, GPTForPretraining, GPTPretrainingCriterion


def main():
    strategy = paddle.distributed.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 2, "pp_degree": 2,
                               "sharding_degree": 2}
    import jax

    on_tpu = any(d.platform in ("tpu", "axon") for d in jax.devices())
    strategy.amp = on_tpu  # bf16 allreduce promotion trips XLA's CPU backend
    strategy.amp_configs = {"level": "O2"}
    strategy.pipeline_configs = {"accumulate_steps": 4, "schedule": "1f1b"}
    fleet.init(is_collective=True, strategy=strategy)

    cfg = GPTConfig.tiny()
    model = GPTForPretraining(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=3e-4, parameters=model.parameters())
    step = fleet.distributed_step(model, opt, GPTPretrainingCriterion())

    rng = np.random.default_rng(0)
    for it in range(5):
        ids = rng.integers(0, cfg.vocab_size, (8, 64)).astype("int32")
        metrics = step(paddle.to_tensor(ids), paddle.to_tensor(ids))
        print(f"iter {it} loss {float(metrics['loss']):.4f} lr {float(metrics['lr']):.2e}")

    # outputs land under the gitignored examples/_out (override with
    # PADDLE_TPU_EXAMPLE_OUT) so test/bench runs leave `git status` clean
    out_root = os.environ.get(
        "PADDLE_TPU_EXAMPLE_OUT",
        os.path.join(os.path.dirname(os.path.abspath(__file__)), "_out"))
    ckpt_dir = os.path.join(out_root, "gpt_ckpt")
    paddle.distributed.checkpoint.save_train_step(step, ckpt_dir)
    print("checkpoint saved to", ckpt_dir)


if __name__ == "__main__":
    sys.exit(main())
