"""hapi Model.fit -> QAT -> int8 Predictor: the train-to-deploy loop.

Run:  python examples/finetune_classifier.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("PT_EXAMPLE_CPU", "1")
import jax

if os.environ["PT_EXAMPLE_CPU"] == "1" and not any(
        d.platform in ("tpu", "axon") for d in jax.devices()):
    jax.config.update("jax_platforms", "cpu")

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.vision.transforms as T
from paddle_tpu.inference import Config, create_predictor
from paddle_tpu.quantization import ImperativeQuantAware


def make_data(n=256):
    rng = np.random.default_rng(0)
    xs = rng.standard_normal((n, 1, 12, 12)).astype("float32")
    ys = (xs.mean((1, 2, 3)) > 0).astype("int64")
    return xs, ys


def main():
    pipeline = T.Compose([T.Normalize(mean=[0.0], std=[1.0])])
    xs, ys = make_data()
    xs = np.stack([pipeline(x) for x in xs])

    net = paddle.nn.Sequential(
        paddle.nn.Conv2D(1, 8, 3, padding=1), paddle.nn.ReLU(),
        paddle.nn.Flatten(), paddle.nn.Linear(8 * 12 * 12, 2))
    qat = ImperativeQuantAware()
    qat.quantize(net)

    model = paddle.Model(net)
    model.prepare(paddle.optimizer.Adam(learning_rate=1e-3, parameters=net.parameters()),
                  paddle.nn.CrossEntropyLoss(), paddle.metric.Accuracy())
    class ArrayDataset(paddle.io.Dataset):
        def __len__(self):
            return len(xs)

        def __getitem__(self, i):
            return xs[i], ys[i]

    model.fit(ArrayDataset(), epochs=2, batch_size=32, verbose=1,
              callbacks=[paddle.callbacks.EarlyStopping(monitor="loss", patience=3)])

    # gitignored output dir (override with PADDLE_TPU_EXAMPLE_OUT) so
    # test/bench runs leave `git status` clean
    out_root = os.environ.get(
        "PADDLE_TPU_EXAMPLE_OUT",
        os.path.join(os.path.dirname(os.path.abspath(__file__)), "_out"))
    os.makedirs(out_root, exist_ok=True)
    prefix = os.path.join(out_root, "clf_int8")
    net.eval()
    qat.save_quantized_model(net, prefix,
                             input_spec=[paddle.static.InputSpec([None, 1, 12, 12], "float32")])
    pred = create_predictor(Config(prefix))
    (probs,) = pred.run([xs[:4]])
    print("served int8 logits:", np.asarray(probs).round(3))


if __name__ == "__main__":
    main()
