"""DLRM recommender training over mesh-sharded embedding tables.

The recsys workload vertical end to end: the planner picks a row-sharded
plan for the fused embedding table on a dp4 mesh, training runs K steps
per XLA dispatch with the RowSparseAdam touched-rows-only update, the
online-learning hook rotates row-sharded checkpoints, and an elastic
scale-down (dp4 -> dp2) restores the table bitwise through the cross-mesh
converter.

Run:  python examples/train_dlrm.py    (4-dev virtual CPU mesh by default
                                        when no TPU is attached)
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
# must land before the first jax backend init: virtual devices on CPU
os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
    " --xla_force_host_platform_device_count=4"

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.distributed import planner
from paddle_tpu.distributed.embedding import EmbeddingCheckpointRotation
from paddle_tpu.distributed.resilience import CheckpointManager
from paddle_tpu.models.dlrm import DLRM, DLRMConfig, DLRMCriterion
from paddle_tpu.observability.metrics import counter_inc
from paddle_tpu.stability import state_to_savable


def make_batch(rng, cfg, batch):
    dense = rng.normal(size=(batch, cfg.num_dense)).astype(np.float32)
    ids = np.stack([np.minimum((rng.pareto(1.05, batch) * (v // 20))
                               .astype(np.int64), v - 1)
                    for v in cfg.vocab_sizes], axis=1).astype(np.int32)
    labels = rng.integers(0, 2, (batch, 1)).astype(np.float32)
    return (dense, ids), (labels,)


def build(cfg, ndev, plan):
    """Fresh model + RowSparseAdam + the plan's sharded TrainStep."""
    import jax

    paddle.seed(0)
    model = DLRM(cfg)
    opt = paddle.optimizer.RowSparseAdam(
        learning_rate=1e-2, parameters=model.parameters(),
        sparse_params=model.sparse_param_names())
    step = planner.build_step(model, opt, DLRMCriterion(), plan,
                              devices=jax.devices()[:ndev], seed=0)
    return model, step


def main():
    import jax

    cfg = DLRMConfig(num_dense=8, vocab_sizes=(512, 256, 1024), embedding_dim=16,
                     bottom_mlp=(32,), top_mlp=(32,))
    batch, k = 64, 4
    ndev = min(4, len(jax.devices()))

    # 1. the planner chooses the parallel plan — its template generator
    # row-shards the ShardedEmbedding table in every candidate
    inputs = [jax.ShapeDtypeStruct((batch, cfg.num_dense), np.float32),
              jax.ShapeDtypeStruct((batch, cfg.num_sparse), np.int32)]
    labels_spec = [jax.ShapeDtypeStruct((batch, 1), np.float32)]
    paddle.seed(0)
    probe = DLRM(cfg)
    plans = planner.search(
        probe, ndev, inputs_spec=inputs, labels_spec=labels_spec,
        loss=DLRMCriterion(),
        optimizer=paddle.optimizer.RowSparseAdam(
            learning_rate=1e-2, parameters=probe.parameters(),
            sparse_params=probe.sparse_param_names()),
        meshes=[{"dp": ndev}] if ndev > 1 else [{}], cache=False)
    plan = next(p for p in plans if p.feasible)
    print(f"plan: {plan.label}  embedding spec: "
          f"{plan.param_specs['embedding.weight']}")

    model, step = build(cfg, ndev, plan)
    exch = model.embedding.exchange_stats(batch * cfg.num_sparse, shards=ndev)
    print(f"embedding exchange: {exch['shards']} shards, "
          f"{exch['bytes_total']:,} a2a bytes/step")

    # 2. online training: K steps per dispatch + checkpoint rotation
    out_root = os.environ.get(
        "PADDLE_TPU_EXAMPLE_OUT",
        os.path.join(os.path.dirname(os.path.abspath(__file__)), "_out"))
    rot = EmbeddingCheckpointRotation(
        CheckpointManager(os.path.join(out_root, "dlrm_ckpt"), keep_last_k=2),
        every=2 * k, table_names=model.sparse_param_names())
    rng = np.random.default_rng(0)
    step.run_steps([make_batch(rng, cfg, batch) for _ in range(k)])  # compile
    t0 = time.perf_counter()
    done = 0
    for it in range(4):
        metrics = step.run_steps([make_batch(rng, cfg, batch) for _ in range(k)])
        done += k
        counter_inc("recsys.steps", k)
        counter_inc("recsys.examples", k * batch)
        rot.maybe_save(step.state, done)
        print(f"dispatch {it}: loss {float(metrics['loss'].numpy()[-1]):.4f}")
    dt = time.perf_counter() - t0
    print(f"trained {done * batch} examples at "
          f"{done * batch / dt:,.0f} examples/sec ({k} steps/dispatch)")

    # 3. elastic scale-down: restore the dp4 checkpoint onto a dp2 mesh —
    # the converter re-partitions the row-sharded table bitwise
    rot.save(step.state, done)  # publish the final state before rescaling
    before = np.asarray(step.state["params"]["embedding.weight"])
    ndev2 = max(1, ndev // 2)
    plan2 = planner.Plan(mesh={"dp": ndev2} if ndev2 > 1 else {},
                         template="row", n_devices=ndev2,
                         param_specs={"embedding.weight": ["dp"]})
    model2, step2 = build(cfg, ndev2, plan2)
    state2, at = rot.restore(target=state_to_savable(step2.state),
                             shardings=dict(step2._state_shardings))
    step2.set_state(state2)
    after = np.asarray(step2.state["params"]["embedding.weight"])
    print(f"resharded dp{ndev} -> dp{ndev2} bitwise: "
          f"{np.array_equal(before, after)} (checkpoint step {at})")
    m2 = step2.run_steps([make_batch(rng, cfg, batch) for _ in range(k)])
    print(f"resumed on dp{ndev2}: loss {float(m2['loss'].numpy()[-1]):.4f}")


if __name__ == "__main__":
    sys.exit(main())
