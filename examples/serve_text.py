"""Raw text -> tokenizer -> model -> label, streamed through the
FleetExecutor interceptor pipeline (the reference's serving DAG).

Run:  python examples/serve_text.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

if not any(d.platform in ("tpu", "axon") for d in jax.devices()):
    jax.config.update("jax_platforms", "cpu")

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.distributed import FleetExecutor, TaskNode
from paddle_tpu.framework import FasterTokenizer, StringTensor

VOCAB = {t: i for i, t in enumerate(
    ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "great", "terrible", "movie", "plot", "acting"])}


def main():
    tok = FasterTokenizer(VOCAB)
    emb = paddle.nn.Embedding(len(VOCAB), 16)
    head = paddle.nn.Linear(16, 2)

    def classify(ids):
        import paddle_tpu.nn.functional as F

        h = emb(paddle.to_tensor(ids))
        pooled = F.sequence_pool(h, paddle.to_tensor((ids != 0).sum(-1)), "average")
        return ["negative", "positive"][int(np.argmax(np.asarray(head(pooled).numpy())))]

    fe = FleetExecutor().init([
        TaskNode(lambda s: tok([s], max_seq_len=16)[0], name="tokenize"),
        TaskNode(classify, name="classify"),
    ])
    reqs = StringTensor(["great movie great plot", "terrible acting", "movie plot"])
    for text, label in zip(reqs, fe.run(reqs)):
        print(f"{str(text)!r:<28} -> {label}")


if __name__ == "__main__":
    main()
