"""GPT serving end to end: export → Predictor → generate → continuous
batching.

1. export a decoder artifact (StableHLO: prefill + KV-cache token loop) and
   serve it through paddle.inference.create_predictor;
2. serve the LIVE model through the static-KV-cache DecodeEngine — exactly
   two compiled programs (bucketed prefill + decode step, donated cache
   buffers) for the whole request stream;
3. run a continuous-batching burst: requests with mixed prompt lengths
   admitted into free batch slots mid-flight, with request-level telemetry;
4. the round-2 hot path: ``fuse=D`` (D decode tokens per dispatch inside
   one donated scan — use whenever per-dispatch host overhead is visible),
   ``prefill_chunk=C`` (prompts prefill in C-token dispatches interleaved
   with decode — use when long prompts would stall the stream, and to
   collapse the prefill compile family to 2 programs), and
   ``prefix_cache_mb=M`` (KV reuse across requests sharing a prompt prefix
   — use when traffic shares system prompts / few-shot headers). All three
   keep tokens bitwise equal to the plain path;
5. (``--fleet``) the fault-tolerant fleet tier: 2 engine replicas behind
   the prefix-affinity router, a chaos-injected replica kill mid-stream,
   and every request finishing exactly once with tokens bitwise-equal to
   the unkilled run — plus a load-shed and a deadline expiry;
6. (``--http``) the network boundary: ``ServingIngress`` in front of the
   fleet — a real HTTP POST, an idempotent retry replaying the same
   answer, a chunked per-token stream, and a graceful drain to exit 0.

Run:  python examples/serve_gpt.py [--fleet] [--http]
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

if not any(d.platform in ("tpu", "axon") for d in jax.devices()):
    jax.config.update("jax_platforms", "cpu")

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import profiler
from paddle_tpu.inference import Config, ContinuousBatchingScheduler, DecodeEngine, create_predictor
from paddle_tpu.models.gpt import GPTConfig, GPTForPretraining

OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "_out")


def main():
    paddle.seed(0)
    cfg = GPTConfig.tiny()
    model = GPTForPretraining(cfg)
    model.eval()
    rng = np.random.default_rng(0)

    # 1) export the whole decode loop as a deployable StableHLO artifact
    prefix = os.path.join(OUT, "gpt_decoder")
    model.export_decoder(prefix, prompt_len=8, max_new_tokens=8)
    pred = create_predictor(Config(prefix))
    ids = rng.integers(0, cfg.vocab_size, (2, 8)).astype("int32")
    tokens = pred.generate(ids)
    print(f"predictor[{pred.get_resolved_backend()}] served {tokens.shape[1] - 8} "
          f"tokens/row from the exported artifact")

    # 2) the serving engine: static KV cache, 2 compiled programs total
    profiler.reset_counters("infer.")
    engine = DecodeEngine(model, max_batch_slots=4, max_seq_len=64,
                          prefill_buckets=(8, 16, 32))
    out = engine.generate(ids, max_new_tokens=12)
    c = profiler.counters("infer.")
    print(f"engine decoded {out.shape[1] - ids.shape[1]} tokens/row with "
          f"{int(c['infer.compiles'])} compiled programs "
          f"(prefill + step), cache {engine.cache_bytes() // 1024} KiB")

    # 3) continuous batching: admit-into-free-slots over mixed prompts
    sched = ContinuousBatchingScheduler(engine)
    for n in (5, 9, 3, 14, 7, 11):
        sched.submit(rng.integers(0, cfg.vocab_size, (n,)).astype("int32"),
                     max_new_tokens=6)
    done = sched.run()
    for rid in sorted(done):
        r = done[rid]
        print(f"  request {rid}: prompt {len(r.prompt):>2} tok (bucket {r.bucket:>2}) "
              f"slot {r.slot} -> {len(r.tokens)} tokens in {r.total_seconds * 1e3:6.1f} ms "
              f"(ttft {r.ttft_seconds * 1e3:5.1f} ms)")
    lat = sorted(r.total_seconds for r in done.values())
    print(f"served {len(done)} requests, p50 latency {lat[len(lat) // 2] * 1e3:.1f} ms")

    # 4) round-2 knobs: fused decode + chunked prefill + prefix reuse.
    #    The burst shares a 16-token system prompt, so after the first
    #    admission every request's shared prefix comes from the KV cache.
    profiler.reset_counters("infer.")
    engine2 = DecodeEngine(model, max_batch_slots=4, max_seq_len=64,
                           fuse=4, prefill_chunk=8, prefix_cache_mb=16.0)
    sched2 = ContinuousBatchingScheduler(engine2)
    system = rng.integers(0, cfg.vocab_size, (16,)).astype("int32")
    for n in (5, 9, 3, 14, 7, 11):
        prompt = np.concatenate([system, rng.integers(0, cfg.vocab_size, (n,)).astype("int32")])
        sched2.submit(prompt, max_new_tokens=8)
    done2 = sched2.run()
    c = profiler.counters("infer.")
    ps = engine2.prefix_cache.stats()
    toks = sum(len(r.tokens) for r in done2.values())
    print(f"round-2 engine served {len(done2)} requests / {toks} tokens with "
          f"{int(c['infer.decode_dispatches'])} decode dispatches (fuse=4), "
          f"{int(c['infer.compiles'])} compiles "
          f"(chunk + final + fused step + prefix insert/extract)")
    print(f"  prefix cache: {ps['hits']} hits / {ps['misses']} misses, "
          f"{ps['entries']} chunks ({ps['bytes_used'] // 1024} KiB), "
          f"stall p99 {max(r.stall_seconds for r in done2.values()) * 1e3:.2f} ms")

    # 5) (--fleet) the fault-tolerant fleet: replica kill mid-stream,
    #    requeue onto the survivor, exactly-once bitwise completions
    if "--fleet" in sys.argv:
        fleet_stage(model, rng, cfg)

    # 6) (--http) the network boundary: HTTP front door over the fleet
    if "--http" in sys.argv:
        http_stage(model, rng, cfg)


def fleet_stage(model, rng, cfg):
    from paddle_tpu.inference import FleetOverloadError, ServingFleet
    from paddle_tpu.testing import chaos

    kw = dict(max_batch_slots=2, max_seq_len=64, prefill_chunk=8, fuse=2)
    prompts = [rng.integers(0, cfg.vocab_size, (int(n),)).astype("int32")
               for n in (5, 9, 3, 12, 7, 11)]

    # unkilled single-replica reference: the tokens every request must get
    ref = ServingFleet(model, replicas=1, **kw)
    want = [ref.submit(p, max_new_tokens=6, seed=i) for i, p in enumerate(prompts)]
    ref_done = ref.run()
    want = [list(ref_done[f].tokens) for f in want]

    with chaos.inject(FLAGS_chaos_replica_kill_at="1:2"):
        fleet = ServingFleet(model, replicas=2, **kw)
        fids = [fleet.submit(p, max_new_tokens=6, seed=i)
                for i, p in enumerate(prompts)]
        done = fleet.run()
    st = fleet.stats()
    ok = all(list(done[f].tokens) == want[i] for i, f in enumerate(fids))
    print(f"fleet served {len(done)}/{len(prompts)} requests through a "
          f"mid-stream replica kill (dead: {st['dead']}, requeues: "
          f"{st['requeues']}), tokens bitwise-equal to the unkilled run: {ok}")

    # graceful degradation: deadline expiry + queue-depth shed
    small = ServingFleet(model, replicas=1, max_queue_depth=2, **kw)
    fid = small.submit(prompts[3], max_new_tokens=40, deadline_s=0.001)
    small.run()
    print(f"  deadline: request {fid} ended "
          f"{small.requests[fid].status} (slot reclaimed, not drained)")
    small.submit(prompts[0], max_new_tokens=4)
    small.submit(prompts[1], max_new_tokens=4)
    try:
        small.submit(prompts[2], max_new_tokens=4)
    except FleetOverloadError as e:
        print(f"  overload shed: {e}")
    small.run()


def http_stage(model, rng, cfg):
    import http.client
    import json

    from paddle_tpu.inference import ServingFleet, ServingIngress

    kw = dict(max_batch_slots=2, max_seq_len=64, prefill_chunk=8, fuse=2)
    fleet = ServingFleet(model, replicas=2, **kw)
    ing = ServingIngress(fleet, port=0)
    prompt = rng.integers(0, cfg.vocab_size, (6,)).astype("int32").tolist()

    def post(body, key=None, stream=False):
        conn = http.client.HTTPConnection("127.0.0.1", ing.port, timeout=60)
        hdrs = {"Content-Type": "application/json"}
        if key:
            hdrs["Idempotency-Key"] = key
        conn.request("POST", "/v1/generate", json.dumps(body), hdrs)
        resp = conn.getresponse()
        if stream:
            lines = [json.loads(ln) for ln in resp.read().splitlines() if ln]
            conn.close()
            return lines
        doc = json.loads(resp.read())
        conn.close()
        return doc

    # a real request over the wire, then an idempotent retry of the same
    # key: the ingress replays the ledger answer, never re-generates
    body = {"prompt": prompt, "max_new_tokens": 8, "seed": 7}
    first = post(body, key="example-1")
    again = post(body, key="example-1")
    replay = first["tokens"] == again["tokens"] and first["fid"] == again["fid"]
    print(f"http: POST /v1/generate -> {first['status']}, "
          f"{len(first['tokens'])} tokens; idempotent retry replayed "
          f"fid {again['fid']}: {replay}")

    # per-token chunked streaming rides the same exactly-once ledger
    lines = post(dict(body, seed=8, stream=True), stream=True)
    toks = [t for ln in lines if "tokens" in ln for t in ln["tokens"]]
    print(f"http: streamed {len(toks)} tokens in {len(lines) - 1} chunks, "
          f"final status {lines[-1].get('status')}")

    # graceful drain: healthz flips NotReady, in-flight finishes, exit 0
    ing.begin_drain()
    rc = ing.drain(grace=30.0)
    print(f"http: drained with exit code {rc}")


if __name__ == "__main__":
    main()
