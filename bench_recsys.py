"""Recommender-workload benchmark: DLRM over mesh-sharded embedding tables.

Prints ONE JSON line on the bench.py schema: {"metric", "value", "unit",
"vs_baseline", ...}. Measurements:

1. **recsys_examples_per_sec** — DLRM (dense bottom MLP + fused
   ``ShardedEmbedding`` bags + pairwise interaction + top MLP) trained
   through the one-dispatch ``TrainStep.run_steps`` scan on a row-sharded
   dp mesh with the ``RowSparseAdam`` touched-rows-only optimizer path;
2. **embedding_a2a_bytes_per_step** — the static per-step ``all_to_all``
   exchange payload (ids + embeddings, fwd + grad push) the sharded
   lookup declares from shapes alone;
3. **touched_row_fraction** — mean unique-ids / vocab over the measured
   batches: the fraction of the table a step actually updates, the number
   that justifies the row-sparse optimizer contract.

Like bench.py / bench_serve.py, this process NEVER hangs into the driver's
timeout and never exits non-zero: the default backend is probed in a
throwaway child first, the measured run gets its own subprocess under
``BENCH_BUDGET_RECSYS``, and any timeout/crash still emits one parseable
JSON line with a structured status at rc 0.
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def _measure():
    import jax

    if os.environ.get("BENCH_FORCE_CPU"):
        jax.config.update("jax_platforms", "cpu")

    import paddle_tpu as paddle
    from paddle_tpu import profiler
    from paddle_tpu.distributed.planner import Plan, build_step
    from paddle_tpu.models.dlrm import DLRM, DLRMConfig, DLRMCriterion
    from paddle_tpu.observability.metrics import counter_inc
    from paddle_tpu.optimizer import RowSparseAdam

    d0 = jax.devices()[0]
    on_tpu = d0.platform in ("tpu", "axon") or "TPU" in getattr(d0, "device_kind", "")
    ndev = len(jax.devices())
    if on_tpu:
        cfg = DLRMConfig(num_dense=13, vocab_sizes=(100_000,) * 8,
                         embedding_dim=64, bottom_mlp=(256, 128),
                         top_mlp=(256, 128))
        batch, k, rounds = 4096, 8, 4
        shards = ndev
    else:
        cfg = DLRMConfig(num_dense=8, vocab_sizes=(512, 256, 1024, 512),
                         embedding_dim=16, bottom_mlp=(32,), top_mlp=(32,))
        batch, k, rounds = 64, 8, 4
        shards = min(4, ndev)

    paddle.seed(0)
    model = DLRM(cfg)
    opt = RowSparseAdam(learning_rate=1e-3, parameters=model.parameters(),
                        sparse_params=model.sparse_param_names())
    plan = Plan(mesh={"dp": shards} if shards > 1 else {}, template="row",
                n_devices=shards, param_specs={"embedding.weight": ["dp"]})
    step = build_step(model, opt, DLRMCriterion(), plan,
                      devices=jax.devices()[:shards], seed=0)

    rng = np.random.default_rng(0)

    def make_batch():
        dense = rng.normal(size=(batch, cfg.num_dense)).astype(np.float32)
        # power-law id skew: the recsys-traffic shape (hot head, long tail)
        ids = np.stack(
            [np.minimum((rng.pareto(1.05, batch) * (v // 50)).astype(np.int64),
                        v - 1) for v in cfg.vocab_sizes], axis=1).astype(np.int32)
        labels = rng.integers(0, 2, (batch, 1)).astype(np.float32)
        return (dense, ids), (labels,)

    stacks = [[make_batch() for _ in range(k)] for _ in range(rounds)]
    offsets = np.cumsum((0,) + cfg.vocab_sizes[:-1])[None, :]
    touched = np.mean([  # per-STEP touched fraction of the fused table
        np.unique(b[0][1] + offsets).size / cfg.total_vocab
        for stack in stacks for b in stack])

    t_build0 = time.perf_counter()
    step.run_steps(stacks[0])  # compile (run_steps scan) + first dispatch
    ttfs = time.perf_counter() - t_build0

    profiler.reset_counters("train_step.")
    t0 = time.perf_counter()
    last = None
    for stack in stacks:
        last = step.run_steps(stack)
    float(last["loss"].numpy()[-1])  # host sync: everything above finished
    dt = time.perf_counter() - t0
    steps = rounds * k
    counter_inc("recsys.steps", steps)
    counter_inc("recsys.examples", steps * batch)
    c = profiler.counters("train_step.")
    exch = model.embedding.exchange_stats(batch * cfg.num_sparse,
                                          shards=shards)

    config_key = (f"{d0.device_kind or d0.platform}/dlrm-v{cfg.total_vocab}"
                  f"d{cfg.embedding_dim}b{batch}x{shards}")
    return {
        "value": round(steps * batch / dt, 1),
        "config": config_key,
        "on_tpu": on_tpu,
        "recsys_examples_per_sec": round(steps * batch / dt, 1),
        "steps_per_sec": round(steps / dt, 2),
        "embedding_a2a_bytes_per_step": exch["bytes_total"],
        "touched_row_fraction": round(float(touched), 5),
        "exchange_capacity": exch["capacity"],
        "shards": shards,
        "batch": batch,
        "total_vocab": cfg.total_vocab,
        "embedding_dim": cfg.embedding_dim,
        "loss_final": round(float(last["loss"].numpy()[-1]), 5),
        "dispatches_per_run_steps": c.get("train_step.dispatches", 0) / rounds,
        "time_to_first_step": round(ttfs, 3),
    }


def main():
    if os.environ.get("BENCH_ONE"):
        print(json.dumps(_measure()))
        return

    # virtual CPU mesh for the sharded exchange; must land before any jax
    # backend init in this process or a child (harmless on real TPUs)
    os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + \
        " --xla_force_host_platform_device_count=4"

    from __graft_entry__ import _probe_default_backend

    budget = float(os.environ.get("BENCH_BUDGET_RECSYS", 300))
    verdict = _probe_default_backend(timeout=75.0)
    extras = None
    error = None
    fallback = None
    if verdict is None:
        try:  # no subprocess machinery: measure in-process (CPU sandboxes)
            extras = _measure()
        except Exception as exc:
            error = f"{type(exc).__name__}: {exc}"
    else:
        import subprocess

        def _child(force_cpu):
            env = dict(os.environ, BENCH_ONE="recsys")
            if force_cpu:
                env["BENCH_FORCE_CPU"] = "1"
                env["JAX_PLATFORMS"] = "cpu"
            r = subprocess.run([sys.executable, os.path.abspath(__file__)],
                               env=env, capture_output=True, text=True,
                               timeout=budget)
            line = [l for l in r.stdout.splitlines() if l.startswith("{")][-1]
            return json.loads(line)

        if verdict is True:
            try:
                extras = _child(force_cpu=False)
            except Exception:
                fallback = "recsys_bench_failed"
        else:
            fallback = "tpu_unreachable"
        if extras is None:
            try:  # graceful CPU fallback: still a real sharded-mesh signal
                extras = _child(force_cpu=True)
            except subprocess.TimeoutExpired:
                error = fallback or "timeout"
            except Exception as exc:
                error = fallback or f"{type(exc).__name__}"

    if extras is None:
        print(json.dumps({"metric": "dlrm_examples_per_sec", "value": None,
                          "unit": "examples/sec", "vs_baseline": None,
                          "recsys_examples_per_sec": None,
                          "embedding_a2a_bytes_per_step": None,
                          "touched_row_fraction": None,
                          "error": error or "bench_error"}))
        return

    base_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "bench_recsys_baseline.json")
    vs = 1.0
    if os.path.exists(base_path):
        try:
            prior = json.load(open(base_path))
            if prior.get("config") == extras.get("config") and prior.get("value"):
                vs = extras["value"] / prior["value"]
        except Exception:
            pass
    else:
        try:
            json.dump({"metric": "dlrm_examples_per_sec",
                       "value": extras["value"], "unit": "examples/sec",
                       "config": extras.get("config")},
                      open(base_path, "w"))
        except OSError:
            pass

    out = {"metric": "dlrm_examples_per_sec", "value": extras["value"],
           "unit": "examples/sec", "vs_baseline": round(vs, 4)}
    out.update({key: v for key, v in extras.items() if key != "value"})
    if fallback:
        out["fallback"] = fallback
    print(json.dumps(out))


if __name__ == "__main__":
    try:
        main()
    except Exception as exc:  # any unplanned failure still emits one line
        print(json.dumps({"metric": "dlrm_examples_per_sec", "value": None,
                          "unit": "examples/sec", "vs_baseline": None,
                          "error": f"{type(exc).__name__}: {exc}"}))
    sys.exit(0)
